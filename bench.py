"""Headline benchmark: flagship GPT-89.6M train-step throughput on real hardware.

Two measured configs:

1. **Reference workload** (batch 8 × seq 512 = 4,096 tokens/step, AdamW,
   dropout 0.1 — BASELINE.md): the apples-to-apples comparison against the
   reference's ~27.9k tokens/s. This is the headline JSON line.
2. **Tuned workload** (batch 32, remat, rbg dropout PRNG): same model and
   optimizer, bigger per-step token count — the per-chip-utilization number
   (a 4,096-token step cannot saturate a v5e; see PERF.md).

Prints ONE JSON line:

    {"metric": "tokens_per_sec", "value": ..., "unit": "tokens/s", "vs_baseline": ...}

vs_baseline is relative to the reference's best strategy throughput,
~27.9k tokens/s for DP/TP on its (unspecified) CUDA-12 GPUs
(`/root/reference/outputs/dp/log.csv`, SURVEY.md §6).
"""

from __future__ import annotations

import json
import time

BASELINE_TOKENS_PER_SEC = 27_900.0  # reference DP/TP, SURVEY.md §6

#: Flagship GPT-89.6M dims shared by every bench config (heads/seq vary
#: per config; these do not — one definition so decode and train rows
#: cannot silently drift onto different models).
FLAGSHIP_DIMS = dict(vocab_size=50258, d_model=512, n_layers=12, d_ff=2048)


def flagship_model_cfg(heads=16, max_seq_len=512, dropout=0.1, remat=True,
                       block_q=512, block_kv=512, block_q_bwd=0,
                       block_kv_bwd=0, moe_experts=0, moe_dispatch="einsum",
                       moe_capacity_factor=1.25):
    """The flagship ModelConfig with the sweepable knobs — ONE definition
    (scripts/bench_common.py re-exports it), so bench rows, the step
    sweeps, and sweeps deriving MFU from a config cannot drift onto
    different models."""
    from dtc_tpu.config.schema import ModelConfig

    return ModelConfig(
        **FLAGSHIP_DIMS, n_heads=heads,
        max_seq_len=max_seq_len, dropout=dropout, param_dtype="float32",
        compute_dtype="bfloat16", attention="auto", remat=remat,
        attention_block_q=block_q, attention_block_kv=block_kv,
        attention_block_q_bwd=block_q_bwd, attention_block_kv_bwd=block_kv_bwd,
        moe_experts=moe_experts, moe_dispatch=moe_dispatch,
        moe_capacity_factor=moe_capacity_factor,
    )


def run_config(
    batch: int,
    remat: bool,
    prng_impl: str,
    bench_steps: int = 30,
    n_heads: int = 16,
    max_seq_len: int = 512,
    moe_experts: int = 0,
    moe_dispatch: str = "einsum",
    attention_block_q: int = 512,
    attention_block_kv: int = 512,
    attention_block_q_bwd: int = 0,
    attention_block_kv_bwd: int = 0,
):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import linen as nn

    from dtc_tpu.config.schema import MeshConfig, OptimConfig, TrainConfig
    from dtc_tpu.data.synthetic import synthetic_batch_iterator
    from dtc_tpu.models.gpt import GPT
    from dtc_tpu.parallel.mesh import mesh_from_config
    from dtc_tpu.parallel.sharding import DEFAULT_RULES
    from dtc_tpu.train.train_step import Batch, create_train_step
    from dtc_tpu.train.trainer import init_state
    from dtc_tpu.utils.metrics import mfu

    model_cfg = flagship_model_cfg(
        heads=n_heads, max_seq_len=max_seq_len, remat=remat,
        moe_experts=moe_experts, moe_dispatch=moe_dispatch,
        block_q=attention_block_q, block_kv=attention_block_kv,
        block_q_bwd=attention_block_q_bwd, block_kv_bwd=attention_block_kv_bwd,
    )
    opt_cfg = OptimConfig(lr=3e-4, weight_decay=0.1, grad_clip=1.0)
    train_cfg = TrainConfig(
        seed=0, parallel="dp", batch=batch, steps=1, log_every=1, output_dir="",
        dataset="synthetic", warmup_steps=0, prefetch=0, mesh=MeshConfig(),
    )
    mesh = mesh_from_config("dp", train_cfg.mesh)
    model = GPT(model_cfg)
    warmup_steps = 8

    with mesh, nn.logical_axis_rules(DEFAULT_RULES):
        state = init_state(model, model_cfg, train_cfg, opt_cfg, mesh, DEFAULT_RULES)
        step_fn = create_train_step(mesh, model=model, state=state)
        # One fixed device-resident batch: the bench measures the train step,
        # not host tokenization (the trainer's prefetch pipeline covers that).
        tok = next(synthetic_batch_iterator(batch, model_cfg.max_seq_len + 1, model_cfg.vocab_size))
        x, y = jnp.asarray(tok[:, :-1]), jnp.asarray(tok[:, 1:])
        key = jax.random.key(0, impl=prng_impl)

        for i in range(warmup_steps):
            state, loss = step_fn(state, Batch(x=x, y=y), jax.random.fold_in(key, i))
        # Sync via value fetch: on some remote-execution platforms
        # block_until_ready returns before device work completes, but a
        # host transfer of the result cannot.
        float(np.asarray(loss))

        # Best-of-3 timed loops: the tunneled chip shows ±10-30% run-to-run
        # latency spikes (observed b8 spread 31-78 ms for the identical
        # program); the minimum of three windows is the sustained-throughput
        # number, the mean of one window is a coin flip. Each window also
        # splits host dispatch from blocked-on-device time (the obs
        # subsystem's step breakdown, at bench granularity): dispatch is
        # the async step_fn calls returning, blocked is the window
        # remainder spent waiting on the final value fetch.
        elapsed = float("inf")
        dispatch = 0.0
        for _ in range(3):
            disp = 0.0
            start = time.perf_counter()
            for i in range(bench_steps):
                t0 = time.perf_counter()
                state, loss = step_fn(
                    state, Batch(x=x, y=y), jax.random.fold_in(key, warmup_steps + i)
                )
                disp += time.perf_counter() - t0
            final_loss = float(np.asarray(loss))
            window = time.perf_counter() - start
            if window < elapsed:
                elapsed, dispatch = window, disp

        # Live working set, sampled while state/batch are still resident.
        # (The allocator's PEAK is process-lifetime-monotone, so a
        # per-config peak would echo whichever earlier config was largest;
        # the single process-wide peak is reported once at bench level.)
        from dtc_tpu.obs.device import max_stat, sample_memory

        in_use = max_stat(sample_memory(), "bytes_in_use")

    step_time = elapsed / bench_steps
    tokens_per_sec = batch * model_cfg.max_seq_len / step_time
    u = mfu(model_cfg, batch, model_cfg.max_seq_len, step_time, jax.device_count())
    res = {
        "step_time_s": round(step_time, 5),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu": round(u, 4) if u is not None else None,
        "final_loss": round(final_loss, 4),
        # Step-time breakdown + device memory (None on backends without
        # PJRT memory accounting).
        "dispatch_s": round(dispatch / bench_steps, 6),
        "blocked_s": round(max(0.0, elapsed - dispatch) / bench_steps, 6),
        "hbm_bytes_in_use": in_use,
    }
    if moe_experts > 0:
        # The dispatch A/B is judged on the useful basis (k·T routed
        # tokens, dispatch uncounted — implementation-independent); the
        # hardware basis above additionally credits the einsum path's
        # structural work. See utils/metrics.py.
        uu = mfu(model_cfg, batch, model_cfg.max_seq_len, step_time,
                 jax.device_count(), moe_basis="useful")
        res["mfu_useful"] = round(uu, 4) if uu is not None else None
        res["moe_dispatch"] = moe_dispatch
    return res


def decode_bench(
    batch: int = 8,
    prompt_len: int = 32,
    new_tokens: int = 128,
    decode_attention: str = "fused",
    kv_cache_dtype: str = "auto",
) -> dict:
    """KV-cache autoregressive decode throughput on the flagship model —
    the serving surface (the reference trains and plots only; SURVEY §1
    lists no sampling path). Random params: decode cost is shape-, not
    value-, dependent.

    ``decode_attention`` selects the attention backend (``fused_layers``
    = the layer-fused megakernel, one Pallas launch per TOKEN —
    ops/decode_fused.py; ``fused`` = the single-launch-per-layer kernel;
    ``xla`` = the oracle) and ``kv_cache_dtype`` the cache storage
    (``int8`` = quantized payload + per-head scales) — the A/Bs that
    isolate launch count and KV bytes from each other. Every row carries
    the memory-bandwidth roofline for its shape
    (utils/metrics.decode_roofline_ms at the run's MEAN cache length,
    DTYPE-CORRECT byte model: the int8 rows are scored against the
    smaller int8 floor, so their pct_of_roofline is not flattered) and
    ``pct_of_roofline`` = floor/measured, so the serving numbers are
    always read against the same floor PERF.md derives.

    ``ms_per_token`` is decode-scan-only (a timed prefill-only leg is
    subtracted, so the prompt-length A/B measures cache-length
    sensitivity, not prefill size); ``wall_s``/``tokens_per_sec`` stay
    end-to-end, the serving-shaped throughput.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtc_tpu.config.schema import ModelConfig
    from dtc_tpu.generate import generate
    from dtc_tpu.models.gpt import GPT
    from dtc_tpu.utils.metrics import decode_roofline_ms

    model_cfg = ModelConfig(
        **FLAGSHIP_DIMS, n_heads=16,
        max_seq_len=512, dropout=0.0, param_dtype="float32",
        compute_dtype="bfloat16", attention="auto",
        decode_attention=decode_attention, kv_cache_dtype=kv_cache_dtype,
    )
    model = GPT(model_cfg)
    x = jnp.ones((batch, 1), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)["params"]
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, model_cfg.vocab_size, jnp.int32
    )
    out = generate(model, params, prompt, new_tokens)  # compile
    np.asarray(out)
    # Prefill-only leg: max_new_tokens=1 returns before the token scan,
    # so best - best_prefill isolates the scan and ms_per_token measures
    # the decode kernel, not prompt processing — otherwise the p256 row's
    # 8x-larger prefill would masquerade as cache-length sensitivity.
    np.asarray(generate(model, params, prompt, 1))  # compile
    best = best_prefill = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = generate(model, params, prompt, new_tokens)
        np.asarray(out)  # sync by value fetch (tunnel-safe)
        best = min(best, time.perf_counter() - t0)
        t0 = time.perf_counter()
        np.asarray(generate(model, params, prompt, 1))
        best_prefill = min(best_prefill, time.perf_counter() - t0)
    decode_s = max(best - best_prefill, 0.0)
    ms_per_token = decode_s / max(new_tokens - 1, 1) * 1e3
    # Roofline at the mean write frontier over the measured run; a decode
    # "token" here is one STEP of the whole batch, matching ms_per_token.
    floor_ms = decode_roofline_ms(
        model_cfg, batch, prompt_len + new_tokens // 2
    )
    return {
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "decode_attention": decode_attention,
        "kv_cache_dtype": kv_cache_dtype,
        "wall_s": round(best, 4),
        "prefill_s": round(best_prefill, 4),
        "tokens_per_sec": round(batch * new_tokens / best, 1),
        "ms_per_token": round(ms_per_token, 3),
        "roofline_ms_per_token": round(floor_ms, 4),
        "pct_of_roofline": round(floor_ms / ms_per_token, 4),
    }


def spec_decode_bench(
    spec_k: int = 2,
    batch: int = 8,
    prompt_len: int = 32,
    new_tokens: int = 128,
    draft_layers: int | None = None,
    model_cfg=None,
    model_label: str = "flagship",
) -> dict:
    """One speculative-decoding row (ISSUE 19): ``spec_generate`` on the
    layer-fused megakernel backend — a resident ``draft_layers``-deep
    rung of the target proposes ``spec_k - 1`` tokens per round, ONE
    k-query verify launch accepts or rolls back. Scored on the
    launch-economy metrics, not raw ms/token:

    - ``ms_per_accepted_token`` — wall ms per EMITTED token (proposals
      never enter the denominator; the A/B partner is a plain
      ``decode_*`` row's ms_per_token at the same batch/backend);
    - ``tokens_accepted_per_launch`` — mean emitted per verify launch,
      in [1, spec_k]; the plain-decode equivalent is 1.0 by definition;
    - ``accept_rate`` — draft proposals the verify kept.

    Greedy acceptance only (the row is exactness-gated: fused_layers on
    BOTH draft and verify — ``check_spec_backend``). ``draft_layers``
    defaults to n_layers // 3 (the shallow-rung operating point).
    Random params: launch economy is shape-dependent; accept_rate on
    random weights is REAL but pessimistic (a trained target's layers
    are more redundant), so the row's accept_rate is a floor, not the
    deployment number."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtc_tpu.config.schema import ModelConfig
    from dtc_tpu.models.gpt import GPT
    from dtc_tpu.spec import extract_draft, spec_generate
    from dtc_tpu.utils.metrics import (
        ms_per_accepted_token, tokens_accepted_per_launch,
    )

    model_cfg = model_cfg or ModelConfig(
        **FLAGSHIP_DIMS, n_heads=16,
        max_seq_len=512, dropout=0.0, param_dtype="float32",
        compute_dtype="bfloat16", attention="auto",
        decode_attention="fused_layers",
    )
    dl = draft_layers or max(1, model_cfg.n_layers // 3)
    model = GPT(model_cfg)
    x = jnp.ones((batch, 1), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)["params"]
    draft_model, draft_params = extract_draft(model, params, dl)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, model_cfg.vocab_size,
        jnp.int32,
    )
    run = lambda: spec_generate(  # noqa: E731
        model, params, draft_model, draft_params, prompt, new_tokens,
        spec_k=spec_k, return_stats=True,
    )
    out, stats = run()  # compile
    np.asarray(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out, stats = run()
        np.asarray(out)  # sync by value fetch (tunnel-safe)
        best = min(best, time.perf_counter() - t0)
    emitted = batch * new_tokens  # every row completes exactly new_tokens
    launches = int(stats["rounds"])
    rate = int(stats["accepted"]) / max(int(stats["proposed"]), 1)
    mspa = ms_per_accepted_token(best, emitted)
    # Per ROW per launch (one launch verifies the whole batch), so the
    # number lands in [1, spec_k] and plain decode's equivalent is 1.0.
    tapl = tokens_accepted_per_launch(emitted, launches * batch)
    return {
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "decode_attention": model_cfg.decode_attention,
        "kv_cache_dtype": model_cfg.kv_cache_dtype,
        "spec_k": spec_k,
        "draft_layers": dl,
        "spec_acceptance": "greedy",
        "spec_model": model_label,
        "platform": jax.devices()[0].platform,
        "wall_s": round(best, 4),
        "verify_launches": launches,
        "accept_rate": round(rate, 4),
        "tokens_accepted_per_launch": (
            None if tapl is None else round(tapl, 3)
        ),
        "ms_per_accepted_token": (
            None if mspa is None else round(mspa, 3)
        ),
        "tokens_per_sec": round(emitted / best, 1),
    }


from dtc_tpu.utils.percentile import nearest_rank as _pct  # noqa: E402
# _pct: shared nearest-rank percentile (ISSUE 7 satellite) — one
# definition for bench, scripts/trace_report.py, and the registry-
# histogram parity tests. Serving-row percentiles below now come from
# the registry's log-bucketed histograms instead of private sample
# lists; _pct remains the exact oracle for small host-side samples
# (trace_overhead_bench).


def trace_overhead_bench(steps: int = 200) -> dict:
    """Measure the tracing substrate's per-step host cost: the full
    telemetry hook cycle (step clock + step event + span synthesis +
    JSONL write) with spans ON vs OFF, p50 over ``steps`` iterations.
    Pure host-side — the span path adds zero device syncs by design, so
    per-step microseconds here over the benched step time IS the
    tracing overhead (PERF.md records the %)."""
    import tempfile
    import time as _t

    from dtc_tpu.config.schema import ObsConfig
    from dtc_tpu.obs import Telemetry

    def loop(trace: bool) -> float:
        times = []
        with tempfile.TemporaryDirectory(prefix="dtc_trace_ovh_") as d:
            tele = Telemetry(
                ObsConfig(trace=trace, memory_sample_every=0), output_dir=d,
            )
            try:
                for s in range(1, steps + 1):
                    t0 = _t.perf_counter()
                    tele.on_step_start(s)
                    with tele.clock.phase("data_wait"):
                        pass
                    with tele.clock.phase("dispatch"):
                        pass
                    tele.on_step_end(s, elapsed_s=0.0, synced=True)
                    times.append(_t.perf_counter() - t0)
            finally:
                tele.close()
        return float(_pct(times, 0.5))

    on, off = loop(True), loop(False)
    return {
        "steps": steps,
        "us_per_step_traced": round(on * 1e6, 2),
        "us_per_step_untraced": round(off * 1e6, 2),
        "span_overhead_us_per_step": round((on - off) * 1e6, 2),
    }


def devprof_bench(capture_steps: int = 3) -> dict:
    """Device-time attribution row for the b8 reference train step
    (ISSUE 8): a programmatic devprof capture around ``capture_steps``
    steps of the SAME flagship b8 workload as ``reference_workload_b8``,
    rolled up to components via the compiled module's op_name metadata.

    Gated STRUCTURALLY, not on raw timings (CPU wall clocks swing ±30%
    on the CI host; op structure does not): every dot/conv-class op must
    attribute to a model component and the unattributed share must stay
    under 10% — plus the warn-band cross-check against the static
    collective census (``comm_bytes_per_step``), the dynamic counterpart
    of the graph auditor's collective rules.
    """
    import jax
    from flax import linen as nn

    from dtc_tpu.obs import devprof
    from dtc_tpu.utils.metrics import (
        comm_bytes_per_step, gpt_step_flops, peak_flops_per_chip,
    )
    from scripts.bench_common import build_step

    step_fn, state, batch, key, (mesh, rules), model_cfg = build_step(
        batch=8, remat=False
    )
    import tempfile

    with tempfile.TemporaryDirectory(prefix="dtc_devprof_bench_") as trace_dir:
        with mesh, nn.logical_axis_rules(rules):
            # AOT lower+compile: the SAME executable runs the capture and
            # yields the optimized-HLO text whose per-instruction op_name
            # metadata recovers scope paths for the trace's bare op names.
            rng = jax.random.fold_in(key, 0)
            compiled = step_fn.lower(state, batch, rng).compile()
            hlo_text = compiled.as_text()
            out = compiled(state, batch, rng)  # warmup (donates state)
            jax.block_until_ready(out[1])
            comm = comm_bytes_per_step(
                model_cfg, 8, model_cfg.max_seq_len,
                {k: int(v) for k, v in mesh.shape.items()}, "dp",
            )
            with devprof.CaptureWindow(
                trace_dir, steps=capture_steps, reason="bench_b8",
                step_flops=gpt_step_flops(model_cfg, 8, model_cfg.max_seq_len),
                peak_flops=peak_flops_per_chip(),
                comm_estimate=comm,
            ) as cap:
                for _ in range(capture_steps):
                    out = compiled(out[0], batch, rng)
                jax.block_until_ready(out[1])
        if not cap.ok:
            return {"error": "profiler capture failed (see warning above)"}
        analysis = devprof.analyze_capture(trace_dir, hlo_text=hlo_text)
        if analysis is None:
            return {"error": "capture produced no trace file"}
    att = analysis["attribution"]
    gates = devprof.structural_gates(att)
    warnings = devprof.census_crosscheck(att, comm)
    for w in warnings:
        print(f"# devprof census warning: {w}")
    meta = analysis["meta"]
    mfu_dev = att.device_mfu(
        meta.get("step_flops"), meta.get("peak_flops"), capture_steps
    )
    return {
        "capture_steps": capture_steps,
        "device_s_per_step": round(att.total_s / capture_steps, 6),
        "device_busy_s_per_step": round(att.busy_s / capture_steps, 6),
        "component_share": {
            r["component"]: r["share"] for r in att.component_table()
        },
        "phase_share": {
            k: round(v / att.total_s, 4) for k, v in sorted(att.phases.items())
        } if att.total_s else {},
        "overlap_ratio": round(att.overlap_ratio, 4),
        "unattributed_share": gates["unattributed_share"],
        "all_dot_fusions_attributed": gates["all_dot_fusions_attributed"],
        "unattributed_share_ok": gates["unattributed_share_ok"],
        "census_warnings": warnings,
        "device_mfu": None if mfu_dev is None else round(mfu_dev, 4),
        "peak_hbm_bytes": meta.get("peak_hbm_bytes"),
    }


def fsdp_overlap_bench(
    collectives: str = "xla", batch: int = 8, bench_steps: int = 20,
    capture_steps: int = 2,
) -> dict:
    """One leg of the ISSUE 12 A/B: the flagship train step under
    ``parallel: fsdp`` over ALL local devices with ``collectives`` set,
    timed (tokens/s) AND devprof-captured for the comm/compute
    ``overlap_ratio`` — the ROADMAP item-2 headline number (xla leg
    measures 0.0 by construction; the overlapped leg's target is ≥0.5).

    Same-config drift rule (the PR 10 pattern): the row carries
    ``collectives``/``platform``/``devices``, and the guard only compares
    rows whose config matches. Requires a real ring: on a single-device
    platform (the tunneled 1-chip TPU, a plain CPU) this raises — the
    row then records the error and stays wired-but-unmeasured, never a
    fake number."""
    import jax
    import numpy as np
    from flax import linen as nn

    from dtc_tpu.obs import devprof
    from dtc_tpu.utils.metrics import (
        comm_bytes_per_step, gpt_step_flops, mfu, peak_flops_per_chip,
    )
    from scripts.bench_common import build_step

    if jax.device_count() < 2:
        raise RuntimeError(
            "fsdp_overlap_ab needs >= 2 devices (an FSDP ring of 1 is "
            "inert); run on a multi-chip slice"
        )
    step_fn, state, batch_obj, key, (mesh, rules), model_cfg = build_step(
        batch=batch, remat=False, parallel="fsdp", collectives=collectives,
    )
    import tempfile

    with tempfile.TemporaryDirectory(prefix="dtc_fsdp_overlap_") as trace_dir:
        with mesh, nn.logical_axis_rules(rules):
            rng = jax.random.fold_in(key, 0)
            compiled = step_fn.lower(state, batch_obj, rng).compile()
            hlo_text = compiled.as_text()
            out = compiled(state, batch_obj, rng)
            jax.block_until_ready(out[1])
            for i in range(4):  # warmup
                out = compiled(out[0], batch_obj, rng)
            float(np.asarray(out[1]))
            start = time.perf_counter()
            for _ in range(bench_steps):
                out = compiled(out[0], batch_obj, rng)
            float(np.asarray(out[1]))
            elapsed = time.perf_counter() - start
            comm = comm_bytes_per_step(
                model_cfg, batch, model_cfg.max_seq_len,
                {k: int(v) for k, v in mesh.shape.items()}, "fsdp",
            )
            with devprof.CaptureWindow(
                trace_dir, steps=capture_steps, reason="fsdp_overlap_ab",
                step_flops=gpt_step_flops(model_cfg, batch, model_cfg.max_seq_len),
                peak_flops=peak_flops_per_chip(),
                comm_estimate=comm,
            ) as cap:
                for _ in range(capture_steps):
                    out = compiled(out[0], batch_obj, rng)
                jax.block_until_ready(out[1])
        analysis = (
            devprof.analyze_capture(trace_dir, hlo_text=hlo_text)
            if cap.ok else None
        )
        att = analysis["attribution"] if analysis else None
    step_time = elapsed / bench_steps
    u = mfu(model_cfg, batch, model_cfg.max_seq_len, step_time,
            jax.device_count())
    res = {
        "collectives": collectives,
        "platform": jax.default_backend(),
        "devices": jax.device_count(),
        "step_time_s": round(step_time, 5),
        "tokens_per_sec": round(batch * model_cfg.max_seq_len / step_time, 1),
        "mfu": round(u, 4) if u is not None else None,
        "final_loss": round(float(np.asarray(out[1])), 4),
        "comm_bytes_per_step": round(comm["total"]),
    }
    if att is not None:
        res.update(
            overlap_ratio=round(att.overlap_ratio, 4),
            collective_ms_per_step=round(
                att.collective_s / capture_steps * 1e3, 4
            ),
            fused_collective_ms_per_step=round(
                att.fused_collective_s / capture_steps * 1e3, 4
            ),
        )
    else:
        res["overlap_ratio"] = None  # capture failed: timing still real
    return res


def precision_ab_bench(
    precision: str = "fp32", batch: int = 8, bench_steps: int = 20,
) -> dict:
    """One leg of the ISSUE 14 mixed-precision A/B: the flagship dp train
    step under ``OptimConfig.precision`` — tokens/s PLUS the analytic
    per-device HBM budget (``utils/metrics.train_memory_bytes``), so the
    row carries both the speed and the byte story the static memory audit
    pins (params halved, +4 B/param fp32 masters, bf16 grads on the
    wire). Same-config drift rule: the row carries precision/platform/
    devices. CPU legs are shape-only (this host EMULATES bf16 — often
    slower than fp32); the TPU A/B is the real number
    (wired-but-unmeasured while the tunnel is down, PERF.md ISSUE-14
    round)."""
    import jax

    from dtc_tpu.config.schema import OptimConfig
    from dtc_tpu.train.train_step import resolve_precision
    from dtc_tpu.utils.metrics import mfu as mfu_fn
    from dtc_tpu.utils.metrics import train_memory_bytes
    from scripts.bench_common import time_step

    ms = time_step(
        steps=bench_steps, warmup=4, batch=batch, parallel="dp",
        precision=precision, remat=False, dropout=0.0,
    )
    model_cfg = resolve_precision(
        OptimConfig(lr=3e-4, weight_decay=0.1, grad_clip=1.0,
                    precision=precision),
        flagship_model_cfg(remat=False, dropout=0.0),
    )
    mesh_shape = {"data": jax.device_count()}
    mem = train_memory_bytes(
        model_cfg, batch, model_cfg.max_seq_len, mesh_shape, "dp",
        precision=precision,
    )
    step_time = ms / 1e3
    u = mfu_fn(model_cfg, batch, model_cfg.max_seq_len, step_time,
               jax.device_count())
    return {
        "precision": precision,
        "platform": jax.default_backend(),
        "devices": jax.device_count(),
        "step_time_s": round(step_time, 5),
        "tokens_per_sec": round(batch * model_cfg.max_seq_len / step_time, 1),
        "mfu": round(u, 4) if u is not None else None,
        "hbm_params_bytes": round(mem["params"]),
        "hbm_master_bytes": round(mem["master"]),
        "hbm_moments_bytes": round(mem["moments"]),
        "hbm_grads_bytes": round(mem["grads"]),
        "hbm_total_bytes": round(mem["total"]),
    }


def serve_bench(
    rps: float | None,
    *,
    model_cfg=None,
    model_label: str = "flagship",
    n_requests: int = 32,
    slots: int = 4,
    prompt_len: int = 32,
    max_new_tokens: int = 32,
    seed: int = 0,
    queue_depth: int | None = None,
    shed_watermark: float = 0.75,
    deadline_s: float = 0.0,
    max_wall_s: float = 600.0,
    n_tenants: int = 0,
    adapter_rank: int = 8,
    spec_k: int = 0,
    draft_layers: int = 0,
) -> dict:
    """One serving-scheduler row: Poisson arrivals at ``rps`` offered
    requests/s through the continuous-batching engine (dtc_tpu/serve/),
    measuring the SLO surface — sustained tokens/s, p50/p99 TTFT and
    ms/token, queue wait, and the shed/expired/rejected counts that keep
    the tail bounded past saturation.

    ``n_tenants > 0`` is the multi-tenant LoRA leg (ISSUE 10): the model
    gains a rank-``adapter_rank`` adapter config, N tenants' factors are
    loaded into the engine's resident stack, and requests round-robin
    across the tenants plus the un-adapted base — all co-scheduled in the
    same in-flight batch over ONE set of base weights. Everything else
    (arrival process, prompts, SLO accounting) is identical to the
    adapter-free rows, so the serve_lora vs serve row delta IS the
    multi-tenant overhead (the per-row factor gather + low-rank matmuls).

    Arrivals are DETERMINISTIC per ``seed`` (one seeded exponential
    inter-arrival sequence + fixed per-index prompts), so a row reproduces
    on the same machine run-to-run. ``rps=None`` is the closed-loop
    calibration row: every request submitted at t=0, which saturates the
    slots and measures the engine's token capacity — the offered loads
    for the open-loop rows are set relative to it. The past-saturation
    row exists to show overload POLICY, not throughput: bounded queue
    wait and non-exploding p99 ms/token via shedding, never silent drops.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtc_tpu.config.schema import ServeConfig
    from dtc_tpu.models.gpt import GPT
    from dtc_tpu.serve import QueueFullError, Request, RequestState, ServingEngine
    from dtc_tpu.utils.arrivals import arrival_schedule

    model_cfg = model_cfg or flagship_model_cfg(dropout=0.0)
    if n_tenants > 0:
        import dataclasses

        from dtc_tpu.config.schema import AdapterConfig

        model_cfg = dataclasses.replace(
            model_cfg, adapter=AdapterConfig(rank=adapter_rank)
        )
    model = GPT(model_cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.ones((1, 1), jnp.int32),
        train=False,
    )["params"]
    # Speculative serving leg (ISSUE 19): spec_k > 0 turns serve.spec on
    # — the engine extracts the resident draft rung at construction and
    # every decode iteration becomes one draft-propose + k-verify round.
    # Exactness-gated by the engine itself (fused_layers backend, no
    # adapters), so a misconfigured row errors instead of measuring a
    # token-forking scheduler.
    spec_kw = {}
    if spec_k > 0:
        from dtc_tpu.config.schema import SpecConfig

        spec_kw["spec"] = SpecConfig(spec_k=spec_k, draft_layers=draft_layers)
    scfg = ServeConfig(
        slots=slots,
        page_size=16,
        queue_depth=queue_depth or 4 * slots,
        max_new_tokens=max_new_tokens,
        prefill_bucket=prompt_len,
        shed_watermark=shed_watermark,
        deadline_s=deadline_s,
        max_adapters=max(n_tenants + 1, 2),
        **spec_kw,
    )
    eng = ServingEngine(model, params, scfg)
    tenant_names: list = [None]
    if n_tenants > 0:
        from dtc_tpu.adapters import init_lora

        # Real (A random / B zero) factor trees: values don't change the
        # schedule, shapes and the per-row gather are what's measured.
        factors = init_lora(model, seed=1)
        for t in range(n_tenants):
            eng.load_adapter(f"tenant{t}", factors)
            tenant_names.append(f"tenant{t}")

    arrivals, prompts = arrival_schedule(
        seed, n_requests, prompt_len, model_cfg.vocab_size, rps,
    )
    # Warm the compiled surfaces outside the measured window (one
    # admission + one decode step), so row 1 doesn't pay the jit tax —
    # then drop the warm request's samples from the SLO histograms so
    # the measured percentiles cover only the row's own requests.
    eng.submit(Request(
        rid="warm", prompt=prompts[0], max_new_tokens=2,
        adapter=tenant_names[-1],
    ))
    eng.run(max_steps=16)
    for name in ("serve_ttft_s", "serve_ms_per_token", "serve_queue_wait_s"):
        eng.reg.histogram(name).reset()

    rejected = 0
    i = 0
    t0 = time.perf_counter()
    while True:
        now = time.perf_counter() - t0
        while i < n_requests and arrivals[i] <= now:
            try:
                eng.submit(Request(
                    rid=f"q{i}", prompt=prompts[i],
                    max_new_tokens=max_new_tokens,
                    adapter=tenant_names[i % len(tenant_names)],
                ))
            except QueueFullError:
                rejected += 1  # typed backpressure — counted, not dropped
            i += 1
        busy = eng.step()
        if now > max_wall_s:
            break
        if not busy:
            if i >= n_requests:
                break
            time.sleep(max(0.0, min(arrivals[i] - (time.perf_counter() - t0), 0.01)))
    wall = time.perf_counter() - t0

    res = [r for rid, r in eng.results.items() if rid != "warm"]
    done = [r for r in res if r.state is RequestState.DONE]
    by_state = lambda s: sum(1 for r in res if r.state.value == s)  # noqa: E731
    tokens_out = sum(len(r.tokens) for r in done)
    # Percentiles from the REGISTRY histograms — the same log-bucketed
    # instruments serve/telemetry reports live — not private sample
    # lists (ISSUE 7). ttft/queue-wait cover every request that reached
    # a first token (the SLO population); ms/token covers completed
    # requests (matching the old done-only list). Values are within one
    # ~10% bucket of exact nearest-rank (parity-tested in test_trace).
    q = lambda name, p: eng.reg.histogram(name).percentile(p)  # noqa: E731
    r4 = lambda v: None if v is None else round(v, 4)  # noqa: E731
    # Speculative acceptance aggregates (spec rows only): accepted-token
    # throughput IS sustained_tokens_per_sec (every delivered token was
    # accepted — the exactness gate), so the extra numbers are the
    # acceptance economics behind it.
    spec_fields: dict = {
        "spec_k": spec_k,
        "draft_layers": draft_layers if spec_k > 0 else 0,
        "spec_acceptance": "greedy" if spec_k > 0 else "off",
    }
    if spec_k > 0:
        prop = sum(r.n_spec_proposed for r in res)
        acc = sum(r.n_spec_accepted for r in res)
        spec_fields["spec_accept_rate"] = (
            round(acc / prop, 4) if prop else None
        )
    return {
        **spec_fields,
        "rps": None if rps is None else round(rps, 3),
        "offered_tokens_per_sec": (
            None if rps is None else round(rps * max_new_tokens, 1)
        ),
        "n_requests": n_requests,
        "slots": slots,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new_tokens,
        "seed": seed,
        "completed": len(done),
        "shed": by_state("shed"),
        "expired": by_state("expired"),
        "rejected": rejected,
        "evictions": sum(r.n_evictions for r in res),
        "wall_s": round(wall, 3),
        "sustained_tokens_per_sec": round(tokens_out / wall, 1) if wall else None,
        "ttft_p50_s": r4(q("serve_ttft_s", 0.50)),
        "ttft_p99_s": r4(q("serve_ttft_s", 0.99)),
        "ms_per_token": r4(q("serve_ms_per_token", 0.50)),
        "ms_per_token_p99": r4(q("serve_ms_per_token", 0.99)),
        "queue_wait_p50_s": r4(q("serve_queue_wait_s", 0.50)),
        "queue_wait_p99_s": r4(q("serve_queue_wait_s", 0.99)),
        "platform": jax.devices()[0].platform,
        "serve_model": model_label,
        "decode_attention": model_cfg.decode_attention,
        "kv_cache_dtype": model_cfg.kv_cache_dtype,
        "n_tenants": n_tenants,
        "adapter_rank": adapter_rank if n_tenants > 0 else 0,
    }


def _calibrated_serve_rows(
    emit, model_cfg, seed: int, prefix: str,
    load_fracs: tuple[tuple[str, float], ...], **kw
) -> None:
    """Shared calibrate-then-load skeleton for every serving row family:
    one closed-loop calibration row (queue deep enough for the whole
    burst, shedding OFF — capacity must be measured with nothing
    dropped), then open-loop Poisson rows at the given fractions of the
    calibrated request capacity. ONE definition so a calibration fix
    applies to the adapter-free and lora families alike."""
    n_req = kw.get("n_requests", 32)
    cal_label = f"{prefix}_cal_closed_loop"
    cal = emit(cal_label, _safe(cal_label, lambda: serve_bench(
        None, model_cfg=model_cfg, seed=seed, queue_depth=n_req,
        shed_watermark=0.0, **kw)))
    cap_tps = cal.get("sustained_tokens_per_sec")
    if not cap_tps:
        print(f"# {prefix} bench: calibration failed; skipping load rows")
        return
    cap_rps = cap_tps / cal["max_new_tokens"]
    for suffix, frac in load_fracs:
        label = f"{prefix}_{suffix}"
        emit(label, _safe(label, lambda f=frac: serve_bench(
            cap_rps * f, model_cfg=model_cfg, seed=seed, **kw)))


def serve_bench_rows(emit, model_cfg=None, *, seed: int = 0, **kw) -> None:
    """The serving row set: closed-loop calibration, then open-loop
    Poisson rows at 0.5x / 0.9x / 3x the calibrated request capacity —
    the 3x row is deliberately past saturation so the recorded
    shed/expired counts and bounded p99 demonstrate the overload policy
    holding (the acceptance criterion), not raw throughput. (3x, not
    1.2x: the closed-loop calibration UNDERestimates steady-state
    capacity — its wall clock includes the serialized prefill ramp — so
    a mild multiplier can land under true saturation and show nothing;
    3x is decisively past it on every platform measured.)"""
    _calibrated_serve_rows(
        emit, model_cfg, seed, "serve",
        (("load50", 0.5), ("load90", 0.9), ("sat300", 3.0)), **kw,
    )


def serve_int8_row(emit, serve_cfg_kw: dict, *, seed: int = 0) -> None:
    """The ISSUE 11 serving row: one closed-loop capacity measurement on
    the layer-fused megakernel + int8 KV cache. A/B against
    ``serve_cal_closed_loop`` (same arrival shape, fp-cache model) reads
    the quantized cache's scheduler-level price; the ``*_int8``
    serve_model label + config fields keep the drift guard comparing
    like to like."""
    import dataclasses

    kw = dict(serve_cfg_kw)
    kw["model_cfg"] = dataclasses.replace(
        kw.pop("model_cfg", None) or flagship_model_cfg(dropout=0.0),
        kv_cache_dtype="int8", decode_attention="fused_layers",
    )
    kw["model_label"] = kw.get("model_label", "flagship") + "_int8"
    emit("serve_int8_closed_loop", _safe("serve_int8_closed_loop",
         lambda: serve_bench(
             None, seed=seed, queue_depth=kw.get("n_requests", 32),
             shed_watermark=0.0, **kw)))


def serve_spec_row(
    emit, serve_cfg_kw: dict, *, seed: int = 0, spec_k: int = 4,
    draft_layers: int | None = None,
) -> None:
    """The ISSUE 19 serving row: one closed-loop capacity measurement
    with ``serve.spec`` ON (layer-fused backend — the exactness gate's
    requirement — and the draft rung resident). A/B against
    ``serve_cal_closed_loop`` (same arrival shape, spec off) reads
    speculation's scheduler-level value: the delta in sustained
    tokens/s is pure launch economy, because the emitted tokens are
    token-identical by construction. The ``*_spec`` serve_model label +
    the spec config fields keep the drift guard comparing like to
    like."""
    import dataclasses

    kw = dict(serve_cfg_kw)
    base_cfg = kw.pop("model_cfg", None) or flagship_model_cfg(dropout=0.0)
    kw["model_cfg"] = dataclasses.replace(
        base_cfg, decode_attention="fused_layers", dropout=0.0
    )
    dl = draft_layers or max(1, base_cfg.n_layers // 3)
    kw["model_label"] = kw.get("model_label", "flagship") + "_spec"
    emit("serve_spec_closed_loop", _safe("serve_spec_closed_loop",
         lambda: serve_bench(
             None, seed=seed, queue_depth=kw.get("n_requests", 32),
             shed_watermark=0.0, spec_k=spec_k, draft_layers=dl, **kw)))


def serve_lora_rows(
    emit, model_cfg=None, *, seed: int = 0, n_tenants: int = 4, **kw
) -> None:
    """The multi-tenant LoRA row set (ISSUE 10): ``n_tenants`` adapters
    sharing ONE resident base model, requests round-robining tenants +
    base under Poisson arrivals — tokens/s and p99 ms/token land next to
    the adapter-free ``serve_*`` rows so the per-token multi-tenant
    overhead is one table read. Distinct ``serve_lora_*`` labels keep the
    decode drift guard's same-model comparison rule working: lora rows
    only ever compare against committed lora rows."""
    _calibrated_serve_rows(
        emit, model_cfg, seed, "serve_lora",
        (("load50", 0.5), ("load90", 0.9)), n_tenants=n_tenants, **kw,
    )


def fleet_bench(
    rps: float | None,
    *,
    model_cfg=None,
    model_label: str = "flagship",
    n_replicas: int = 3,
    n_requests: int = 48,
    slots: int = 2,
    prompt_len: int = 16,
    max_new_tokens: int = 16,
    seed: int = 0,
    queue_depth: int | None = None,
    shed_watermark: float = 0.75,
    kill_replica_at: int = 0,
    max_wall_s: float = 600.0,
    obs_dir: str | None = None,
) -> dict:
    """One serving-FLEET row (ISSUE 13): Poisson arrivals at ``rps``
    offered requests/s through the tenant-aware router over
    ``n_replicas`` in-process engine replicas, measuring the fleet SLO
    surface — sustained tokens/s, fleet-level p50/p99 TTFT + ms/token
    (the router's pooled histograms), AND the per-replica percentile
    rows (each replica's own registry) the fleet view is reduced from.

    ``kill_replica_at > 0`` is the chaos leg: replica 0 is declared dead
    at that router iteration mid-traffic, its queued + in-flight
    requests fail over to survivors (prompt+generated re-prefill), and
    the row records failovers/replica_deaths plus ``zero_silent_drops``
    — accepted submits reconciled against terminal results, the fleet
    acceptance criterion.

    Honesty: in-process replicas time-slice ONE host's compute, so CPU
    fleet wall-clocks are SHAPE-only (scheduling/failover/accounting are
    real; absolute throughput is not — compare fleet rows only against
    fleet rows with the same replica count, which the drift guard
    enforces)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtc_tpu.config.schema import ChaosConfig, RouterConfig, ServeConfig
    from dtc_tpu.models.gpt import GPT
    from dtc_tpu.serve import FleetRouter, QueueFullError, Request, RequestState
    from dtc_tpu.utils.arrivals import arrival_schedule

    model_cfg = model_cfg or flagship_model_cfg(dropout=0.0)
    model = GPT(model_cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.ones((1, 1), jnp.int32),
        train=False,
    )["params"]
    rcfg = RouterConfig(
        n_replicas=n_replicas,
        serve=ServeConfig(
            slots=slots,
            page_size=16,
            queue_depth=queue_depth or 4 * slots,
            max_new_tokens=max_new_tokens,
            prefill_bucket=prompt_len,
            shed_watermark=shed_watermark,
        ),
        chaos=ChaosConfig(
            enabled=kill_replica_at > 0,
            fleet_kill_replica_at_step=kill_replica_at,
            fleet_target_replica=0,
        ),
    )
    router = FleetRouter(model, params, rcfg, obs_dir=obs_dir or "")
    arrivals, prompts = arrival_schedule(
        seed, n_requests, prompt_len, model_cfg.vocab_size, rps,
    )
    router.warmup(prompts[0])

    rejected = 0
    accepted = 0
    i = 0
    t0 = time.perf_counter()
    while True:
        now = time.perf_counter() - t0
        while i < n_requests and arrivals[i] <= now:
            try:
                router.submit(Request(
                    rid=f"q{i}", prompt=prompts[i],
                    max_new_tokens=max_new_tokens,
                ))
                accepted += 1
            except QueueFullError:
                rejected += 1  # typed fleet backpressure — counted
            i += 1
        busy = router.step()
        if now > max_wall_s:
            break
        if not busy:
            if i >= n_requests:
                break
            time.sleep(max(0.0, min(
                arrivals[i] - (time.perf_counter() - t0), 0.01)))
    wall = time.perf_counter() - t0

    res = list(router.results.values())
    done = [r for r in res if r.state is RequestState.DONE]
    by_state = lambda s: sum(1 for r in res if r.state.value == s)  # noqa: E731
    summ = router.fleet_summary()
    row = {
        "rps": None if rps is None else round(rps, 3),
        "n_requests": n_requests,
        "n_replicas": n_replicas,
        "slots": slots,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new_tokens,
        "seed": seed,
        "kill_replica_at": kill_replica_at,
        "completed": len(done),
        "shed": by_state("shed"),
        "expired": by_state("expired"),
        "failed": by_state("failed"),
        "rejected": rejected,
        "failovers": summ["failovers"],
        "replica_deaths": summ["replica_deaths"],
        # Zero-silent-drops reconciliation: every ACCEPTED submit must
        # reach a terminal fleet result (the acceptance criterion — a
        # False here is a bug, not a bench observation).
        "zero_silent_drops": accepted == len(res),
        "wall_s": round(wall, 3),
        "sustained_tokens_per_sec": (
            round(sum(len(r.tokens) for r in done) / wall, 1) if wall else None
        ),
        "ttft_p50_s": summ["ttft_p50_s"],
        "ttft_p99_s": summ["ttft_p99_s"],
        "ms_per_token": summ["ms_per_token_p50"],
        "ms_per_token_p99": summ["ms_per_token_p99"],
        "per_replica": {
            k: {kk: v[kk] for kk in (
                "state", "done", "ttft_p99_s", "ms_per_token_p99")}
            for k, v in summ["replicas"].items()
        },
        "platform": jax.devices()[0].platform,
        "serve_model": model_label,
        "decode_attention": model_cfg.decode_attention,
        "kv_cache_dtype": model_cfg.kv_cache_dtype,
    }
    router.close()
    return row


def serve_fleet_rows(
    emit, model_cfg=None, *, seed: int = 0, n_replicas: int = 3, **kw
) -> None:
    """The fleet row set (ISSUE 13): closed-loop calibration over
    ``n_replicas`` replicas, open-loop Poisson at 0.9x and 3x the
    calibrated fleet request capacity (same rationale as
    serve_bench_rows: 3x is decisively past saturation — the row that
    shows FLEET backpressure holding typed), and the replica-kill chaos
    leg at 0.9x — failover mid-traffic with zero silent drops, per-
    replica AND fleet percentiles recorded."""
    import tempfile

    n_req = kw.get("n_requests", 48)
    cal = emit("serve_fleet_cal_closed_loop", _safe(
        "serve_fleet_cal_closed_loop",
        lambda: fleet_bench(
            None, model_cfg=model_cfg, seed=seed, n_replicas=n_replicas,
            queue_depth=n_req, shed_watermark=0.0, **kw)))
    cap_tps = cal.get("sustained_tokens_per_sec")
    if not cap_tps:
        print("# fleet bench: calibration failed; skipping load rows")
        return
    cap_rps = cap_tps / cal["max_new_tokens"]
    for suffix, frac, kill in (
        ("load90", 0.9, 0), ("sat300", 3.0, 0), ("kill", 0.9, 8),
    ):
        label = f"serve_fleet_{suffix}"
        obs_dir = tempfile.mkdtemp(prefix=f"dtc_bench_{suffix}_")
        row = emit(label, _safe(label, lambda f=frac, k=kill, d=obs_dir:
                                fleet_bench(
            cap_rps * f, model_cfg=model_cfg, seed=seed,
            n_replicas=n_replicas, kill_replica_at=k, obs_dir=d, **kw)))
        # Goodput companion rows (ISSUE 16): the load and chaos legs
        # report effective-tokens/s (tokens delivered in COMPLETED
        # requests over the ledger extent) next to the raw tokens/s,
        # plus the fleet goodput % and incident count — so a recovery
        # path that burns wall-clock shows up as a bench number, not
        # just a log line.
        if suffix in ("load90", "kill") and "error" not in row:
            glabel = f"goodput_fleet_{suffix}"
            emit(glabel, _safe(glabel, lambda r=row, d=obs_dir:
                               goodput_row_from_obs(d, r)))


def goodput_row_from_obs(obs_dir: str, base_row: dict) -> dict:
    """One ``goodput_*`` row from a leg's event shards: the ledger's
    fleet goodput %, effective-tokens/s next to the leg's raw tokens/s,
    the badput split, and the incident bill count. Carries the SAME
    config fields as its base leg (platform/model/replicas/chaos) so the
    drift guard's same-config rule can pair rows across rounds."""
    from dtc_tpu.obs.goodput import GoodputLedger

    s = GoodputLedger.from_dir(obs_dir).summary()
    if s is None:
        return {"error": "no classifiable events in obs shards"}
    tokens = s["tokens"]
    eff = tokens.get("effective_serve_tokens_per_sec")
    if eff is None:
        eff = tokens.get("effective_train_tokens_per_sec")
    sec = s["fleet"]["seconds"]
    badput = {
        k: v for k, v in sorted(sec.items(), key=lambda kv: -kv[1])
        if k not in ("productive_train", "productive_decode", "prefill")
    }
    return {
        "goodput_pct": s["fleet"]["goodput_pct"],
        "effective_tokens_per_sec": eff,
        "raw_tokens_per_sec": base_row.get("sustained_tokens_per_sec"),
        "effective_serve_tokens": tokens.get("effective_serve_tokens"),
        "badput_serve_tokens": tokens.get("badput_serve_tokens"),
        "badput_s": {k: round(v, 4) for k, v in badput.items()},
        "incidents": len(s["incidents"]),
        # Same-config drift fields, copied from the measured leg.
        **{k: base_row.get(k) for k in (
            "platform", "serve_model", "n_replicas", "kill_replica_at",
            "slots", "max_new_tokens", "decode_attention",
            "kv_cache_dtype",
        )},
    }


def pool_bench(chaos: bool = True) -> dict:
    """Resource-pool row (ISSUE 17): one scripts/pool_smoke.py leg in a
    subprocess — the pool needs the 8-virtual-device mesh, which the
    bench process (single device) cannot host. The smoke's own gates
    (typed transitions, zero silent drops, loss parity, exactly one
    recompile per mesh change, goodput billing) all hold or the row is
    an error; the row itself is the machine-readable '# pool-smoke:'
    summary (train tokens/s under arbitration, fleet completions,
    transition/resize/recompile counts, goodput %)."""
    import os
    import re
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=8 "
        "--xla_cpu_use_thunk_runtime=false"
    )
    cmd = [sys.executable, "scripts/pool_smoke.py", "--json"]
    if chaos:
        cmd.append("--chaos")
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode != 0:
        return {
            "error": f"pool_smoke rc={proc.returncode}",
            "tail": (proc.stdout + proc.stderr)[-400:],
        }
    m = re.search(r"# pool-smoke: (\{.*\})", proc.stdout)
    if not m:
        return {"error": "pool_smoke printed no '# pool-smoke:' row"}
    return json.loads(m.group(1))


def pool_diurnal_rows(emit) -> None:
    """The pool row family: the clean diurnal leg and the combined-chaos
    leg (spike-mid-grow abort + kill-mid-shrink) side by side — the
    delta in train tokens/s is the measured price of surviving chaos
    under arbitration."""
    emit("pool_diurnal", _safe(
        "pool_diurnal", lambda: pool_bench(chaos=False)))
    emit("pool_diurnal_chaos", _safe(
        "pool_diurnal_chaos", lambda: pool_bench(chaos=True)))


def _bench_detail(path: str) -> dict:
    """Parsed ``# bench-detail:`` dict of one committed BENCH file, or {}.

    Tolerates any malformed/foreign file shape — the guard is advisory
    and must never be the reason a bench run dies."""
    import re

    try:
        with open(path) as f:
            prev_raw = json.load(f)
        # The committed files wrap the run: the detail dict lives on the
        # "# bench-detail:" line inside "tail".
        m = re.search(r"# bench-detail: (\{.*\})", prev_raw.get("tail", ""))
        return json.loads(m.group(1)) if m else {}
    except (OSError, ValueError, AttributeError, TypeError):
        return {}


def decode_drift_guard(extra: dict, repo_dir: str | None = None) -> list[str]:
    """Compare this run's decode rows against the newest committed
    ``BENCH_r*.json`` that HAS decode rows and flag any ms/token
    regression > 20% — the same drift discipline the training rows get
    from round-over-round BENCH comparison, applied automatically so a
    serving regression cannot ship silently inside an otherwise-green
    bench. Returns human-readable flag strings (also stored under
    ``extra["decode_regressions"]``).

    Serving rows (labels ``serve_*``, ISSUE 6) ride the same guard with
    their own newest-file-with-serve-rows fallback; a serve comparison is
    additionally skipped when the committed row was measured on a
    different platform (the committed scheduler rows are CPU-measured
    under the TPU-tunnel outage — comparing TPU ms/token against them
    would be noise, not drift).

    Same-CONFIG comparisons only (ISSUE 11, the same rule as the PR 6
    same-platform rule): rows are compared only when their
    ``decode_attention`` and ``kv_cache_dtype`` labels match — a label
    whose config changed meaning across rounds (e.g. decode_b8 re-pointed
    at a different backend) must not be judged against its old self.
    Rows committed before these fields existed default to the config
    every pre-ISSUE-11 row actually ran ("fused"/"auto").

    Degrades gracefully: a newest file without decode rows (e.g. a round
    whose decode configs all ``_safe``-errored) falls back to older
    files, and when NO committed file carries a decode ms/token the guard
    prints a warning and compares nothing — it never raises."""
    import glob
    import os

    repo_dir = repo_dir or os.path.dirname(os.path.abspath(__file__))
    paths = sorted(glob.glob(os.path.join(repo_dir, "BENCH_r*.json")))
    flags: list[str] = []
    if not paths:
        return flags

    def compare(prefix: str, metric: str, comparable,
                higher_is_better: bool = False) -> None:
        """One guarded row family: walk committed files newest-first,
        stop at the first file holding at least one COMPARABLE row —
        a newest file whose rows are all incomparable (different
        platform/model/config, e.g. TPU rows committed during a CPU
        round) must not deactivate the guard while an older comparable
        file exists — and flag metric regressions > 20%.
        ``comparable(old, row)`` is the family's same-config rule.
        ``higher_is_better`` flips the regression direction (the goodput
        family: a DROP in effective-tokens/s is the regression)."""

        def has_rows(detail: dict) -> bool:
            return any(
                label.startswith(prefix) and isinstance(row, dict)
                and metric in row
                for label, row in detail.items()
            )

        if not has_rows(extra):
            return  # this run measured no such rows: nothing to guard
        for path in reversed(paths):
            prev = _bench_detail(path)
            if not has_rows(prev):
                continue
            compared = False
            for label, row in extra.items():
                if not (isinstance(row, dict) and label.startswith(prefix)):
                    continue
                old = prev.get(label)
                if not (isinstance(old, dict) and metric in old):
                    continue
                if not comparable(old, row):
                    continue
                compared = True
                new_v, old_v = row.get(metric), old[metric]
                if not (
                    isinstance(new_v, (int, float)) and isinstance(old_v, (int, float))
                    and new_v and old_v
                ):
                    continue
                worse = (new_v < old_v / 1.2 if higher_is_better
                         else new_v > 1.2 * old_v)
                if worse:
                    flags.append(
                        f"{label}: {new_v} {metric} vs {old_v} in "
                        f"{os.path.basename(path)} ({(new_v / old_v - 1) * 100:+.0f}%)"
                    )
            if compared:
                return
        if prefix == "decode":
            print(
                "# decode drift guard: no committed BENCH_r*.json carries "
                "decode rows — nothing to compare against (guard inactive "
                "this run)"
            )

    # Same-config rule per family. Decode: decode_attention/kv_cache_dtype
    # must match (pre-ISSUE-11 rows lack the fields and ran the then-only
    # config — normalize so history stays guarded). Serve: additionally
    # same platform AND serve model (tiny vs flagship rows share labels;
    # the committed scheduler rows are CPU-measured under the TPU-tunnel
    # outage). fsdp_overlap (ISSUE 12): collectives/platform/devices must
    # all match — an overlapped row must never be judged against an xla
    # row, nor a multi-chip row against a 1-chip one.
    def decode_cfg(r):
        # Spec keys (ISSUE 19) ride the same rule: a speculative row must
        # never be judged against a plain one (their ms/token means
        # different things — accepted vs sequential tokens). Pre-ISSUE-19
        # rows lack the fields and were all spec-off — normalize, same
        # pattern as the ISSUE-11 kv_cache_dtype default above.
        return (
            r.get("decode_attention", "fused"),
            r.get("kv_cache_dtype", "auto"),
            r.get("spec_k", 0),
            r.get("draft_layers", 0),
            r.get("spec_acceptance", "off"),
        )

    compare("decode", "ms_per_token", lambda o, r: decode_cfg(o) == decode_cfg(r))
    # Speculative rows (ISSUE 19, labels spec_*): guarded on
    # ms-per-ACCEPTED-token — the launch-economy metric a spec row is
    # scored by (raw ms/token would reward rejected work) — under the
    # decode rule, whose spec keys keep k2 vs k4 vs plain apart.
    compare("spec", "ms_per_accepted_token", lambda o, r: (
        decode_cfg(o) == decode_cfg(r)
        # A CPU-measured spec row (tiny model, tunnel-outage artifact)
        # must never be judged against a TPU flagship one — the same
        # platform/model rule the serve family carries.
        and o.get("platform") == r.get("platform")
        and o.get("spec_model") == r.get("spec_model")
    ))
    # Fleet rows (serve_fleet_*, ISSUE 13) ride the serve family via the
    # shared "serve" prefix; their extra same-config requirement is the
    # replica count (absent on both sides for single-engine rows) — a
    # 3-replica row must never be judged against a 1-replica one, and
    # the chaos kill leg only against kill legs (kill_replica_at match).
    compare("serve", "ms_per_token", lambda o, r: (
        decode_cfg(o) == decode_cfg(r)
        and o.get("platform") == r.get("platform")
        and o.get("serve_model") == r.get("serve_model")
        and o.get("n_replicas") == r.get("n_replicas")
        and o.get("kill_replica_at") == r.get("kill_replica_at")
    ))
    compare("fsdp_overlap", "step_time_s", lambda o, r: all(
        o.get(k) == r.get(k) for k in ("collectives", "platform", "devices")
    ))
    # Goodput rows (ISSUE 16): effective-tokens/s is higher-is-better —
    # a >20% DROP is the regression. Same-config rule: platform + model
    # + replica count + the chaos config (kill_replica_at) must all
    # match, so a clean leg is never judged against a kill leg.
    compare("goodput", "effective_tokens_per_sec", lambda o, r: all(
        o.get(k) == r.get(k) for k in (
            "platform", "serve_model", "n_replicas", "kill_replica_at")
    ), higher_is_better=True)
    # Pool rows (ISSUE 17): train tokens/s under arbitration is
    # higher-is-better. Same-config rule: platform + model + chaos leg
    # must match — the clean diurnal leg is never judged against the
    # combined-chaos one.
    compare("pool", "train_tokens_per_sec", lambda o, r: all(
        o.get(k) == r.get(k) for k in ("platform", "serve_model", "chaos")
    ), higher_is_better=True)

    if flags:
        extra["decode_regressions"] = flags
    return flags


def ring_block_smoke() -> dict:
    """Execute the zigzag-ring Pallas BLOCK kernels on the real chip.

    The ring itself needs >= 2 devices (the whole-ring VJP short-circuits
    to dense on this 1-chip box, and CPU tests run the kernels in
    interpret mode), but the four per-device kernel flavors the ring is
    built from — fwd/bwd x causal/cross-chunk — are ordinary single-chip
    pallas_calls. Compiling and running them here pins the Mosaic path
    every round (round-4 VERDICT weak #5): parity vs an fp32 jnp oracle,
    on-device.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtc_tpu.ops import flash_attention as fa

    b, tc, h, d = 2, 512, 16, 32
    g = fa._packed_group(d, h)
    scale = float(d**-0.5)
    kq, kk, kv, kd = jax.random.split(jax.random.PRNGKey(7), 4)
    q = jax.random.normal(kq, (b, tc, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, tc, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, tc, h, d), jnp.float32)
    do = jax.random.normal(kd, (b, tc, h, d), jnp.float32)
    pk = lambda x: x.reshape(b, tc, h * d)

    def oracle(q, k, v, causal):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if causal:
            mask = jnp.tril(jnp.ones((tc, tc), bool))
            s = jnp.where(mask, s, -1e9)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    res = {}
    for causal in (True, False):
        tag = "causal" if causal else "cross"
        fwd = jax.jit(lambda q, k, v, c=causal: fa._block_call(
            pk(q), pk(k), pk(v), scale, c, g, d))
        out, lse = fwd(q, k, v)
        ref = oracle(q, k, v, causal)
        res[f"fwd_{tag}_err"] = float(
            jnp.max(jnp.abs(out.reshape(b, tc, h, d) - ref))
        )
        bwd = jax.jit(lambda q, k, v, do, o, lse, c=causal: fa._block_call(
            pk(q), pk(k), pk(v), scale, c, g, d, do=pk(do), o=o, lse=lse))
        dq, dk, dv = bwd(q, k, v, do, out, lse)
        g_ref = jax.jit(jax.grad(
            lambda q, k, v, c=causal: jnp.sum(oracle(q, k, v, c) * do),
            argnums=(0, 1, 2),
        ))(q, k, v)
        for name, got, ref_g in zip("qkv", (dq, dk, dv), g_ref):
            err = float(jnp.max(jnp.abs(
                got.reshape(b, tc, h, d) - ref_g
            )) / (jnp.max(jnp.abs(ref_g)) + 1e-8))
            res[f"bwd_{tag}_d{name}_err"] = round(err, 6)
        res[f"fwd_{tag}_err"] = round(res[f"fwd_{tag}_err"], 6)
    # Tolerance: on TPU, fp32 dots run as bf16 MXU passes at DEFAULT
    # precision on BOTH sides of the comparison, so kernel-vs-oracle
    # differences land at ~1e-2 (measured max 0.0104; exact-arithmetic
    # parity at 2e-5 is pinned by the CPU interpret-mode tests). A real
    # mask/lse/layout bug shows up as O(1) error.
    res["ok"] = bool(np.all([e < 5e-2 for kk_, e in res.items() if kk_ != "ok"]))
    return res


def _safe(label: str, fn, retries: int = 1):
    """Run one bench config; never let a transient tunnel/compile error
    kill the whole bench (the driver records its single JSON line at
    round end — partial results beat none)."""
    err = "unknown error"  # bound before the loop: `retries` could be -1,
    # and leaving it to the except-branch makes the return below depend on
    # loop-iteration order (round-5 ADVICE fragile-binding cleanup).
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — robustness surface
            first = (str(e).splitlines() or [""])[0]
            err = f"{type(e).__name__}: {first[:120]}"
            print(f"# bench config {label} attempt {attempt + 1} failed: {err}")
    return {"error": err}


def main(argv: list[str] | None = None) -> None:
    import argparse

    import jax

    from dtc_tpu.obs import MemorySink, MetricsRegistry

    ap = argparse.ArgumentParser(description="dtc_tpu benchmark")
    ap.add_argument(
        "--serve-only", action="store_true",
        help="run ONLY the serving-scheduler rows (the CPU-measured "
        "scheduler artifact path while the TPU tunnel is down; the full "
        "bench still includes them)",
    )
    ap.add_argument(
        "--fleet-only", action="store_true",
        help="run ONLY the serving-fleet rows (calibration, load, the "
        "replica-kill chaos leg) plus their goodput_* companion rows "
        "(ISSUE 16 — effective-tokens/s next to raw tokens/s)",
    )
    ap.add_argument(
        "--pool-only", action="store_true",
        help="run ONLY the resource-pool rows (ISSUE 17 — the diurnal "
        "and combined-chaos pool_smoke legs in subprocesses; train "
        "tokens/s under arbitration next to fleet completions and the "
        "transition/recompile counts)",
    )
    ap.add_argument(
        "--spec-only", action="store_true",
        help="run ONLY the speculative-decoding rows (ISSUE 19 — the "
        "spec_b8_k{2,4} launch-economy rows + the serve_spec closed-loop "
        "capacity row and its spec-off calibration partner; the "
        "CPU-measured artifact path while the TPU tunnel is down)",
    )
    ap.add_argument(
        "--devprof-only", action="store_true",
        help="run ONLY the device-time attribution row + trace overhead "
        "(ISSUE 8 — the CPU-measured observatory artifact path while the "
        "TPU tunnel is down; the full bench still includes them)",
    )
    ap.add_argument(
        "--serve-model", default="flagship", choices=("flagship", "tiny"),
        help="model for the serving rows: flagship (TPU-scale) or tiny "
        "(the audit/test model — scheduler metrics are model-agnostic and "
        "this keeps a CPU run in minutes)",
    )
    ap.add_argument("--serve-seed", type=int, default=0,
                    help="arrival-process seed (rows reproduce per seed)")
    args = ap.parse_args(argv)

    # Every per-config result flows through the metrics registry — the
    # same funnel the trainer emits through — so the BENCH json is a view
    # over registry events, not a hand-assembled dict.
    reg = MetricsRegistry()
    sink = reg.add_sink(MemorySink())

    def emit(label: str, res: dict) -> dict:
        reg.emit("bench_config", label=label, **res)
        return res

    if args.serve_model == "tiny":
        from dtc_tpu.analysis.lowering import audit_model_cfg

        serve_cfg_kw = dict(
            model_cfg=audit_model_cfg(), model_label="tiny", prompt_len=8,
            max_new_tokens=8, slots=4, n_requests=32,
        )
    else:
        serve_cfg_kw = dict(model_cfg=None, model_label="flagship")

    if args.spec_only:
        # The spec_* rows on the chosen model (tiny fits the 1-core CPU
        # host in minutes; flagship is the TPU row set). Tiny shapes
        # respect the audit model's max_seq_len=32 headroom
        # (prompt + new + spec_k - 1 <= 32).
        if args.serve_model == "tiny":
            from dtc_tpu.analysis.lowering import audit_model_cfg

            spec_gen_kw = dict(
                model_cfg=audit_model_cfg(decode_attention="fused_layers"),
                model_label="tiny", prompt_len=8, new_tokens=16,
                draft_layers=2,
            )
        else:
            spec_gen_kw = dict()
        for k in (2, 4):
            emit(f"spec_b8_k{k}", _safe(f"spec_b8_k{k}",
                 lambda k=k: spec_decode_bench(spec_k=k, **spec_gen_kw)))
        # The closed-loop A/B pair: spec-off calibration + spec-on row,
        # same arrival shape — the delta IS the launch economy.
        cal_label = "serve_cal_closed_loop"
        n_req = serve_cfg_kw.get("n_requests", 32)
        emit(cal_label, _safe(cal_label, lambda: serve_bench(
            None, seed=args.serve_seed, queue_depth=n_req,
            shed_watermark=0.0, **serve_cfg_kw)))
        serve_spec_row(emit, serve_cfg_kw, seed=args.serve_seed)
        extra = {
            "devices": jax.device_count(),
            "device_kind": jax.devices()[0].device_kind,
            "serve_model": args.serve_model,
        }
        for ev in sink.events:
            if ev["etype"] != "bench_config":
                continue
            extra[ev["label"]] = {
                k: v for k, v in ev.items()
                if k not in ("etype", "ts", "proc", "label")
            }
        for flag in decode_drift_guard(extra):
            print(f"# DECODE REGRESSION: {flag}")
        print("# bench-detail:", json.dumps(extra))
        reg.close()
        return

    if args.devprof_only:
        emit("devprof_b8", _safe("devprof_b8", devprof_bench))
        emit("trace_overhead", _safe("trace_overhead", trace_overhead_bench))
        extra = {
            "devices": jax.device_count(),
            "device_kind": jax.devices()[0].device_kind,
        }
        for ev in sink.events:
            if ev["etype"] != "bench_config":
                continue
            extra[ev["label"]] = {
                k: v for k, v in ev.items()
                if k not in ("etype", "ts", "proc", "label")
            }
        print("# bench-detail:", json.dumps(extra))
        reg.close()
        return

    if args.pool_only:
        pool_diurnal_rows(emit)
        extra = {
            "devices": jax.device_count(),
            "device_kind": jax.devices()[0].device_kind,
        }
        for ev in sink.events:
            if ev["etype"] != "bench_config":
                continue
            extra[ev["label"]] = {
                k: v for k, v in ev.items()
                if k not in ("etype", "ts", "proc", "label")
            }
        for flag in decode_drift_guard(extra):
            print(f"# DECODE REGRESSION: {flag}")
        print("# bench-detail:", json.dumps(extra))
        reg.close()
        return

    if args.fleet_only:
        serve_fleet_rows(emit, seed=args.serve_seed, **serve_cfg_kw)
        extra = {
            "devices": jax.device_count(),
            "device_kind": jax.devices()[0].device_kind,
            "serve_model": args.serve_model,
        }
        for ev in sink.events:
            if ev["etype"] != "bench_config":
                continue
            extra[ev["label"]] = {
                k: v for k, v in ev.items()
                if k not in ("etype", "ts", "proc", "label")
            }
        for flag in decode_drift_guard(extra):
            print(f"# DECODE REGRESSION: {flag}")
        print("# bench-detail:", json.dumps(extra))
        reg.close()
        return

    if args.serve_only:
        serve_bench_rows(emit, seed=args.serve_seed, **serve_cfg_kw)
        serve_lora_rows(emit, seed=args.serve_seed, **serve_cfg_kw)
        serve_int8_row(emit, serve_cfg_kw, seed=args.serve_seed)
        # Speculative serving row (ISSUE 19): closed-loop capacity with
        # serve.spec ON — A/B partner of serve_cal_closed_loop.
        serve_spec_row(emit, serve_cfg_kw, seed=args.serve_seed)
        # Fleet rows (ISSUE 13): router over 3 in-process replicas —
        # calibration, 0.9x/3x offered load, replica-kill chaos leg.
        serve_fleet_rows(emit, seed=args.serve_seed, **serve_cfg_kw)
        emit("trace_overhead", _safe("trace_overhead", trace_overhead_bench))
        extra = {
            "devices": jax.device_count(),
            "device_kind": jax.devices()[0].device_kind,
            "serve_model": args.serve_model,
        }
        for ev in sink.events:
            if ev["etype"] != "bench_config":
                continue
            extra[ev["label"]] = {
                k: v for k, v in ev.items()
                if k not in ("etype", "ts", "proc", "label")
            }
        for flag in decode_drift_guard(extra):
            print(f"# DECODE REGRESSION: {flag}")
        print("# bench-detail:", json.dumps(extra))
        reg.close()
        return

    ref = emit("reference_workload_b8", run_config(batch=8, remat=False, prng_impl="rbg"))
    tuned = emit(
        "tuned_b32_remat",
        run_config(batch=32, remat="block_save_flash", prng_impl="rbg"),
    )
    # Same 89.6M-class budget with an MXU-friendly attention shape
    # (head_dim=128): demonstrates the framework, not the workload, sets the
    # ceiling (PERF.md "Why 40% is out of reach for THIS model shape").
    hd128 = emit("mxu_hd128_b32_remat", _safe("hd128", lambda: run_config(
        batch=32, remat="block_save_flash", prng_impl="rbg", n_heads=4)))
    # Long-context: 8x the flagship sequence through the flash kernel.
    # Tiling from the round-5 on-chip sweep (PERF.md): the forward wants
    # wide KV blocks, the fused backward a square 512 tile.
    long_ctx = emit("long_context_t4096_b4", _safe("long_ctx", lambda: run_config(
        batch=4, remat="block_save_flash", prng_impl="rbg", max_seq_len=4096,
        bench_steps=10, attention_block_kv=1024,
        attention_block_q_bwd=512, attention_block_kv_bwd=512,
    )))
    # T=8192: exercises the packed SPLIT backward (fused dk/dv scratches
    # exceed VMEM past T=4096) — the shape that had no packed path before
    # round 5.
    long_ctx_8k = emit("long_context_t8192_b2", _safe("long_ctx_8k", lambda: run_config(
        batch=2, remat="block_save_flash", prng_impl="rbg", max_seq_len=8192,
        bench_steps=8, attention_block_kv=1024,
        attention_block_q_bwd=512, attention_block_kv_bwd=1024,
    )))
    # Same long-context budget at an MXU-friendly head shape (head_dim=128):
    # the hd32 row's gap to peak is the workload's lane bound, not the
    # kernels' (PERF.md round-5 ceiling analysis).
    long_ctx_hd128 = emit(
        "long_context_t4096_b4_hd128", _safe("long_ctx_hd128", lambda: run_config(
            batch=4, remat="block_save_flash", prng_impl="rbg", max_seq_len=4096,
            bench_steps=10, n_heads=4, attention_block_kv=1024,
        )))
    # MoE: flagship dims with top-2 expert FFNs — the dispatch-backend A/B
    # (ops/moe_dispatch.py): einsum vs sort at E=8 and E=16, identical
    # routing, so step-time deltas are pure dispatch cost. Rows report
    # both MFU bases ("mfu" = hardware/einsum-structural, "mfu_useful" =
    # k·T routed tokens — the A/B-honest number); PERF.md MoE section
    # carries the resulting tables.
    moe = emit("moe_e8_top2_b32", _safe("moe", lambda: run_config(
        batch=32, remat="block_save_flash", prng_impl="rbg", moe_experts=8,
        bench_steps=15,
    )))
    emit("moe_e8_top2_b32_sort", _safe("moe_sort", lambda: run_config(
        batch=32, remat="block_save_flash", prng_impl="rbg", moe_experts=8,
        moe_dispatch="sort", bench_steps=15,
    )))
    emit("moe_e16_top2_b32", _safe("moe_e16", lambda: run_config(
        batch=32, remat="block_save_flash", prng_impl="rbg", moe_experts=16,
        bench_steps=15,
    )))
    emit("moe_e16_top2_b32_sort", _safe("moe_e16_sort", lambda: run_config(
        batch=32, remat="block_save_flash", prng_impl="rbg", moe_experts=16,
        moe_dispatch="sort", bench_steps=15,
    )))

    result = {
        "metric": "tokens_per_sec",
        "value": ref["tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": round(ref["tokens_per_sec"] / BASELINE_TOKENS_PER_SEC, 3),
    }
    print(json.dumps(result))
    # Decode (serving) rows: b8 kept for round-over-round continuity, the
    # batch sweep amortizes the weight read (Pope et al.'s lever — the
    # roofline says b64 costs ~1.5x b8 per step for 8x the tokens), the
    # xla row is the fused-kernel A/B oracle, and the p256 row is the
    # prompt-length leg (cache_len sensitivity: mean write frontier 320
    # vs the p32 row's 96 — 3.3x the KV read through the same kernel).
    emit("decode_b8", _safe("decode_b8", decode_bench))
    emit("decode_b8_xla", _safe("decode_b8_xla", lambda: decode_bench(
        decode_attention="xla")))
    emit("decode_b32", _safe("decode_b32", lambda: decode_bench(batch=32)))
    emit("decode_b64", _safe("decode_b64", lambda: decode_bench(batch=64)))
    emit("decode_b8_p256", _safe("decode_b8_p256", lambda: decode_bench(
        prompt_len=256, new_tokens=128)))
    # ISSUE 11 rows: the layer-fused megakernel (one launch per token —
    # the launch-count lever) and megakernel+int8 (the KV-bytes lever on
    # top; pct_of_roofline is computed against the int8 byte model, so
    # the two levers are separable in the table).
    emit("decode_b8_fused_layers", _safe("decode_b8_fused_layers",
         lambda: decode_bench(decode_attention="fused_layers")))
    emit("decode_b8_int8", _safe("decode_b8_int8", lambda: decode_bench(
        decode_attention="fused_layers", kv_cache_dtype="int8")))
    # ISSUE 19 rows: speculative decoding on the megakernel — scored on
    # ms per ACCEPTED token and tokens/launch (the A/B partner is
    # decode_b8_fused_layers' ms_per_token; a draft earns its keep when
    # ms_per_accepted_token comes in under it).
    emit("spec_b8_k2", _safe("spec_b8_k2",
         lambda: spec_decode_bench(spec_k=2)))
    emit("spec_b8_k4", _safe("spec_b8_k4",
         lambda: spec_decode_bench(spec_k=4)))
    # Serving-scheduler rows (ISSUE 6): Poisson arrivals through the
    # continuous-batching engine at calibrated offered loads, including
    # one past saturation — the row that shows shedding holds p99.
    serve_bench_rows(emit, seed=args.serve_seed, **serve_cfg_kw)
    # int8-KV serving row (ISSUE 11): the closed-loop capacity shape on
    # the megakernel + int8 cache — see serve_int8_row.
    serve_int8_row(emit, serve_cfg_kw, seed=args.serve_seed)
    # Speculative serving row (ISSUE 19): closed-loop capacity with
    # serve.spec ON — A/B partner of serve_cal_closed_loop.
    serve_spec_row(emit, serve_cfg_kw, seed=args.serve_seed)
    # Multi-tenant LoRA rows (ISSUE 10): N tenants on one resident base;
    # the delta vs the serve_* rows is the per-token multi-tenant price.
    serve_lora_rows(emit, seed=args.serve_seed, **serve_cfg_kw)
    # Fleet rows (ISSUE 13): tenant-aware router over 3 in-process
    # replicas — calibration, 0.9x/3x offered load, and the replica-kill
    # chaos leg (failover mid-traffic, zero silent drops).
    serve_fleet_rows(emit, seed=args.serve_seed, **serve_cfg_kw)
    # Resource-pool rows (ISSUE 17): the diurnal and combined-chaos
    # pool_smoke legs, each in a subprocess with its own 8-virtual-device
    # mesh — train tokens/s under arbitration next to fleet completions
    # and the transition/recompile counts.
    pool_diurnal_rows(emit)
    # Tracing substrate cost (ISSUE 7): host-side span-emission µs per
    # step, A/B traced vs untraced — PERF.md reads the % off this row.
    emit("trace_overhead", _safe("trace_overhead", trace_overhead_bench))
    # Device-time attribution (ISSUE 8): component breakdown + overlap%
    # for the b8 reference step, gated structurally (every dot attributed,
    # unattributed share bounded) with the census cross-check.
    emit("devprof_b8", _safe("devprof_b8", devprof_bench))
    # Overlapped-collectives A/B (ISSUE 12): the SAME fsdp config with
    # collectives xla vs overlapped — tokens/s plus the devprof
    # overlap_ratio (ROADMAP item 2's 0.0 -> >=0.5 headline). Needs a
    # multi-chip slice; on the 1-chip tunnel both legs record the typed
    # error (wired-but-unmeasured, PERF.md round 11).
    emit("fsdp_overlap_ab_xla", _safe("fsdp_overlap_ab_xla",
         lambda: fsdp_overlap_bench(collectives="xla")))
    emit("fsdp_overlap_ab_overlapped", _safe("fsdp_overlap_ab_overlapped",
         lambda: fsdp_overlap_bench(collectives="overlapped")))
    # Mixed-precision A/B (ISSUE 14): the SAME flagship dp step under
    # precision fp32 vs bf16_mixed — tokens/s + the analytic HBM budget
    # (params/masters/moments/grads). CPU legs are shape-only (bf16 is
    # emulated here); the TPU pair is the real speed number.
    emit("precision_ab_fp32", _safe("precision_ab_fp32",
         lambda: precision_ab_bench(precision="fp32")))
    emit("precision_ab_bf16", _safe("precision_ab_bf16",
         lambda: precision_ab_bench(precision="bf16_mixed")))
    emit("ring_block_smoke", _safe("ring_block_smoke", ring_block_smoke))

    # Assemble the detail line FROM the registry's event stream: each
    # bench_config event becomes one keyed entry, existing keys unchanged
    # (new per-config fields ride along: dispatch_s/blocked_s/peak_hbm_bytes).
    extra = {
        "devices": jax.device_count(),
        "device_kind": jax.devices()[0].device_kind,
    }
    for ev in sink.events:
        if ev["etype"] != "bench_config":
            continue
        body = {k: v for k, v in ev.items() if k not in ("etype", "ts", "proc", "label")}
        extra[ev["label"]] = body
    extra["mfu"] = tuned["mfu"]  # honest per-chip utilization on the REFERENCE shape
    extra["mfu_hd128"] = hd128.get("mfu")  # None if the _safe config errored
    # Process-lifetime HBM peak (across ALL configs — per-config peaks are
    # not separable; per-config live working sets are hbm_bytes_in_use).
    from dtc_tpu.obs import peak_hbm_bytes, sample_memory

    extra["peak_hbm_bytes"] = peak_hbm_bytes(sample_memory())
    for flag in decode_drift_guard(extra):
        print(f"# DECODE REGRESSION: {flag}")
    print("# bench-detail:", json.dumps(extra))
    reg.close()


if __name__ == "__main__":
    main()
