"""Headline benchmark: flagship GPT-89.6M train-step throughput on real hardware.

Runs the reference workload (batch 8 × seq 512 = 4,096 tokens/step, AdamW,
dropout 0.1 — BASELINE.md) with this framework's TPU path (bf16 compute,
fused attention when available) on whatever devices are present, and prints
ONE JSON line:

    {"metric": "tokens_per_sec", "value": ..., "unit": "tokens/s", "vs_baseline": ...}

vs_baseline is relative to the reference's best strategy throughput,
~27.9k tokens/s for DP/TP on its (unspecified) CUDA-12 GPUs
(`/root/reference/outputs/dp/log.csv`, SURVEY.md §6).
"""

from __future__ import annotations

import json
import time

BASELINE_TOKENS_PER_SEC = 27_900.0  # reference DP/TP, SURVEY.md §6


def main() -> None:
    import jax
    import numpy as np

    from dtc_tpu.config.schema import MeshConfig, ModelConfig, OptimConfig, TrainConfig
    from dtc_tpu.data.synthetic import synthetic_batch_iterator
    from dtc_tpu.data.prefetch import ShardedPrefetchIterator
    from dtc_tpu.models.gpt import GPT
    from dtc_tpu.parallel.mesh import mesh_from_config
    from dtc_tpu.parallel.sharding import DEFAULT_RULES, batch_spec
    from dtc_tpu.train.train_step import Batch, create_train_step
    from dtc_tpu.train.trainer import init_state
    from dtc_tpu.utils.metrics import mfu
    from flax import linen as nn

    model_cfg = ModelConfig(
        vocab_size=50258, d_model=512, n_layers=12, n_heads=16, d_ff=2048,
        max_seq_len=512, dropout=0.1, param_dtype="float32",
        compute_dtype="bfloat16", attention="auto",
    )
    opt_cfg = OptimConfig(lr=3e-4, weight_decay=0.1, grad_clip=1.0)
    n_dev = jax.device_count()
    train_cfg = TrainConfig(
        seed=0, parallel="dp", batch=8, steps=1, log_every=1, output_dir="",
        dataset="synthetic", warmup_steps=0, prefetch=2, mesh=MeshConfig(),
    )

    mesh = mesh_from_config("dp", train_cfg.mesh)
    model = GPT(model_cfg)
    rules = DEFAULT_RULES

    warmup_steps, bench_steps = 10, 30
    with mesh, nn.logical_axis_rules(rules):
        state = init_state(model, model_cfg, train_cfg, opt_cfg, mesh, rules)
        step_fn = create_train_step(mesh, model=model)
        it = ShardedPrefetchIterator(
            synthetic_batch_iterator(
                train_cfg.batch, model_cfg.max_seq_len + 1, model_cfg.vocab_size
            ),
            mesh, batch_spec(rules), queue_size=4,
        )
        key = jax.random.PRNGKey(0)

        for _ in range(warmup_steps):
            x, y = next(it)
            key, sub = jax.random.split(key)
            state, loss = step_fn(state, Batch(x=x, y=y), sub)
        # Sync via value fetch: on some remote-execution platforms
        # block_until_ready returns before device work completes, but a
        # host transfer of the result cannot.
        float(np.asarray(loss))

        start = time.perf_counter()
        for _ in range(bench_steps):
            x, y = next(it)
            key, sub = jax.random.split(key)
            state, loss = step_fn(state, Batch(x=x, y=y), sub)
        final_loss = float(np.asarray(loss))
        elapsed = time.perf_counter() - start

    step_time = elapsed / bench_steps
    tokens_per_sec = train_cfg.batch * model_cfg.max_seq_len / step_time
    u = mfu(model_cfg, train_cfg.batch, model_cfg.max_seq_len, step_time, n_dev)
    result = {
        "metric": "tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / BASELINE_TOKENS_PER_SEC, 3),
    }
    print(json.dumps(result))
    # Context lines for humans (stderr-free; driver reads the JSON line above).
    extra = {
        "step_time_s": round(step_time, 5),
        "devices": n_dev,
        "device_kind": jax.devices()[0].device_kind,
        "mfu": round(u, 4) if u is not None else None,
        "final_loss": final_loss,
    }
    print("# bench-detail:", json.dumps(extra))


if __name__ == "__main__":
    main()
