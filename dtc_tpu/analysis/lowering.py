"""Auditable entry points: lower/compile the real steps, capture evidence.

One registry of (mode -> lowering recipe) so the audit CLI, the drift
baselines, and the HLO collective tests all compile THE SAME programs the
trainer runs. Fidelity notes that make the audit representative:

- Input shardings are COMMITTED (``jax.device_put`` of the batch under the
  mode's ``batch_spec``, ``init_state``'s placed params): in this
  environment flax's in-graph logical constraints lower to nothing, so
  GSPMD derives every collective from committed argument shardings alone —
  exactly how the trainer feeds the step (``prefetch.split_put``). Lowering
  an uncommitted batch produces a collective-free module that would
  "pass" every census vacuously.
- The audit model is the test suite's tiny config (fp32 compute: XLA's CPU
  backend check-fails on some bf16 collectives, see tests/conftest.py), on
  the same 8-virtual-device mesh — baselines are per-(mode, model) and say
  so in their fingerprint.
- Recompile fingerprints come from EXECUTING the compiled step twice under
  the obs compile watcher: cold must compile exactly once (two means the
  PR 1 out-shardings bug class is back), steady must compile zero times.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import NamedSharding

from dtc_tpu.config.schema import MeshConfig, ModelConfig, OptimConfig, TrainConfig
from dtc_tpu.models.gpt import GPT
from dtc_tpu.obs.stepclock import CompileWatcher
from dtc_tpu.parallel.mesh import mesh_from_config
from dtc_tpu.parallel.sharding import (
    DEFAULT_RULES,
    FSDP_RULES,
    batch_spec,
    ring_rules_from,
)
from dtc_tpu.train.train_step import Batch, create_train_step
from dtc_tpu.utils.metrics import comm_bytes_per_step

#: HLO dtype token for the numpy dtypes the audit model can hold.
_NP_TO_HLO = {
    "float32": "f32", "bfloat16": "bf16", "float16": "f16",
    "float64": "f64", "int32": "s32", "int64": "s64", "uint32": "u32",
    "bool": "pred",
}


def audit_model_cfg(**overrides: Any) -> ModelConfig:
    """The audit's tiny model — dimension-for-dimension the test suite's
    ``tiny_model_cfg`` (divisibility over model=2/4/8, pipe=2/4), so the
    committed baselines and the HLO tests describe the same programs."""
    base = dict(
        vocab_size=97, d_model=64, n_layers=4, n_heads=4, d_ff=128,
        max_seq_len=32, dropout=0.0, param_dtype="float32",
        compute_dtype="float32", attention="dense",
    )
    base.update(overrides)
    return ModelConfig(**base)


def audit_opt_cfg(precision: str = "fp32") -> OptimConfig:
    return OptimConfig(
        lr=1e-3, weight_decay=0.1, grad_clip=1.0, precision=precision
    )


def audit_train_cfg(parallel: str, mesh: MeshConfig) -> TrainConfig:
    return TrainConfig(
        seed=0, parallel=parallel, batch=8, steps=4, log_every=2,
        output_dir="", dataset="synthetic", warmup_steps=0, prefetch=0,
        mesh=mesh,
    )


@dataclasses.dataclass(frozen=True)
class EntrySpec:
    """One auditable entry point: how to build + lower it."""

    name: str
    parallel: str
    mesh: MeshConfig
    model_overrides: dict[str, Any]
    rules: str  # "default" | "fsdp" | "ring"
    # Training precision policy (OptimConfig.precision): "bf16_mixed"
    # lifts bf16 param/compute dtypes onto the model config through the
    # trainer's own resolve_precision, so the audited program IS the
    # trained program (ISSUE 14).
    precision: str = "fp32"


#: The registry. ``dp/tp/fsdp/ep`` are the audit CLI's default set (the
#: paper's strategy comparison); ``ep_sort`` and ``ulysses`` exist so the
#: refactored collective tests lower through this same table.
TRAIN_ENTRIES: dict[str, EntrySpec] = {
    "dp": EntrySpec("dp", "dp", MeshConfig(), {}, "default"),
    "tp": EntrySpec("tp", "tp", MeshConfig(), {}, "default"),
    "fsdp": EntrySpec("fsdp", "fsdp", MeshConfig(), {}, "fsdp"),
    "ep": EntrySpec(
        "ep", "3d", MeshConfig(pipe=1, data=4, model=2),
        dict(moe_experts=4, moe_top_k=2, moe_capacity_factor=2.0), "default",
    ),
    "ep_sort": EntrySpec(
        "ep_sort", "3d", MeshConfig(pipe=1, data=4, model=2),
        dict(moe_experts=4, moe_top_k=2, moe_capacity_factor=2.0,
             moe_dispatch="sort"), "default",
    ),
    "ulysses": EntrySpec(
        "ulysses", "3d", MeshConfig(pipe=1, data=2, model=4),
        dict(attention="ulysses"), "ring",
    ),
    # ISSUE 12 — overlapped training collectives. Two audited flavors:
    # the pure-FSDP ring (the b8 reference's mesh) and the first-class
    # DP×FSDP×TP "3d" mode (configs/train_config_3d.yaml). On this CPU
    # the op resolves to the decomposed transport, so the baselines pin
    # the collective-permute ring census + the ABSENCE of the per-layer
    # kernel all-gathers it replaces; TPU lowerings carry the Pallas
    # custom-calls instead (rules.py accepts either fingerprint, and
    # tests/test_overlap_collectives.py pins the tpu_custom_call via
    # jax.export).
    "fsdp_overlapped": EntrySpec(
        "fsdp_overlapped", "fsdp", MeshConfig(),
        dict(collectives="overlapped"), "fsdp",
    ),
    "3d": EntrySpec(
        "3d", "fsdp", MeshConfig(pipe=1, data=4, model=2),
        dict(collectives="overlapped"), "fsdp",
    ),
    # ISSUE 14 — the bf16_mixed training mode the numerics + memory
    # passes certify: bf16 params + bf16 matmuls (resolve_precision lifts
    # them from the opt config), fp32 masters + moments in the optimizer
    # (with_master_weights), bf16 grads on the dp all-reduce wire. Same
    # dp mesh as the fp32 reference entry, so the two baselines are an
    # A/B of the policy alone. (The CPU backend legalizes bf16 DOTS to
    # f32 in the optimized HLO — which is why the numerics rules read the
    # StableHLO — but compiles and runs this program fine; the bf16
    # collective crash class in tests/conftest.py is pipeline-specific.)
    "bf16": EntrySpec(
        "bf16", "dp", MeshConfig(), {}, "default", precision="bf16_mixed",
    ),
}

_RULE_TABLES = {
    "default": DEFAULT_RULES,
    "fsdp": FSDP_RULES,
    "ring": ring_rules_from(DEFAULT_RULES),
}


@dataclasses.dataclass
class Artifact:
    """Everything the rule engine audits about one lowered entry point.

    The two text blobs are deliberately both kept: the optimized HLO is
    where collectives/donation/f64 live; the backend-independent StableHLO
    is where declared matmul dtypes survive CPU legalization (see
    ``hlo.dot_dtype_counts``).
    """

    name: str
    kind: str                       # "train" | "decode"
    parallel: str | None
    mesh_shape: dict[str, int]
    batch: int
    seq_len: int
    hlo_text: str
    stablehlo_text: str
    expected_donated: int           # donated leaves the alias map must cover
    param_shapes: list[tuple[str, tuple[int, ...]]]  # sharded params' FULL dims
    weak_outputs: int               # weak-typed jaxpr outvars
    n_layers: int
    moe_experts: int
    compute_dtype: str
    cold_compiles: int | None = None   # None = not executed
    steady_compiles: int | None = None
    comm_estimate: dict[str, float] | None = None
    # --- ISSUE 14: numerics + memory evidence ---
    precision: str = "fp32"            # declared policy (OptimConfig.precision)
    loss_dtype: str = ""               # jaxpr dtype of the loss output ("" = n/a)
    # Exact per-device LOCAL bytes of the live placed state, classified
    # by pytree path: params / opt_master / opt_moments / opt_other
    # (+ cache / lora_stack for serving entries). The module-side
    # entry-layout bytes verify this decomposition (analysis/memory.py).
    state_bytes: dict[str, int] | None = None
    # Distinct dtypes per state class, e.g. {"opt_moments": ["f32"]} —
    # the optimizer-state numerics rule reads these.
    state_dtypes: dict[str, list[str]] | None = None
    batch_bytes: int = 0               # non-state entry inputs (tokens, rng, idx)
    # XLA's CompiledMemoryStats (argument/output/temp/alias bytes). The
    # CPU backend DOES report temp for real modules (the audit plans use
    # it as the measured activation row); where a backend reports 0/none,
    # the memory plan falls back to the analytic estimate and says so.
    mem_stats: dict[str, int] | None = None
    # utils/metrics.train_memory_bytes for train entries (None elsewhere)
    # — the analytic cross-check target.
    mem_estimate: dict[str, float] | None = None


def _local_nbytes(leaf: Any) -> int:
    """Per-device LOCAL bytes of one placed array (its shard shape under
    the committed sharding; the full shape for unsharded/abstract
    leaves) — the same basis the GSPMD module's entry layout uses."""
    shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and hasattr(sharding, "shard_shape"):
        try:
            shape = tuple(int(d) for d in sharding.shard_shape(shape))
        except Exception:
            pass
    itemsize = np.dtype(leaf.dtype).itemsize if hasattr(leaf, "dtype") else 4
    return math.prod(shape) * itemsize if shape else itemsize


#: HLO dtype token per numpy dtype name, for state_dtypes entries.
def _hlo_dtype(leaf: Any) -> str:
    return _NP_TO_HLO.get(str(np.dtype(leaf.dtype)), str(leaf.dtype))


def _classify_state(state: Any) -> tuple[dict[str, int], dict[str, list[str]]]:
    """(bytes, dtypes) of a TrainState's leaves by class, keyed on the
    pytree PATH — the one place the params/master/moments split is ground
    truth (optax state is named tuples: ``.mu``/``.nu`` are the AdamW
    moments, ``.master`` the with_master_weights fp32 copies; everything
    else in opt_state is counts/clip bookkeeping)."""
    import jax.tree_util as jtu

    bytes_by: dict[str, int] = {}
    dtypes_by: dict[str, set[str]] = {}
    for path, leaf in jtu.tree_flatten_with_path(state)[0]:
        key = jtu.keystr(path)
        if key.startswith(".params"):
            cls = "params"
        elif ".master" in key:
            cls = "opt_master"
        elif ".mu" in key or ".nu" in key:
            cls = "opt_moments"
        elif key.startswith(".opt_state"):
            cls = "opt_other"
        else:
            cls = "opt_other"  # .step and friends: scalar bookkeeping
        bytes_by[cls] = bytes_by.get(cls, 0) + _local_nbytes(leaf)
        dtypes_by.setdefault(cls, set()).add(_hlo_dtype(leaf))
    return bytes_by, {k: sorted(v) for k, v in dtypes_by.items()}


def _compiled_mem_stats(compiled: Any) -> dict[str, int] | None:
    """argument/output/temp/alias bytes from XLA's memory analysis (None
    when the backend does not report one)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    return {
        "argument": int(getattr(ma, "argument_size_in_bytes", 0) or 0),
        "output": int(getattr(ma, "output_size_in_bytes", 0) or 0),
        "temp": int(getattr(ma, "temp_size_in_bytes", 0) or 0),
        "alias": int(getattr(ma, "alias_size_in_bytes", 0) or 0),
    }


def _loss_dtype(traced: Any) -> str:
    """HLO dtype token of the step's LOSS output (the last flattened
    outvar — the steps return ``(state, loss)``)."""
    avals = traced.jaxpr.out_avals
    return _NP_TO_HLO.get(str(np.dtype(avals[-1].dtype)), "?")


def _param_shapes(params: Any) -> list[tuple[str, tuple[int, ...]]]:
    """Full (unsharded) parameter shapes as (hlo-dtype, dims)."""
    out = []
    for leaf in jax.tree.leaves(params):
        dt = _NP_TO_HLO.get(str(np.dtype(leaf.dtype)), "f32")
        out.append((dt, tuple(int(d) for d in leaf.shape)))
    return out


def _sharded_param_shapes(
    params: Any,
    rules: Sequence[tuple[str, str | None]],
    mesh,
    min_size: int,
) -> list[tuple[str, tuple[int, ...]]]:
    """Full shapes of the params that are actually SHARDED under
    ``rules`` on ``mesh`` (spec keeps a live mesh axis after GSPMD
    normalization) and at least ``min_size`` elements — the
    forbidden-gather rule's comparison set.

    Two deliberate exclusions, both verified against healthy graphs:

    - Replicated params: their GRADIENTS are param-shaped and
      legitimately assembled via all-gather when computed from sharded
      activations (TP layernorm grads, the EP router grad).
    - Sub-matrix-scale params (``min_size`` = d_model², i.e. smaller than
      one weight matrix — the stacked per-layer biases): their shapes
      collide with incidental small buffers in healthy TP/EP modules, and
      a gathered bias is noise next to the kernel gather that would
      accompany a real replicate-and-slice fallback."""
    from jax.sharding import PartitionSpec as P

    from dtc_tpu.parallel.sharding import param_specs
    from dtc_tpu.train.train_step import normalize_spec

    specs = param_specs(params, rules)
    out = []
    for leaf, spec in zip(
        jax.tree.leaves(params),
        jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)),
    ):
        norm = normalize_spec(spec, mesh)
        if leaf.size >= min_size and any(part is not None for part in norm):
            dt = _NP_TO_HLO.get(str(np.dtype(leaf.dtype)), "f32")
            out.append((dt, tuple(int(d) for d in leaf.shape)))
    return out


def _measure_compiles(call_once, call_again) -> tuple[int, int]:
    """Execute an entry point twice under the compile watcher; return the
    (cold, steady) backend-compile counts. ``call_again`` receives the
    first call's output so a donating step can feed its own result back
    (the donated input is dead after call one). Steady > 0 is the silent
    double-compile the PR 1 watcher caught — here it fails the audit."""
    watcher = CompileWatcher().activate()
    try:
        watcher.drain()
        out = call_once()
        jax.block_until_ready(jax.tree.leaves(out)[-1])
        _, cold = watcher.drain()
        out = call_again(out)
        jax.block_until_ready(jax.tree.leaves(out)[-1])
        _, steady = watcher.drain()
    finally:
        watcher.deactivate()
    return cold, steady


def _lower_train_step(
    parallel: str,
    mesh_cfg: MeshConfig,
    model_cfg: ModelConfig,
    opt_cfg: OptimConfig,
    rules: Sequence[tuple[str, str | None]],
):
    """ONE trainer-faithful lowering for both the audit artifacts and the
    HLO tests: committed param shardings via ``init_state``, committed
    batch shardings via ``device_put`` under the mode's ``batch_spec``,
    out-shardings pinned by passing the placed state into
    ``create_train_step``. Returns ``(mesh, step, state, batch, rng)``;
    callers must keep using the mesh/rules context it opens internally
    only for construction — lower/compile are context-free.

    A single definition on purpose: the module's invariant is that the
    committed baselines and tests/test_collectives_hlo.py describe THE
    SAME programs, which duplicate lowering blocks would quietly break.
    """
    from dtc_tpu.train.trainer import init_state

    mesh = mesh_from_config(parallel, mesh_cfg)
    model = GPT(model_cfg)
    tc = audit_train_cfg(parallel, mesh_cfg)
    with mesh, nn.logical_axis_rules(rules):
        state = init_state(model, model_cfg, tc, opt_cfg, mesh, rules)
        step = create_train_step(mesh, model=model, state=state)
        x = jax.device_put(
            np.zeros((tc.batch, model_cfg.max_seq_len), np.int32),
            NamedSharding(mesh, batch_spec(rules)),
        )
    return mesh, step, state, Batch(x=x, y=x), jax.random.PRNGKey(0)


def compiled_train_hlo(
    parallel: str,
    mesh_cfg: MeshConfig,
    model_cfg: ModelConfig,
    opt_cfg: OptimConfig,
    rules: Sequence[tuple[str, str | None]],
) -> str:
    """Optimized-HLO text of the train step, lowered trainer-faithfully.
    The refactored ``tests/test_collectives_hlo.py`` asserts on this text
    through the shared parsers in :mod:`dtc_tpu.analysis.hlo`."""
    mesh, step, state, batch, rng = _lower_train_step(
        parallel, mesh_cfg, model_cfg, opt_cfg, rules
    )
    with mesh, nn.logical_axis_rules(rules):
        return step.lower(state, batch, rng).compile().as_text()


def build_train_artifact(mode: str, *, execute: bool = True) -> Artifact:
    """Lower + compile one registry train entry and collect the evidence
    the rules audit. ``execute=True`` additionally runs the step twice for
    the recompile fingerprint (adds device time, CPU-cheap at this size)."""
    from dtc_tpu.train.train_step import resolve_precision
    from dtc_tpu.utils.metrics import train_memory_bytes

    spec = TRAIN_ENTRIES[mode]
    opt_cfg = audit_opt_cfg(spec.precision)
    # The SAME resolution the trainer applies: bf16_mixed lifts bf16
    # param/compute dtypes onto the model config — the audited lowering
    # and the trained lowering share one definition by construction.
    model_cfg = resolve_precision(
        opt_cfg, audit_model_cfg(**spec.model_overrides)
    )
    rules = _RULE_TABLES[spec.rules]
    mesh, step, state, batch, rng = _lower_train_step(
        spec.parallel, spec.mesh, model_cfg, opt_cfg, rules
    )
    with mesh, nn.logical_axis_rules(rules):
        lowered = step.lower(state, batch, rng)
        stablehlo = lowered.as_text()
        compiled = lowered.compile()
        hlo = compiled.as_text()
        traced = step.trace(state, batch, rng)
        weak = sum(
            1 for v in traced.jaxpr.jaxpr.outvars
            if getattr(v.aval, "weak_type", False)
        )
        state_bytes, state_dtypes = _classify_state(state)
        batch_bytes = (
            _local_nbytes(batch.x) + _local_nbytes(batch.y)
            + _local_nbytes(rng)
        )
        cold = steady = None
        if execute:
            cold, steady = _measure_compiles(
                lambda: step(state, batch, rng),
                lambda out: step(out[0], batch, rng),
            )
        mesh_shape = {k: int(v) for k, v in mesh.shape.items()}
        return Artifact(
            name=f"train_{mode}",
            kind="train",
            parallel=spec.parallel,
            mesh_shape=mesh_shape,
            batch=int(batch.x.shape[0]),
            seq_len=model_cfg.max_seq_len,
            hlo_text=hlo,
            stablehlo_text=stablehlo,
            expected_donated=len(jax.tree.leaves(state)),
            param_shapes=_sharded_param_shapes(
                state.params, rules, mesh, min_size=model_cfg.d_model**2
            ),
            weak_outputs=weak,
            n_layers=model_cfg.n_layers,
            moe_experts=model_cfg.moe_experts,
            compute_dtype=model_cfg.compute_dtype,
            cold_compiles=cold,
            steady_compiles=steady,
            comm_estimate=comm_bytes_per_step(
                model_cfg, int(batch.x.shape[0]), model_cfg.max_seq_len, mesh_shape,
                spec.parallel,
            ),
            precision=spec.precision,
            loss_dtype=_loss_dtype(traced),
            state_bytes=state_bytes,
            state_dtypes=state_dtypes,
            batch_bytes=batch_bytes,
            mem_stats=_compiled_mem_stats(compiled),
            mem_estimate=train_memory_bytes(
                model_cfg, int(batch.x.shape[0]), model_cfg.max_seq_len,
                mesh_shape, spec.parallel, precision=spec.precision,
            ),
        )


def build_decode_artifact(
    *, execute: bool = True, decode_attention: str = "fused"
) -> Artifact:
    """Lower + compile the greedy decode entry point (prefill + token scan
    under one jit — the serving fast path of PR 4) on the default device.

    Greedy is the audited flavor: it is the bench's continuity row and its
    HLO must stay free of the sampling machinery. No donation is expected
    (generate allocates its cache per call).

    ``decode_attention="fused_layers"`` audits the ISSUE 11 megakernel
    flavor as its own entry (``decode_fused_layers``): the layer loop
    moves from an XLA scan into the Pallas grid, a structurally different
    program whose drift deserves its own committed baseline."""
    from dtc_tpu.generate import _generate_jit

    model_cfg = audit_model_cfg(decode_attention=decode_attention)
    model = GPT(model_cfg)
    params = jax.jit(
        lambda r, x: model.init({"params": r, "dropout": r}, x, train=False)
    )(jax.random.PRNGKey(0), jnp.ones((1, model_cfg.max_seq_len), jnp.int32))[
        "params"
    ]
    prompt = jnp.zeros((2, 4), jnp.int32)
    args = (model, params, prompt, 8, jax.random.PRNGKey(1))
    kwargs = dict(temperature=0.0)
    lowered = _generate_jit.lower(*args, **kwargs)
    stablehlo = lowered.as_text()
    compiled = lowered.compile()
    hlo = compiled.as_text()
    traced = _generate_jit.trace(*args, **kwargs)
    weak = sum(
        1 for v in traced.jaxpr.jaxpr.outvars
        if getattr(v.aval, "weak_type", False)
    )
    cold = steady = None
    if execute:
        cold, steady = _measure_compiles(
            lambda: _generate_jit(*args, **kwargs),
            lambda _out: _generate_jit(*args, **kwargs),
        )
    return Artifact(
        name=(
            "decode_greedy" if decode_attention == "fused"
            else f"decode_{decode_attention}"
        ),
        kind="decode",
        parallel=None,
        mesh_shape={},
        batch=2,
        seq_len=model_cfg.max_seq_len,
        hlo_text=hlo,
        stablehlo_text=stablehlo,
        expected_donated=0,
        param_shapes=_param_shapes(params),
        weak_outputs=weak,
        n_layers=model_cfg.n_layers,
        moe_experts=0,
        compute_dtype=model_cfg.compute_dtype,
        cold_compiles=cold,
        steady_compiles=steady,
        comm_estimate=None,
        state_bytes={
            "params": sum(_local_nbytes(p) for p in jax.tree.leaves(params)),
        },
        state_dtypes={
            "params": sorted({
                _hlo_dtype(p) for p in jax.tree.leaves(params)
            }),
        },
        batch_bytes=_local_nbytes(prompt) + _local_nbytes(args[4]),
        mem_stats=_compiled_mem_stats(compiled),
    )


def build_serve_artifact(
    *, execute: bool = True, lora: bool = True, kv_int8: bool = False
) -> Artifact:
    """Lower + compile the SERVING decode step — the continuous-batching
    iteration ``dtc_tpu/serve/engine.py`` drives over its fixed slot batch
    (per-slot ``(B,)`` cache frontiers, greedy argmax, finite flag).

    TWO audited flavors, because the engine builds two distinct compiled
    step programs (``_build_fns`` branches on the model's adapter config):

    - ``lora=True`` -> ``serve_decode``: the MULTI-TENANT flavor (ISSUE
      10) — the audit model carries a rank-2 LoRA adapter config, so the
      audited program includes the per-slot factor gather from the
      resident ``(max_adapters, ...)`` stack. Its recompile fingerprint
      extends the compiled-shape invariant across the adapter lifecycle:
      between the two measured step executions an adapter is LOADED
      (jitted traced-slot stack write, pre-warmed) and a request ADMITTED
      — the batch goes from one tenant slot to a mixed adapter+base
      batch of two.
    - ``lora=False`` -> ``serve_decode_base``: the adapter-free flavor
      every plain deployment runs — baselined separately so a regression
      in THAT branch cannot hide behind a green lora audit.
    - ``kv_int8=True`` -> ``serve_decode_int8`` (ISSUE 11): the
      quantized-cache + layer-fused-megakernel flavor (``kv_cache_dtype:
      int8`` + ``decode_attention: fused_layers`` with the lora config) —
      the serving program the int8 bench rows run. Its recompile
      fingerprint proves admission and tenant churn stay recompile-free
      when the cache tree grows the int8 payload + scale leaves.

    Either way: admission, eviction, and (lora) tenant churn at fixed
    slots must reuse the ONE executable (cold==1, steady==0), or serving
    latency grows a compile stall on every arrival/load."""
    from dtc_tpu.config.schema import AdapterConfig, ServeConfig
    from dtc_tpu.serve.engine import ServingEngine
    from dtc_tpu.serve.request import Request

    overrides: dict[str, Any] = (
        dict(adapter=AdapterConfig(rank=2, alpha=4.0)) if lora else {}
    )
    if kv_int8:
        overrides.update(
            kv_cache_dtype="int8", decode_attention="fused_layers"
        )
    model_cfg = audit_model_cfg(**overrides)
    model = GPT(model_cfg)
    params = jax.jit(
        lambda r, x: model.init({"params": r, "dropout": r}, x, train=False)
    )(jax.random.PRNGKey(0), jnp.ones((1, model_cfg.max_seq_len), jnp.int32))[
        "params"
    ]
    scfg = ServeConfig(slots=2, page_size=8, queue_depth=8, max_new_tokens=4,
                       prefill_bucket=8, max_adapters=4)
    eng = ServingEngine(model, params, scfg)
    toks = jnp.zeros((scfg.slots,), jnp.int32)
    if lora:
        args = (
            params, eng.lora_stack, jnp.asarray(eng.slot_adapter),
            eng.cache, toks,
        )
    else:
        args = (params, eng.cache, toks)
    lowered = eng._step_fn.lower(*args)
    stablehlo = lowered.as_text()
    compiled = lowered.compile()
    hlo = compiled.as_text()
    traced = eng._step_fn.trace(*args)
    weak = sum(
        1 for v in traced.jaxpr.jaxpr.outvars
        if getattr(v.aval, "weak_type", False)
    )
    # Byte decomposition of the step's resident inputs, taken BEFORE the
    # execution passes below mutate the engine (shapes never change —
    # that is the audited invariant — but the cache object is reassigned).
    serve_state_bytes = {
        "params": sum(_local_nbytes(p) for p in jax.tree.leaves(params)),
        "cache": sum(_local_nbytes(c) for c in jax.tree.leaves(eng.cache)),
    }
    if lora:
        serve_state_bytes["lora_stack"] = sum(
            _local_nbytes(f) for f in jax.tree.leaves(eng.lora_stack)
        )
    cold = steady = None
    if execute:
        # Warm every helper an admission (and, lora flavor, an adapter
        # load) runs — prefill / cache insert / stack insert — so the
        # measured window isolates the decode step itself. Factors built
        # up front: init_lora jits its own one-off init, which must not
        # land inside the window.
        warm_ad = None
        if lora:
            from dtc_tpu.adapters import init_lora

            factors = init_lora(model, seed=1)
            eng.load_adapter("warm_ad", factors)
            warm_ad = "warm_ad"
        eng.submit(Request(rid="warm", prompt=[1, 2, 3], max_new_tokens=1,
                           adapter=warm_ad))
        eng.run(max_steps=8)

        def call_once():
            ad = None
            if lora:
                eng.load_adapter("t1", factors)  # traced-slot stack write
                ad = "t1"
            eng.submit(Request(rid="a", prompt=[1, 2, 3], max_new_tokens=4,
                               adapter=ad))
            eng.step()  # admits "a", decodes — the step's ONE compile
            return eng.cache

        def call_again(_):
            if lora:
                eng.load_adapter("t2", factors)  # hot load mid-flight
            eng.submit(Request(rid="b", prompt=[4, 5], max_new_tokens=4))
            eng.step()  # admits base "b": batch 1->2 (mixed when lora)
            return eng.cache

        cold, steady = _measure_compiles(call_once, call_again)
    name = "serve_decode" if lora else "serve_decode_base"
    if kv_int8:
        name = "serve_decode_int8"
    return Artifact(
        name=name,
        kind="serve",
        parallel=None,
        mesh_shape={},
        batch=scfg.slots,
        seq_len=model_cfg.max_seq_len,
        hlo_text=hlo,
        stablehlo_text=stablehlo,
        expected_donated=0,
        param_shapes=_param_shapes(params),
        weak_outputs=weak,
        n_layers=model_cfg.n_layers,
        moe_experts=0,
        compute_dtype=model_cfg.compute_dtype,
        cold_compiles=cold,
        steady_compiles=steady,
        comm_estimate=None,
        state_bytes=serve_state_bytes,
        state_dtypes={
            "params": sorted({
                _hlo_dtype(p) for p in jax.tree.leaves(params)
            }),
        },
        batch_bytes=_local_nbytes(toks) + (
            _local_nbytes(jnp.asarray(eng.slot_adapter)) if lora else 0
        ),
        mem_stats=_compiled_mem_stats(compiled),
    )


def build_spec_serve_artifact(*, execute: bool = True) -> Artifact:
    """Lower + compile the SPECULATIVE serving round (ISSUE 19) — the
    jitted draft-propose + single k-verify + accept + rollback program
    (:func:`dtc_tpu.spec.serve_round`) the engine drives when
    ``serve.spec`` is on, over the engine's fixed slot-batch shapes
    (``decode_attention: fused_layers``, the one backend that keeps the
    k-verify greedy token-identical — ``check_spec_backend``).

    Its recompile fingerprint extends the compiled-shape invariant to
    the speculative path: between the two measured round executions a
    request is ADMITTED (target prefill + draft-rung prefill + both
    cache inserts), taking the in-flight batch from one slot to two —
    and the round, whose batch is the FIXED slot shape with idle slots
    frozen by ``remaining == 0``, must reuse the ONE executable
    (cold==1, steady==0). The draft rung itself is extracted at engine
    construction (zero-copy layer slice, embed/head shared by
    reference), so "loading the draft" is free of per-request compiles
    by construction; admission is the churn this entry audits."""
    from dtc_tpu.config.schema import ServeConfig, SpecConfig
    from dtc_tpu.serve.engine import ServingEngine
    from dtc_tpu.serve.request import Request
    from dtc_tpu.spec import serve_round

    model_cfg = audit_model_cfg(decode_attention="fused_layers")
    model = GPT(model_cfg)
    params = jax.jit(
        lambda r, x: model.init({"params": r, "dropout": r}, x, train=False)
    )(jax.random.PRNGKey(0), jnp.ones((1, model_cfg.max_seq_len), jnp.int32))[
        "params"
    ]
    spec_cfg = SpecConfig(spec_k=2, draft_layers=3)
    scfg = ServeConfig(slots=2, page_size=8, queue_depth=8, max_new_tokens=4,
                       prefill_bucket=8, spec=spec_cfg)
    eng = ServingEngine(model, params, scfg)
    toks = jnp.zeros((scfg.slots, 1), jnp.int32)
    rem = jnp.zeros((scfg.slots,), jnp.int32)
    args = (
        model, eng.draft_model, spec_cfg.spec_k, params, eng.draft_params,
        eng.cache, eng.draft_cache, toks, rem,
    )
    lowered = serve_round.lower(*args)
    stablehlo = lowered.as_text()
    compiled = lowered.compile()
    hlo = compiled.as_text()
    traced = serve_round.trace(*args)
    weak = sum(
        1 for v in traced.jaxpr.jaxpr.outvars
        if getattr(v.aval, "weak_type", False)
    )
    serve_state_bytes = {
        "params": sum(_local_nbytes(p) for p in jax.tree.leaves(params)),
        "cache": sum(_local_nbytes(c) for c in jax.tree.leaves(eng.cache)),
        # The resident rung's KV — the HBM cost speculation actually
        # adds (draft WEIGHTS are zero-copy views of the target's).
        "draft_cache": sum(
            _local_nbytes(c) for c in jax.tree.leaves(eng.draft_cache)
        ),
        # The rung's weights ARE entry parameters of the round's module
        # (the memory audit reconciles against those), even though
        # host-side they alias the target's buffers — counted here so
        # the decomposition reproduces the program, with the aliasing
        # recorded in the entry's own docs (PERF.md ISSUE-19 round).
        "draft_params": sum(
            _local_nbytes(p) for p in jax.tree.leaves(eng.draft_params)
        ),
    }
    cold = steady = None
    if execute:
        # Warm every helper an admission runs — target prefill, draft
        # prefill, both cache inserts, and the release path (the warm
        # request finishes at prefill: max_new_tokens=1 never enters a
        # spec round) — so the measured window isolates the round.
        eng.submit(Request(rid="warm", prompt=[1, 2, 3], max_new_tokens=1))
        eng.run(max_steps=8)
        # The round's only other dispatch is the host->device transfer of
        # the (slots,) int32 last-token / remaining vectors — a one-off
        # broadcast_in_dim the prefill-only warm request never reaches.
        jax.block_until_ready(
            jnp.asarray(np.zeros((scfg.slots,), np.int32))[:, None]
        )

        def call_once():
            eng.submit(Request(rid="a", prompt=[1, 2, 3], max_new_tokens=4))
            eng.step()  # admits "a", runs a round — the ONE compile
            return eng.cache

        def call_again(_):
            eng.submit(Request(rid="b", prompt=[4, 5], max_new_tokens=4))
            eng.step()  # admits "b": in-flight batch 1 -> 2, same round
            return eng.cache

        cold, steady = _measure_compiles(call_once, call_again)
    return Artifact(
        name="serve_spec",
        kind="serve",
        parallel=None,
        mesh_shape={},
        batch=scfg.slots,
        seq_len=model_cfg.max_seq_len,
        hlo_text=hlo,
        stablehlo_text=stablehlo,
        expected_donated=0,
        param_shapes=_param_shapes(params),
        weak_outputs=weak,
        n_layers=model_cfg.n_layers,
        moe_experts=0,
        compute_dtype=model_cfg.compute_dtype,
        cold_compiles=cold,
        steady_compiles=steady,
        comm_estimate=None,
        state_bytes=serve_state_bytes,
        state_dtypes={
            "params": sorted({
                _hlo_dtype(p) for p in jax.tree.leaves(params)
            }),
        },
        batch_bytes=_local_nbytes(toks) + _local_nbytes(rem),
        mem_stats=_compiled_mem_stats(compiled),
    )


def build_artifacts(
    modes: Sequence[str], *, decode: bool = False, serve: bool = False,
    execute: bool = True
) -> list[Artifact]:
    """Build artifacts for ``modes`` (+ the decode/serve entries when
    asked)."""
    arts = [build_train_artifact(m, execute=execute) for m in modes]
    if decode:
        arts.append(build_decode_artifact(execute=execute))
        # The ISSUE 11 megakernel flavor: layer loop inside the Pallas
        # grid instead of an XLA scan — its own committed baseline.
        arts.append(
            build_decode_artifact(
                execute=execute, decode_attention="fused_layers"
            )
        )
    if serve:
        # All serving flavors: the multi-tenant (lora) step, the
        # adapter-free step, the int8+megakernel step, AND the
        # speculative round (ISSUE 19) — distinct compiled programs,
        # each with its own committed baseline.
        arts.append(build_serve_artifact(execute=execute, lora=True))
        arts.append(build_serve_artifact(execute=execute, lora=False))
        arts.append(
            build_serve_artifact(execute=execute, lora=True, kv_int8=True)
        )
        arts.append(build_spec_serve_artifact(execute=execute))
    return arts
