"""AST lint: host synchronization inside the trainer's hot loop.

Every ``jax.device_get`` / ``block_until_ready`` / ``.item()`` in the
timed training loop is a device round-trip that serializes async dispatch
— the exact per-step sync the trainer was built to avoid (the reference
pays one every step; see train/trainer.py's module doc). The loop DOES
legitimately sync at telemetry boundaries: the log-window loss fetch, the
eval pass, the checkpoint health gate, the opt-in per-step
``sync_every_step`` timing mode. So the lint is not "no syncs" but "no
syncs outside a sanctioned boundary":

- the **hot loop** is any ``while``/``for`` whose condition/iterator
  mentions ``step`` (the trainer has exactly one: ``while step <
  train_cfg.steps``);
- a sync site is **sanctioned** when it sits in the TEST or BODY of an
  enclosing ``if`` whose condition mentions one of the boundary knobs
  below (``log_every``, ``checkpoint_every``, …) — an ``else`` branch is
  NOT sanctioned (it runs exactly when the boundary condition is false,
  i.e. every ordinary step). The knob's presence in the test source is
  the contract, so renaming one without updating this table fails loudly
  in tests/test_analysis.py;
- nested ``def``s are skipped: helpers like ``do_rollback``/``run_eval``
  are defined outside the loop and called only from boundaries.

Pure static analysis (``ast`` on source text): no JAX import, no trainer
import, so it lints any file — including the deliberately-broken fixture
the tests point it at.
"""

from __future__ import annotations

import ast
import dataclasses
import os

#: Call names that force a host<->device round trip.
SYNC_CALLS = frozenset({
    "device_get",          # jax.device_get(...)
    "block_until_ready",   # jax.block_until_ready(x) / x.block_until_ready()
    "item",                # scalar fetch: x.item()
    "asarray",             # np.asarray(device_array) — a transfer
})

#: Substrings that mark an enclosing ``if`` as a sanctioned telemetry /
#: control boundary. These are the trainer's boundary knobs: the log
#: window, periodic eval, periodic checkpoint, graceful-stop drain, and
#: the opt-in per-step timing sync.
SANCTIONED_CONDITIONS = (
    "log_every",
    "eval_every",
    "checkpoint_every",
    "stopping",
    "sync_every_step",
)

#: Default lint target: the trainer module itself.
TRAINER_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "train", "trainer.py",
)


@dataclasses.dataclass
class SyncSite:
    """One host-sync call found inside a hot loop."""

    path: str
    lineno: int
    call: str            # the SYNC_CALLS member that matched
    code: str            # unparsed call expression
    sanctioned: bool
    boundary: str | None  # condition text of the sanctioning ``if``


def _call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_hot_loop(node: ast.AST) -> bool:
    if isinstance(node, ast.While):
        probe = ast.unparse(node.test)
    elif isinstance(node, ast.For):
        probe = ast.unparse(node.iter)
    else:
        return False
    return "step" in probe


def _walk_loop(
    loop: ast.AST, path: str, sites: list[SyncSite]
) -> None:
    """Collect sync calls under ``loop``, threading down the innermost
    sanctioning ``if`` condition. Nested ``def``s are skipped (they only
    run when *called*, and the trainer calls them from boundaries).

    Sanctioning is branch-aware: only a marker-``if``'s TEST and BODY are
    gated by its condition — the ``else`` branch runs precisely when the
    boundary condition is false (every non-boundary step), so a sync
    there is the per-step regression the lint exists to catch and must
    NOT inherit the sanction."""

    def visit(node: ast.AST, boundary: str | None) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in SYNC_CALLS:
                sites.append(SyncSite(
                    path=path,
                    lineno=node.lineno,
                    call=name,
                    code=ast.unparse(node),
                    sanctioned=boundary is not None,
                    boundary=boundary,
                ))
        if isinstance(node, ast.If):
            cond = ast.unparse(node.test)
            gated = boundary
            if any(marker in cond for marker in SANCTIONED_CONDITIONS):
                gated = cond
            visit(node.test, gated)
            for child in node.body:
                visit(child, gated)
            for child in node.orelse:
                visit(child, boundary)  # else: condition is FALSE here
            return
        for child in ast.iter_child_nodes(node):
            visit(child, boundary)

    # Walk the whole loop node: a sync in the loop's own condition is a
    # per-iteration sync too, so it is included alongside the body.
    visit(loop, None)


def lint_source(source: str, path: str = "<string>") -> list[SyncSite]:
    """All sync sites inside hot loops of ``source``."""
    tree = ast.parse(source, filename=path)
    sites: list[SyncSite] = []
    seen: set[int] = set()

    def covered(node: ast.AST) -> list[ast.AST]:
        """Hot loops whose sites _walk_loop(node) collects — i.e. nested
        loops reachable WITHOUT crossing a def boundary (a hot loop
        inside a nested ``def`` is skipped by the walk, so it must stay
        eligible for its own top-level pass)."""
        out = []
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return out
        if _is_hot_loop(node):
            out.append(node)
        for child in ast.iter_child_nodes(node):
            out.extend(covered(child))
        return out

    for node in ast.walk(tree):
        if _is_hot_loop(node) and id(node) not in seen:
            # Mark covered nested loops as seen so they are not walked
            # twice (their sites already collected here).
            for sub in covered(node):
                seen.add(id(sub))
            _walk_loop(node, path, sites)
    return sites


def lint_file(path: str = TRAINER_PATH) -> list[SyncSite]:
    with open(path) as f:
        return lint_source(f.read(), path)


def unsanctioned(sites: list[SyncSite]) -> list[SyncSite]:
    return [s for s in sites if not s.sanctioned]
