"""Dtype-flow analysis over lowered StableHLO text (ISSUE 14).

Why StableHLO and not the optimized HLO: XLA's CPU pipeline LEGALIZES
small dtypes — a bf16 ``dot_general`` compiles to an f32 dot on this
host — so the only place a declared mixed-precision policy is faithfully
visible off-TPU is the backend-independent lowering. (The existing
``hlo.dot_dtype_counts`` learned this in PR 5; this module is the full
dtype-flow generalization.) Everything here is pure string processing —
no JAX imports — same contract as :mod:`dtc_tpu.analysis.hlo`.

What the parsers recover, and the rules built on them (rules.py
``audit_numerics``):

- **Matmul precision regions** (:func:`dot_signature_census`): every
  ``stablehlo.dot_general`` classified by its OPERAND dtypes. ``bf16 ×
  bf16`` and mixed ``bf16``-operand dots (an f32-accumulating
  ``preferred_element_type`` score dot has bf16 operands and an f32
  result — the MXU ideal, NOT a leak) are the bf16 region; ``f32 × f32``
  dots are legitimate only when their operands are natively f32 (the
  fp32-mandated softmax neighborhood's backward). An ``f32 × f32`` dot
  whose operand was just UPCAST from bf16 (``convert`` bf16->f32 feeding
  the dot) is the classic silent-upcast leak — someone widened a value
  specifically to run the matmul in f32 — and is counted separately as
  ``f32_upcast``.
- **fp32-mandatory regions** (:func:`fp32_region_census`):
  ``stablehlo.exponential`` (attention softmax + the CE loss's
  logsumexp — gelu lowers to tanh, not exp, so exp IS the softmax/loss
  fingerprint in this model family) and ``stablehlo.rsqrt`` (LayerNorm
  variance) must be f32 under EVERY policy — a bf16 instance is a
  dangerous downcast, not an optimization.
- **Cast placement** (:func:`scan_convert_census`): ``stablehlo.convert``
  ops INSIDE the layer scan's while body, with the param-cast subset
  identified by ORIGIN, not shape: a downcast whose operand chain
  (through reshape/transpose/broadcast) roots in a ``dynamic_slice`` of a
  loop-carried value is the per-layer fetch of a stacked parameter being
  cast EVERY layer — churn that hoists by storing params in the compute
  dtype (exactly what ``bf16_mixed`` does; under it the count must be
  zero). Shape matching is deliberately avoided: XLA sinks f32->bf16
  converts below gathers (the PR 11 false-positive class), and
  activation tensors can share shapes with param slices on small models.
"""

from __future__ import annotations

import dataclasses
import functools
import re

#: dtype token inside a tensor type, e.g. tensor<8x32x64xbf16> -> "bf16".
_TENSOR_DTYPE = re.compile(r"tensor<(?:[\d?]+x)*([a-z][a-z0-9]*)>")

#: one SSA use/def id: %123, %iterArg_17, %arg4, %cst_9.
_SSA_ID = re.compile(r"%[\w.]+")

#: an op line: `%res[:n] = stablehlo.op ...` (also matches func args etc.;
#: the op-name capture filters).
_OP_LINE = re.compile(r"^\s*(%[\w.]+)(?::\d+)?\s*=\s*stablehlo\.(\w+)\b(.*)$")

#: a call line: `%r:39 = func.call @None(...)` / `%r = call @_take(...)`.
#: jax OUTLINES the layer-scan body into private functions — the while's
#: do-region mostly slices the stacked params and calls these, so any
#: per-layer analysis must follow call edges.
_CALL_LINE = re.compile(
    r"^\s*(%[\w.]+)(?::\d+)?\s*=\s*(?:func\.)?call\s+@([\w.]+)\((.*)$"
)

#: a function definition line: `func.func private @None(%arg0: ..., ...`.
_FUNC_LINE = re.compile(r"^\s*func\.func\s+\w*\s*@([\w.]+)\(")


@dataclasses.dataclass
class StableOp:
    """One parsed StableHLO instruction."""

    result: str                 # SSA id of the result
    op: str                     # op name without the stablehlo. prefix
    operands: tuple[str, ...]   # SSA ids of the operands
    in_dtypes: tuple[str, ...]  # dtype tokens of the operand types
    out_dtype: str              # dtype token of the (first) result type
    in_scan_body: bool          # inside any while op's `do` region
    region: tuple[int, ...] = ()  # open-brace id path (SSA names are
    #                               region-scoped: %88 in one func is not
    #                               %88 in another)


@dataclasses.dataclass
class StableCall:
    """One ``call @fn(...)`` site."""

    callee: str
    operands: tuple[str, ...]
    in_scan_body: bool
    region: tuple[int, ...]


@dataclasses.dataclass
class Program:
    """A parsed StableHLO module: ops, the call graph, and each named
    function's body-region path (the key that scopes its ``%argN``
    names)."""

    ops: list[StableOp]
    calls: list[StableCall]
    funcs: dict[str, tuple[int, ...]]  # func name -> body region path

    def scan_funcs(self) -> set[str]:
        """Functions reachable from any while ``do`` region — i.e. code
        that runs ONCE PER LAYER (or per token, for decode's outer scan).
        jax outlines the scan body into ``@None``-style private funcs, so
        'inside the scan' must be computed over call edges, not just
        syntactic nesting."""
        by_region: list[tuple[tuple[int, ...], str]] = sorted(
            ((reg, name) for name, reg in self.funcs.items()),
            key=lambda t: len(t[0]), reverse=True,
        )

        def owner(region: tuple[int, ...]) -> str | None:
            for reg, name in by_region:
                if region[:len(reg)] == reg:
                    return name
            return None

        reached: set[str] = set()
        frontier = [c.callee for c in self.calls if c.in_scan_body]
        while frontier:
            f = frontier.pop()
            if f in reached:
                continue
            reached.add(f)
            for c in self.calls:
                if owner(c.region) == f:
                    frontier.append(c.callee)
        return reached


def _split_types(tail: str) -> tuple[tuple[str, ...], str]:
    """(operand dtypes, result dtype) from an op line's trailing
    ``: (types) -> type`` or ``: type`` annotation. The split is on the
    LAST top-level `` : `` so attribute payloads that mention types (the
    ``algorithm = <lhs_precision_type = bf16, ...>`` attribute on
    accumulation-controlled dots) never pollute the signature."""
    idx = tail.rfind(" : ")
    if idx < 0:
        return (), ""
    sig = tail[idx + 3:]
    if "->" in sig:
        ins, _, outs = sig.partition("->")
    else:
        # Same-type elementwise shorthand: `%a = stablehlo.rsqrt %b : tensor<..>`
        ins, outs = sig, sig
    in_dt = tuple(_TENSOR_DTYPE.findall(ins))
    out_m = _TENSOR_DTYPE.findall(outs)
    return in_dt, (out_m[0] if out_m else "")


@functools.lru_cache(maxsize=8)
def parse_program(txt: str) -> Program:
    """Parse every ``stablehlo.*`` instruction, call site, and function
    definition, with scan-body membership.

    lru_cached on the raw text: one audited entry's censuses + the
    fingerprint would otherwise re-regex the same multi-MB dump 6+ times
    (hashing the string once is noise next to one parse). Callers treat
    the returned Program as read-only — every consumer here does.

    Scan bodies are tracked syntactically: a ``stablehlo.while`` opens a
    ``cond { ... } do { ... }`` region pair; everything inside a ``do``
    region (nested whiles included — decode's token scan wraps the layer
    scan) is ``in_scan_body``. Brace depth per line is enough because the
    MLIR printer never splits a region brace across tokens. Code the scan
    body reaches through ``call`` edges is resolved separately
    (:meth:`Program.scan_funcs`)."""
    ops: list[StableOp] = []
    calls: list[StableCall] = []
    funcs: dict[str, tuple[int, ...]] = {}
    body_depth = 0                 # nesting count of open `do {` regions
    # Stack of (unique id, is_do_region) per open brace; the id path is
    # the op's region key for SSA-name scoping.
    open_braces: list[tuple[int, bool]] = []
    next_id = 0
    for line in txt.splitlines():
        m = _OP_LINE.match(line)
        pending_func = None
        if m:
            result, op, tail = m.group(1), m.group(2), m.group(3)
            # Operand ids are the SSA uses BEFORE the type annotation (the
            # result id is already consumed by the line regex).
            idx = tail.rfind(" : ")
            head = tail[:idx] if idx >= 0 else tail
            in_dt, out_dt = _split_types(tail)
            ops.append(StableOp(
                result=result,
                op=op,
                operands=tuple(_SSA_ID.findall(head)),
                in_dtypes=in_dt,
                out_dtype=out_dt,
                in_scan_body=body_depth > 0,
                region=tuple(bid for bid, _ in open_braces),
            ))
        else:
            mc = _CALL_LINE.match(line)
            if mc:
                head = mc.group(3)
                idx = head.rfind(" : ")
                if idx >= 0:
                    head = head[:idx]
                calls.append(StableCall(
                    callee=mc.group(2),
                    operands=tuple(_SSA_ID.findall(head)),
                    in_scan_body=body_depth > 0,
                    region=tuple(bid for bid, _ in open_braces),
                ))
            else:
                mf = _FUNC_LINE.match(line)
                if mf:
                    pending_func = mf.group(1)
        # Brace bookkeeping, in source order, AFTER the line's op (the
        # while's own line sits outside its regions). The MLIR printer
        # writes `cond {` / `} do {` / `}` — so `} do {` first pops the
        # cond brace, then pushes the body brace.
        for tok in re.finditer(r"[{}]", line):
            if tok.group() == "{":
                is_do = line[:tok.start()].rstrip().endswith("do")
                open_braces.append((next_id, is_do))
                next_id += 1
                if is_do:
                    body_depth += 1
                if pending_func is not None:
                    # The first `{` of a func.func line opens its body.
                    funcs[pending_func] = tuple(bid for bid, _ in open_braces)
                    pending_func = None
            elif open_braces:
                if open_braces.pop()[1]:
                    body_depth -= 1
    return Program(ops=ops, calls=calls, funcs=funcs)


def parse_ops(txt: str) -> list[StableOp]:
    """All parsed instructions (see :func:`parse_program`)."""
    return parse_program(txt).ops


def _def_map(ops: list[StableOp]) -> dict[tuple, StableOp]:
    """(region path, result id) -> defining op. SSA value names are
    REGION-scoped in MLIR text (`%88` in the main func and `%88` inside a
    private backward func are different values), so lookups must walk the
    use site's region path from innermost outward — :func:`_lookup`."""
    return {(o.region, o.result): o for o in ops}


def _lookup(defs: dict, user: StableOp, operand: str) -> StableOp | None:
    """Resolve an operand id visible at ``user``'s region path: innermost
    scope first, then each enclosing region. Region-boundary names with
    no def anywhere (`%arg*` block args, `%iterArg*` loop carries) return
    None — which is exactly what the origin walks key on."""
    for k in range(len(user.region), -1, -1):
        d = defs.get((user.region[:k], operand))
        if d is not None:
            return d
    return None


#: ops the origin walk for casts looks THROUGH (layout/shape plumbing).
_TRANSPARENT = ("reshape", "transpose", "broadcast_in_dim", "convert")


def dot_signature_census(txt: str) -> dict[str, int]:
    """Counts of ``dot_general`` ops by operand-dtype signature:

    - ``bf16_bf16``: both operands bf16 (result may be bf16 or an f32
      accumulation — both are the bf16 region).
    - ``bf16_mixed``: exactly one bf16 operand.
    - ``f32_f32``: both operands natively f32 (legitimate inside the
      fp32-mandated softmax/loss neighborhood).
    - ``f32_transpose``: exactly ONE operand is a direct bf16->f32
      ``convert``, the other natively f32 — the autodiff transpose of an
      f32-accumulating bf16 dot (the f32 cotangent of the score dot
      contracts against an upcast of the bf16 primal; jax widens the
      primal so dq/dk accumulate in f32 before downcasting). Benign —
      desirable, even — and baseline-pinned so a count change surfaces.
    - ``f32_upcast``: BOTH operands are direct bf16->f32 converts — the
      cast-then-dot leak (a value pair widened specifically to run the
      matmul in f32; no accumulation argument applies when both sides
      were bf16 to begin with).
    - ``other``: anything else (int dots, f64 — the f64 rule catches
      those separately).
    """
    ops = parse_ops(txt)
    defs = _def_map(ops)
    out = {"bf16_bf16": 0, "bf16_mixed": 0, "f32_f32": 0,
           "f32_transpose": 0, "f32_upcast": 0, "other": 0}
    for o in ops:
        if o.op != "dot_general":
            continue
        dts = o.in_dtypes[:2]
        n_bf16 = sum(1 for d in dts if d == "bf16")
        if n_bf16 == 2:
            out["bf16_bf16"] += 1
        elif n_bf16 == 1:
            out["bf16_mixed"] += 1
        elif tuple(dts) == ("f32", "f32"):
            upcasts = 0
            for operand in o.operands[:2]:
                d = _lookup(defs, o, operand)
                if d is not None and d.op == "convert" and (
                    d.in_dtypes[:1] == ("bf16",) and d.out_dtype == "f32"
                ):
                    upcasts += 1
            key = {0: "f32_f32", 1: "f32_transpose", 2: "f32_upcast"}[upcasts]
            out[key] += 1
        else:
            out["other"] += 1
    return out


#: fp32-mandatory op set: softmax/logsumexp exponentials, LN-variance
#: rsqrt. (sqrt is NOT in the set — AdamW's denominator sqrt is f32 by
#: the optimizer-state rule, and grad-clip's norm sqrt follows the grad
#: dtype by design.)
FP32_MANDATORY_OPS = ("exponential", "rsqrt")


def fp32_region_census(txt: str) -> dict[str, dict[str, int]]:
    """Result-dtype counts of the fp32-mandatory ops, e.g.
    ``{"exponential": {"f32": 3}, "rsqrt": {"f32": 3}}`` — a bf16 key
    appearing under either op is a dangerous downcast (rules.py errors)."""
    out: dict[str, dict[str, int]] = {op: {} for op in FP32_MANDATORY_OPS}
    for o in parse_ops(txt):
        if o.op in out:
            row = out[o.op]
            row[o.out_dtype] = row.get(o.out_dtype, 0) + 1
    return out


def _origin(defs: dict, op: StableOp, operand: str) -> tuple[StableOp | None, str | None]:
    """Walk ``operand`` back through shape plumbing
    (reshape/transpose/broadcast/convert); return (last defining op seen,
    final root id). A root with no def is a region-boundary value (block
    arg / loop carry)."""
    last: StableOp | None = None
    cur_op: StableOp | None = op
    cur: str | None = operand
    for _ in range(8):  # bounded walk; chains are short
        d = _lookup(defs, cur_op, cur) if (cur and cur_op) else None
        if d is None:
            break
        last = d
        if d.op in _TRANSPARENT or d.op == "dynamic_slice":
            cur_op = d
            cur = d.operands[0] if d.operands else None
            if d.op == "dynamic_slice":
                break
            continue
        break
    return last, cur


def scan_convert_census(txt: str) -> dict[str, int]:
    """Convert ops that run ONCE PER LAYER — inside a while (scan) body,
    or inside a function the scan body calls (jax outlines the per-layer
    Block computation into ``@None``-style private funcs) — by direction,
    plus the param-cast churn subset:

    - ``f32_to_bf16`` / ``bf16_to_f32``: all per-layer converts by
      direction (the LN/softmax island boundaries legitimately cast every
      layer — these counts are baseline-pinned context, not findings).
    - ``param_slice_downcast``: f32->bf16 converts of a PER-LAYER
      PARAMETER SLICE — identified by origin, not shape: either the
      convert's operand chain roots in a ``dynamic_slice`` of a
      loop-carried value (the stacked-param fetch, inline form), or the
      convert sits in a scan-called function and its operand chain roots
      in a block arg whose CALL-SITE operand is such a slice. This is the
      cast churn the lint exists for: the same parameter bytes re-cast L
      times per step instead of once; storing params in the compute dtype
      (``bf16_mixed``) removes the cast entirely, which is why the count
      must be ZERO under that policy.
    """
    prog = parse_program(txt)
    defs = _def_map(prog.ops)
    scan_funcs = prog.scan_funcs()
    func_regions = {reg: name for name, reg in prog.funcs.items()}

    def in_scan(region: tuple[int, ...], syntactic: bool) -> bool:
        if syntactic:
            return True
        for k in range(len(region), 0, -1):
            name = func_regions.get(region[:k])
            if name is not None:
                return name in scan_funcs
        return False

    # Per scan-body call site: the set of arg positions fed by a
    # dynamic_slice of a loop carry (the per-layer param fetch). Unioned
    # per callee — good enough, since healthy activations never alias a
    # param position.
    slice_args: dict[str, set[int]] = {}
    for c in prog.calls:
        if not c.in_scan_body:
            continue
        fake = StableOp("%_", "call", c.operands, (), "", True, c.region)
        for i, operand in enumerate(c.operands):
            last, root = _origin(defs, fake, operand)
            if (
                last is not None and last.op == "dynamic_slice"
                and root is not None
                and _lookup(defs, last, root) is None
            ):
                slice_args.setdefault(c.callee, set()).add(i)

    out = {"f32_to_bf16": 0, "bf16_to_f32": 0, "param_slice_downcast": 0}
    for o in prog.ops:
        if o.op != "convert" or not in_scan(o.region, o.in_scan_body):
            continue
        src = o.in_dtypes[0] if o.in_dtypes else ""
        dst = o.out_dtype
        if (src, dst) == ("bf16", "f32"):
            out["bf16_to_f32"] += 1
            continue
        if (src, dst) != ("f32", "bf16"):
            continue
        out["f32_to_bf16"] += 1
        last, root = _origin(defs, o, o.operands[0] if o.operands else None)
        if last is not None and last.op == "dynamic_slice" and (
            root is not None and _lookup(defs, last, root) is None
        ):
            # Inline form: slice-of-carry converted in the body itself.
            out["param_slice_downcast"] += 1
            continue
        if root is None or not re.fullmatch(r"%arg\d+", root or ""):
            continue
        # Outlined form: the convert's root is a block arg of the func it
        # lives in; flag when the call site feeds that position a
        # slice-of-carry.
        owner = None
        for k in range(len(o.region), 0, -1):
            name = func_regions.get(o.region[:k])
            if name is not None:
                owner = name
                break
        if owner in slice_args and int(root[4:]) in slice_args[owner]:
            out["param_slice_downcast"] += 1
    return out


def numerics_fingerprint(
    stablehlo_text: str,
    *,
    precision: str = "fp32",
    loss_dtype: str = "",
    state_dtypes: dict[str, list[str]] | None = None,
    collective_dtypes: dict[str, dict[str, int]] | None = None,
) -> dict:
    """The drift-gated numerics summary of one entry (report.py commits
    it as ``<entry>.numerics.json``). Everything in here is deterministic
    graph structure — counts, not timings."""
    return {
        "precision": precision,
        "dots": dot_signature_census(stablehlo_text),
        "fp32_regions": fp32_region_census(stablehlo_text),
        "scan_converts": scan_convert_census(stablehlo_text),
        "loss_dtype": loss_dtype,
        "state_dtypes": state_dtypes or {},
        "collective_dtypes": collective_dtypes or {},
    }
