"""Text-level parsing of XLA's optimized HLO dumps.

Everything here is pure string processing — no JAX imports — so the
parsers unit-test without a backend and run on HLO text captured
anywhere (CPU audit runs, TPU dumps shipped home from a pod).

HLO text format notes (what the regexes lean on):

- One instruction per line: ``%name = <type> <op>(operands), attrs``.
  The result type is either a single ``dtype[dims]{layout}`` or a tuple
  ``(dtype[dims]{..}, ...)`` for variadic ops (a multi-operand
  all-reduce produces a tuple result — its bytes are the SUM of the
  element buffers).
- The donation map lives on the ``HloModule`` header line as
  ``input_output_alias={ {out_idx}: (param, {param_idx}, may-alias),.. }``
  — one entry per aliased (donated) buffer.
- XLA's CPU pipeline DECOMPOSES reduce-scatter into all-reduce +
  partition-id-indexed dynamic-slice, so CPU audits accept the
  ``partition-id`` fingerprint where a TPU dump would show the literal
  instruction (same tolerance tests/test_collectives_hlo.py has always
  applied).
"""

from __future__ import annotations

import math
import re
from collections import Counter
from typing import Any

#: The cross-device ops the census tracks (collective-permute carries
#: pipeline/ring traffic; the other four are the GSPMD workhorses).
COLLECTIVE_OPS = (
    "all-to-all",
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
)

# One instruction per line: "%name = <type> <op>(".  The type can be a
# tuple (contains spaces), so match lazily up to the op name.  This is
# the exact expression tests/test_collectives_hlo.py pinned in round 5;
# it now lives here so the test and the audit share one definition.
_INSTR = re.compile(
    r"%[\w.-]+ = .*? (" + "|".join(COLLECTIVE_OPS) + r")\("
)

#: result-type capture for one collective line: everything between "= "
#: and " <op>(" — a single typed buffer or a tuple of them.
_RESULT = re.compile(
    r"%[\w.-]+ = (.*?) (" + "|".join(COLLECTIVE_OPS) + r")\("
)

#: a single typed buffer inside a result type, e.g. "f32[8,32,64]".
_BUFFER = re.compile(r"(pred|[a-z]+\d+)\[([\d,]*)\]")

#: one "{out}: (param, {idx}, kind)" entry of the header's alias map.
_ALIAS_ENTRY = re.compile(r"\{[\d,\s]*\}:\s*\(\d+,\s*\{[\d,\s]*\}")

_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1, "u8": 1, "f8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def dtype_bytes(dtype: str) -> int:
    return _DTYPE_BYTES.get(dtype, 4)


def _buffer_bytes(type_text: str) -> int:
    """Total bytes of a result type (sums tuple elements)."""
    total = 0
    for dtype, dims in _BUFFER.findall(type_text):
        n = 1
        if dims:
            n = math.prod(int(d) for d in dims.split(","))
        total += n * dtype_bytes(dtype)
    return total


#: result-type capture for a custom-call line (the Pallas lowering:
#: kernels land as ``custom-call(...), custom_call_target="tpu_custom_call"``).
_CC_RESULT = re.compile(r"%[\w.-]+ = (.*?) custom-call\(")

#: the XLA:TPU custom-call target every Pallas kernel lowers to.
PALLAS_CUSTOM_CALL_TARGET = "tpu_custom_call"

#: kernel-name tokens of the ISSUE 12 overlap ring kernels
#: (ops/overlap_collectives.py). The lowering stamps the kernel function
#: name onto the custom-call line (``kernel_name = "_overlap_ag_..."``),
#: which is what lets the census tell RING transport apart from every
#: other Pallas kernel in the module (flash attention, decode, MoE) —
#: accepting any tpu_custom_call would make the overlapped entries'
#: required-transport check vacuous on TPU.
OVERLAP_KERNEL_TOKENS = ("overlap_ag_matmul", "overlap_rs_matmul")


def overlap_kernel_custom_calls(txt: str) -> dict[str, int]:
    """``{"count": n, "bytes": b}`` over the overlap RING kernels only —
    tpu_custom_call lines carrying an OVERLAP_KERNEL_TOKENS name. If a
    backend ever stops printing kernel names in HLO text, this returns 0
    and the overlapped census check FAILS LOUDLY (the right direction:
    a parser gap must never read as 'ring present')."""
    count = tot = 0
    for line in txt.splitlines():
        if f'custom_call_target="{PALLAS_CUSTOM_CALL_TARGET}"' not in line:
            continue
        if not any(tok in line for tok in OVERLAP_KERNEL_TOKENS):
            continue
        count += 1
        m = _CC_RESULT.search(line)
        if m:
            tot += _buffer_bytes(m.group(1))
    return {"count": count, "bytes": tot}


def pallas_custom_calls(txt: str) -> dict[str, int]:
    """``{"count": n, "bytes": b}`` over the module's Pallas custom-calls
    (``tpu_custom_call`` targets; bytes sum each call's result buffers).

    The overlapped-collectives kernels (ops/overlap_collectives.py,
    ISSUE 12) move the FSDP ring INSIDE fused kernels, so a TPU lowering
    of the overlapped step has no named all-gather/reduce-scatter
    instructions to census — this is the fingerprint the census rules
    accept in their place (remote-copy DMAs never lower to named HLO
    collectives)."""
    count = tot = 0
    for line in txt.splitlines():
        if f'custom_call_target="{PALLAS_CUSTOM_CALL_TARGET}"' not in line:
            continue
        count += 1
        m = _CC_RESULT.search(line)
        if m:
            tot += _buffer_bytes(m.group(1))
    return {"count": count, "bytes": tot}


def collective_counts(txt: str) -> Counter:
    """Per-op instruction counts — the round-5 test's ``_collectives``."""
    return Counter(_INSTR.findall(txt))


def collective_census(txt: str) -> dict[str, dict[str, int]]:
    """Per-op ``{"count": n, "bytes": b}`` over the module.

    ``bytes`` sums each instruction's RESULT buffer (post-gather size for
    all-gather, full size for all-reduce, shard size for reduce-scatter)
    — a deterministic graph property suited to baselining, NOT a wire-
    traffic model (ring-algorithm wire bytes differ by the usual
    ``(n-1)/n`` factors; the census cross-check in rules.py applies
    those tolerances).
    """
    census: dict[str, dict[str, int]] = {}
    for m in _RESULT.finditer(txt):
        type_text, op = m.group(1), m.group(2)
        row = census.setdefault(op, {"count": 0, "bytes": 0})
        row["count"] += 1
        row["bytes"] += _buffer_bytes(type_text)
    # Pallas kernels (ISSUE 12 overlapped collectives): counted as their
    # own census row — remote-copy kernels never lower to named HLO
    # collective ops, and a census blind to them would read an overlapped
    # TPU module as collective-free. Row omitted when zero, so every
    # pre-existing (kernel-free) baseline stays byte-identical.
    cc = pallas_custom_calls(txt)
    if cc["count"]:
        census["pallas_custom_call"] = cc
    return census


def collective_dtype_census(txt: str) -> dict[str, dict[str, int]]:
    """Per-collective result-DTYPE counts, e.g.
    ``{"all-reduce": {"f32": 2, "bf16": 1}}`` — the numerics pass' view
    of what rides the wire (ISSUE 14). Under a declared-fp32 policy a
    bf16 collective is a downcast leak; under ``bf16_mixed`` the bf16
    gradient all-reduce is the documented wire choice and the baseline
    pins the split. Tuple results contribute one count per element
    buffer (the combined-op flattening rule every parser here follows).
    NOTE: XLA's CPU pipeline PROMOTES bf16 all-reduces to f32
    (AllReducePromotion), so CPU baselines show the promoted dtype — a
    TPU dump shows the true wire dtype; same env-scoping as the rest of
    the census."""
    out: dict[str, dict[str, int]] = {}
    for m in _RESULT.finditer(txt):
        type_text, op = m.group(1), m.group(2)
        row = out.setdefault(op, {})
        for dtype, _dims in _BUFFER.findall(type_text):
            row[dtype] = row.get(dtype, 0) + 1
    return out


#: a dot line in OPTIMIZED HLO: `%name = <type> dot(<operands>), ...`.
_DOT_LINE = re.compile(r"%[\w.-]+ = (\S+) dot\((.*?)\)(.*)$")

#: the accumulation-algorithm attribute some TPU dots carry, e.g.
#: `algorithm=dot_bf16_bf16_f32` (bf16 inputs, fp32 accumulation).
_DOT_ALGORITHM = re.compile(r"algorithm=([\w]+)")


def dot_entries(txt: str) -> list[dict[str, Any]]:
    """Structured view of every ``dot`` in OPTIMIZED HLO text:
    ``{"result_dtype", "operand_dtypes", "algorithm", "op_name"}``.

    This is the TPU-dump counterpart of the StableHLO dot census in
    :mod:`dtc_tpu.analysis.numerics`: on CPU the optimized HLO is
    useless for dtype policy (the backend legalizes bf16 dots to f32 —
    the reason the numerics rules read StableHLO), but a TPU dump keeps
    bf16 and adds the ``algorithm=`` attribute naming the accumulation
    dtype — ``dot_bf16_bf16_f32`` is the MXU's bf16-in/fp32-accumulate
    contract, which a dtype-region audit must NOT misread as an fp32
    upcast (tests/test_analysis.py pins that case on a fabricated
    dump)."""
    out = []
    for line in txt.splitlines():
        m = _DOT_LINE.search(line)
        if m is None:
            continue
        result_type, operands, attrs = m.groups()
        res = _BUFFER.search(result_type)
        alg = _DOT_ALGORITHM.search(attrs)
        scope = _LINE_OP_NAME.search(line)
        out.append({
            "result_dtype": res.group(1) if res else "",
            "operand_dtypes": tuple(
                d for d, _ in _BUFFER.findall(operands)
            ),
            "algorithm": alg.group(1) if alg else "",
            "op_name": scope.group(1) if scope else "",
        })
    return out


def all_gather_shapes(txt: str) -> list[str]:
    """Result shapes of every all-gather, as ``"f32[8,32,64]"`` strings —
    the exact format the round-5 forbidden-gather regexes match. A
    variadic (combined) all-gather's tuple result contributes one entry
    per element buffer: XLA's all-gather combiner routinely merges
    gathers on TPU, and a forbidden shape hidden inside a combined op
    must still be visible to the rules."""
    return [
        f"{d}[{','.join(str(x) for x in dims)}]"
        for d, dims in all_gather_dims(txt)
    ]


#: op_name metadata on an instruction line (XLA records the named-scope
#: path of the op that produced/consumes the instruction).
_LINE_OP_NAME = re.compile(r'op_name="([^"]+)"')


def all_gather_entries(
    txt: str,
) -> list[tuple[str, tuple[int, ...], str]]:
    """(dtype, dims, op_name) of every all-gather result buffer — the
    scope-aware form of :func:`all_gather_dims` (op_name '' when the
    instruction carries no metadata). The overlapped-collectives rule
    keys on the SCOPE: a rank-2+ gather whose op_name path runs through
    the layer scan ("/blocks/") is serialized per-layer traffic the ring
    should have replaced, while shape-identical gathers at the head/embed
    are legitimate (shape-only matching false-positives on the tiny
    audit model, where lm_head == fc1 shapes — see rules.py)."""
    out = []
    for line in txt.splitlines():
        m = _RESULT.search(line)
        if not m or m.group(2) != "all-gather":
            continue
        scope_m = _LINE_OP_NAME.search(line)
        scope = scope_m.group(1) if scope_m else ""
        for d, dims_txt in _BUFFER.findall(m.group(1)):
            dims = tuple(int(x) for x in dims_txt.split(",")) if dims_txt else ()
            out.append((d, dims, scope))
    return out


def all_gather_dims(txt: str) -> list[tuple[str, tuple[int, ...]]]:
    """(dtype, dims) of every all-gather result buffer — the structured
    form the forbidden-shape rule compares against parameter shapes.
    Tuple results (combined all-gathers) are flattened to their element
    buffers, same as ``_buffer_bytes`` sums them."""
    out = []
    for m in _RESULT.finditer(txt):
        if m.group(2) != "all-gather":
            continue
        for d, dims_txt in _BUFFER.findall(m.group(1)):
            dims = tuple(int(x) for x in dims_txt.split(",")) if dims_txt else ()
            out.append((d, dims))
    return out


def input_output_alias_count(txt: str) -> int:
    """Number of aliased (donated) buffers in the module header.

    The entry pattern is applied to the whole ``HloModule`` line: the
    alias map's braces nest (``{out}: (param, {idx}, kind)``), so there is
    no clean non-greedy way to isolate the map itself — but the entry
    shape is specific enough to count directly, and nothing else on the
    header line matches it."""
    header = txt.split("\n", 1)[0]
    if "input_output_alias" not in header:
        return 0
    return len(_ALIAS_ENTRY.findall(header))


def has_partition_id(txt: str) -> bool:
    """CPU fingerprint of a decomposed reduce-scatter (see module doc)."""
    return "partition-id" in txt


def count_dtype(txt: str, dtype: str) -> int:
    """Occurrences of ``dtype[`` — e.g. ``count_dtype(txt, "f64")``."""
    return txt.count(f"{dtype}[")


def dot_dtype_counts(stablehlo_text: str) -> dict[str, int]:
    """bf16 vs f32 ``dot_general`` counts in LOWERED StableHLO text.

    The bf16-region audit runs on the lowering, not the compiled module:
    the CPU backend legalizes/promotes small dtypes (and check-fails on
    some bf16 collectives — see tests/conftest.py), so only the
    backend-independent StableHLO faithfully shows which matmuls the
    model declared in bf16. An unintended upcast shows up here as an
    f32 dot_general replacing a bf16 one — a count change the baseline
    drift gate flags even when no rule hard-fails.
    """
    bf16 = f32 = 0
    for line in stablehlo_text.splitlines():
        if "dot_general" not in line:
            continue
        if "bf16" in line:
            bf16 += 1
        elif "f32" in line:
            f32 += 1
    return {"bf16_dots": bf16, "f32_dots": f32}
