"""The rule engine: severity-ranked findings over lowered artifacts.

Five families, each encoding an invariant the paper's comparison (and the
round-5 one-off tests) depend on:

1. **collective census** — each mode must emit the collectives its design
   requires (DP: gradient all-reduce; TP: activation all-reduce + param
   all-gather; FSDP: param all-gather + grad reduce-scatter, accepting the
   CPU backend's all-reduce+partition-id decomposition; EP/Ulysses:
   all-to-all) and must NOT emit the replicate-and-slice fallbacks: a
   full-parameter all-gather outside FSDP, a stacked-parameter all-gather
   inside FSDP (ZeRO's memory win hoisted out of the layer scan), a
   full-expert-tensor all-gather under EP. Census bytes are cross-checked
   against ``utils/metrics.comm_bytes_per_step`` within a wide tolerance
   (graph result-bytes vs ring wire-bytes differ by (n-1)/n-class factors
   and CPU decomposition; outside 8x either way something is structurally
   wrong — warn, the baselines pin the exact numbers).
2. **donation audit** — every donated buffer must appear in the module's
   ``input_output_alias`` map (the PR 1 out-shardings regression class:
   GSPMD normalizes a degenerate out-spec, the signature stops matching,
   the donation silently drops and peak memory doubles).
3. **dtype/promotion audit** — no f64 anywhere (CPU silently defaults to
   f64 for stray Python floats under x64; TPU would either crash or
   emulate at 1/10 speed), no weak-typed outputs (weak types re-trace on
   the next call — the canonicalize_state_placement bug class), and a
   declared-bf16 model must actually lower bf16 matmuls.
4. **host-sync lint** — no device round-trips inside the trainer's timed
   loop outside sanctioned boundaries (see :mod:`hostsync`).
5. **recompile fingerprint** — a compiled entry point executes from ONE
   executable: cold exactly one backend compile, steady zero.
"""

from __future__ import annotations

import dataclasses

from dtc_tpu.analysis import hlo
from dtc_tpu.analysis.hostsync import lint_file, unsanctioned
from dtc_tpu.analysis.lowering import Artifact

#: Finding severities, gate-relevant order. Only ``error`` fails the audit.
SEVERITIES = ("error", "warn", "info")


@dataclasses.dataclass
class Finding:
    rule: str        # family.check, e.g. "census.required_collective"
    severity: str    # error | warn | info
    artifact: str    # entry-point name, or "trainer" for the source lint
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


#: Per-mode required collectives (presence; the baseline pins counts).
#: FSDP's reduce-scatter is special-cased below for the CPU decomposition.
REQUIRED_COLLECTIVES: dict[str, tuple[str, ...]] = {
    "train_dp": ("all-reduce",),
    "train_tp": ("all-reduce", "all-gather"),
    "train_fsdp": ("all-gather",),
    "train_ep": ("all-to-all",),
    "train_ep_sort": ("all-to-all",),
    "train_ulysses": ("all-to-all",),
    # The DP×FSDP×TP overlapped mode keeps the Megatron activation
    # all-reduces (the explicit psums); its ring transport is checked
    # separately below.
    "train_3d": ("all-reduce",),
}

#: ISSUE 12 entries whose FSDP traffic rides the overlap ring: the
#: census must see the ring TRANSPORT — collective-permute (decomposed /
#: CPU lowering) or the Pallas custom-calls (fused TPU kernels; the
#: remote-copy DMAs never lower to named HLO collectives) — and must NOT
#: see the serialized per-layer kernel all-gathers the ring replaces.
OVERLAPPED_ENTRIES = ("train_fsdp_overlapped", "train_3d")

#: census ops that can carry an overlapped entry's ring traffic at the
#: XLA level; the fused-kernel form is checked via
#: ``hlo.overlap_kernel_custom_calls`` (kernel-NAME matched — a generic
#: tpu_custom_call count would be satisfied by flash/decode kernels and
#: make the check vacuous on TPU).
RING_TRANSPORT_OPS = ("collective-permute",)

#: Census-bytes vs comm_bytes_per_step cross-check tolerance (ratio band).
CROSS_CHECK_BAND = (1 / 8, 8.0)


def _err(rule: str, art: str, msg: str) -> Finding:
    return Finding(rule, "error", art, msg)


def _warn(rule: str, art: str, msg: str) -> Finding:
    return Finding(rule, "warn", art, msg)


# -- family 1: collective census ------------------------------------------

def audit_census(a: Artifact) -> list[Finding]:
    out: list[Finding] = []
    census = hlo.collective_census(a.hlo_text)
    counts = {op: row["count"] for op, row in census.items()}

    for op in REQUIRED_COLLECTIVES.get(a.name, ()):
        if counts.get(op, 0) == 0:
            out.append(_err(
                "census.required_collective", a.name,
                f"{a.name} lost its {op}s — the partitioner fell back to a "
                f"replicated program (census: {counts})",
            ))
    if a.name == "train_fsdp":
        # ZeRO-3 gradient reduce-scatter: literal instruction, or the CPU
        # pipeline's all-reduce + partition-id dynamic-slice decomposition.
        # Demand the partition-id fingerprint so a plain replicated
        # all-reduce (DP, not ZeRO) cannot pass.
        if counts.get("reduce-scatter", 0) == 0 and not (
            counts.get("all-reduce", 0) > 0 and hlo.has_partition_id(a.hlo_text)
        ):
            out.append(_err(
                "census.required_collective", a.name,
                "FSDP lost its gradient reduce-scatter (neither the literal "
                f"instruction nor the all-reduce+partition-id decomposition "
                f"is present; census: {counts})",
            ))
    if a.name in OVERLAPPED_ENTRIES:
        # Both the param gathers AND the grad reduce-scatter ride the
        # ring here: the transport must be present in one of its two
        # lowered forms — collective-permute (decomposed) or the overlap
        # KERNELS' custom-calls (name-matched; any other Pallas kernel
        # does not count) — or the overlap silently degraded to a
        # replicated program.
        ring_kernels = hlo.overlap_kernel_custom_calls(a.hlo_text)
        if not (
            any(counts.get(op, 0) for op in RING_TRANSPORT_OPS)
            or ring_kernels["count"]
        ):
            out.append(_err(
                "census.required_collective", a.name,
                f"{a.name} lost its overlap ring — neither "
                "collective-permute (decomposed transport) nor the "
                "overlap ring kernels' custom-calls are present "
                f"(census: {counts})",
            ))

    out.extend(_audit_gathers(a))
    out.extend(_cross_check_bytes(a, census))
    return out


def _audit_gathers(a: Artifact) -> list[Finding]:
    """The forbidden-gather rules — replicate-and-slice fingerprints."""
    out: list[Finding] = []
    gathers = hlo.all_gather_dims(a.hlo_text)
    param_shapes = {(d, dims) for d, dims in a.param_shapes if len(dims) >= 2}

    if a.kind == "train" and a.parallel != "fsdp":
        # "No full-parameter all-gather outside FSDP": a gather landing a
        # buffer exactly shaped like the FULL form of a param that is
        # declared SHARDED means the partitioner is rebuilding replicated
        # weights every step. (Replicated params never enter
        # ``param_shapes`` — their gradients are legitimately assembled
        # by param-shaped gathers; see lowering._sharded_param_shapes.)
        bad = [g for g in gathers if g in param_shapes]
        if bad:
            out.append(_err(
                "census.full_param_gather", a.name,
                f"full-parameter all-gather(s) outside FSDP: "
                f"{[f'{d}{list(dims)}' for d, dims in bad[:4]]}",
            ))
    if a.parallel == "fsdp":
        # Inside FSDP, per-layer rank-2 gathers at use are the design; a
        # gather landing EXACTLY a stacked param's full (L, ...) shape
        # means XLA hoisted the whole parameter out of the layer scan and
        # the ZeRO memory win is gone. (dtype, dims) membership, not a
        # bare leading-dim test: incidental rank-3 buffers (the wte
        # scatter-add's s32 index gather) can share the leading dim with
        # n_layers on small meshes (ISSUE 12 found it at data=4). The
        # accepted dtypes are the param dtype AND the model's compute
        # dtype — XLA routinely sinks the fp32->bf16 convert below the
        # gather to halve wire bytes, so a hoisted gather may land the
        # CAST of a stacked param.
        hlo_compute = {
            "float32": "f32", "bfloat16": "bf16", "float16": "f16",
        }.get(a.compute_dtype, "f32")
        stacked_shapes = set()
        for d, dims in a.param_shapes:
            if len(dims) >= 3 and dims[0] == a.n_layers:
                stacked_shapes.add((d, dims))
                stacked_shapes.add((hlo_compute, dims))
        stacked = [g for g in gathers if g in stacked_shapes]
        if stacked:
            out.append(_err(
                "census.stacked_param_gather", a.name,
                "full stacked-parameter all-gather(s) outside the FSDP "
                f"layer scan: {[f'{d}{list(dims)}' for d, dims in stacked[:4]]}",
            ))
    if a.name in OVERLAPPED_ENTRIES:
        # The whole point of the mode: the serialized per-layer gathers
        # must be GONE from the layer scan (replaced by the ring). Keyed
        # on the gathers' op_name SCOPE, not shapes: shape matching
        # false-positives on the tiny audit model (lm_head's TP-local
        # (64,64) == q_proj's per-layer shape), while the scope is
        # unambiguous — a healthy overlapped module's only "/blocks/"
        # gathers are the rank-1 bias/LN assemblies, and a degraded one
        # shows rank-2 kernel gathers OR rank-3 activation gathers there
        # (XLA serializes FSDP either way; both are forbidden).
        bad = [
            (d, dims, scope)
            for d, dims, scope in hlo.all_gather_entries(a.hlo_text)
            if "/blocks/" in scope and len(dims) >= 2
        ]
        if bad:
            out.append(_err(
                "census.serialized_layer_gather", a.name,
                "overlapped mode still emits serialized layer-scan "
                "all-gather(s): "
                f"{[(f'{d}{list(dims)}', s.split('/')[-1]) for d, dims, s in bad[:4]]}"
                " — the ring did not take these matmuls over",
            ))
    if a.moe_experts > 0:
        # EP: a gather landing a full leading-E expert tensor (B,E,...) or
        # (B,T,E,...) is the replicate-everything fallback the EP rule
        # rows exist to prevent.
        b, e = a.batch, a.moe_experts
        bad = [
            (d, dims) for d, dims in gathers
            if d == "f32" and len(dims) >= 3 and dims[0] == b
            and (dims[1] == e or (len(dims) >= 4 and dims[2] == e))
        ]
        if bad:
            out.append(_err(
                "census.expert_gather", a.name,
                f"EP gathered full expert tensors: "
                f"{[f'{d}{list(dims)}' for d, dims in bad[:4]]}",
            ))
    return out


def _cross_check_bytes(a: Artifact, census: dict) -> list[Finding]:
    """Census result-bytes vs the analytic comm_bytes_per_step estimate.

    Wide-band sanity only (warn): the census sums per-instruction result
    buffers while the estimator models ring wire traffic, and the CPU
    backend decomposes reduce-scatter — but a DP mode whose all-reduce
    bytes are 100x off the gradient estimate is structurally wrong in a
    way the presence checks cannot see."""
    est = a.comm_estimate or {}
    checks: list[tuple[str, tuple[str, ...], float, float]] = []
    if est.get("dp_allreduce"):
        dp_ops: tuple[str, ...] = ("all-reduce", "reduce-scatter", "all-gather")
        extra_bytes = 0.0
        if a.name in OVERLAPPED_ENTRIES:
            # The FSDP bytes ride the ring transport in this mode — the
            # cross-check must count them or every overlapped entry would
            # warn vacuously (the estimator models the same wire bytes
            # re-phased, not removed). Fused-kernel bytes are matched by
            # kernel NAME so foreign Pallas kernels (flash/decode) never
            # pollute the measurement.
            dp_ops = dp_ops + RING_TRANSPORT_OPS
            extra_bytes = float(
                hlo.overlap_kernel_custom_calls(a.hlo_text)["bytes"]
            )
        checks.append((
            "dp_allreduce", dp_ops,
            est["dp_allreduce"], extra_bytes,
        ))
    if est.get("tp_allreduce"):
        checks.append((
            "tp_allreduce", ("all-reduce", "all-gather", "all-to-all"),
            est["tp_allreduce"], 0.0,
        ))
    out: list[Finding] = []
    lo, hi = CROSS_CHECK_BAND
    for label, ops, estimate, extra in checks:
        measured = extra + float(
            sum(census.get(op, {}).get("bytes", 0) for op in ops)
        )
        if measured == 0:
            continue  # presence checks already cover a missing collective
        ratio = measured / estimate
        if not (lo <= ratio <= hi):
            out.append(_warn(
                "census.bytes_cross_check", a.name,
                f"{label}: census bytes {measured:.3e} vs "
                f"comm_bytes_per_step estimate {estimate:.3e} "
                f"(ratio {ratio:.2f} outside [{lo:.3f}, {hi:.1f}])",
            ))
    return out


# -- family 2: donation audit ---------------------------------------------

def audit_donation(a: Artifact) -> list[Finding]:
    aliased = hlo.input_output_alias_count(a.hlo_text)
    if a.expected_donated and aliased < a.expected_donated:
        return [_err(
            "donation.dropped", a.name,
            f"{a.expected_donated} leaves donated but only {aliased} appear "
            "in input_output_alias — XLA dropped donation(s); peak memory "
            "doubles for every dropped buffer (PR 1 out-shardings bug class)",
        )]
    if aliased > a.expected_donated:
        return [_warn(
            "donation.unexpected", a.name,
            f"{aliased} aliased buffers but only {a.expected_donated} "
            "donated — the alias map covers something the entry point "
            "never donated",
        )]
    return []


# -- family 3: dtype / promotion audit ------------------------------------

def audit_dtypes(a: Artifact) -> list[Finding]:
    out: list[Finding] = []
    f64 = hlo.count_dtype(a.hlo_text, "f64")
    if f64:
        out.append(_err(
            "dtype.f64", a.name,
            f"{f64} f64 buffer(s) in the compiled module — a Python-float "
            "or x64 leak (TPU would emulate or reject)",
        ))
    if a.weak_outputs:
        out.append(_err(
            "dtype.weak_type", a.name,
            f"{a.weak_outputs} weak-typed output(s) in the jaxpr — the next "
            "call's signature will not match and the step recompiles "
            "(canonicalize_state_placement bug class)",
        ))
    dots = hlo.dot_dtype_counts(a.stablehlo_text)
    if a.compute_dtype == "bfloat16" and dots["bf16_dots"] == 0:
        out.append(_err(
            "dtype.bf16_region", a.name,
            "model declares compute_dtype=bfloat16 but zero bf16 "
            f"dot_generals were lowered ({dots}) — every matmul silently "
            "upcast to f32",
        ))
    return out


# -- family 4: host-sync lint ---------------------------------------------

def audit_hostsync(path: str | None = None) -> list[Finding]:
    """Lint the trainer source (or ``path``) for unsanctioned hot-loop
    syncs. Source-level, so it is one finding list per file, not per
    lowered artifact."""
    sites = lint_file(path) if path else lint_file()
    return [
        _err(
            "hostsync.hot_loop", "trainer",
            f"{s.path}:{s.lineno}: {s.call} in the timed loop outside any "
            f"sanctioned boundary ({s.code})",
        )
        for s in unsanctioned(sites)
    ]


# -- family 5: recompile fingerprint ---------------------------------------

def audit_recompile(a: Artifact) -> list[Finding]:
    out: list[Finding] = []
    if a.steady_compiles is not None and a.steady_compiles > 0:
        out.append(_err(
            "recompile.steady", a.name,
            f"second identical call compiled {a.steady_compiles} more "
            "executable(s) — signature churn (shape/dtype/donation drift)",
        ))
    if a.cold_compiles is not None and a.cold_compiles > 1:
        out.append(_err(
            "recompile.cold", a.name,
            f"first call compiled {a.cold_compiles} executables — the "
            "double-compile class the obs watcher caught in PR 1 "
            "(out_shardings no longer pin the state's shardings?)",
        ))
    return out


def audit_artifact(a: Artifact) -> list[Finding]:
    """All per-artifact rule families (1-3, 5; the source lint in family 4
    is per-file — see :func:`audit_hostsync`)."""
    return (
        audit_census(a) + audit_donation(a) + audit_dtypes(a)
        + audit_recompile(a)
    )
