"""The rule engine: severity-ranked findings over lowered artifacts.

Five families, each encoding an invariant the paper's comparison (and the
round-5 one-off tests) depend on:

1. **collective census** — each mode must emit the collectives its design
   requires (DP: gradient all-reduce; TP: activation all-reduce + param
   all-gather; FSDP: param all-gather + grad reduce-scatter, accepting the
   CPU backend's all-reduce+partition-id decomposition; EP/Ulysses:
   all-to-all) and must NOT emit the replicate-and-slice fallbacks: a
   full-parameter all-gather outside FSDP, a stacked-parameter all-gather
   inside FSDP (ZeRO's memory win hoisted out of the layer scan), a
   full-expert-tensor all-gather under EP. Census bytes are cross-checked
   against ``utils/metrics.comm_bytes_per_step`` within a wide tolerance
   (graph result-bytes vs ring wire-bytes differ by (n-1)/n-class factors
   and CPU decomposition; outside 8x either way something is structurally
   wrong — warn, the baselines pin the exact numbers).
2. **donation audit** — every donated buffer must appear in the module's
   ``input_output_alias`` map (the PR 1 out-shardings regression class:
   GSPMD normalizes a degenerate out-spec, the signature stops matching,
   the donation silently drops and peak memory doubles).
3. **dtype/promotion audit** — no f64 anywhere (CPU silently defaults to
   f64 for stray Python floats under x64; TPU would either crash or
   emulate at 1/10 speed), no weak-typed outputs (weak types re-trace on
   the next call — the canonicalize_state_placement bug class), and a
   declared-bf16 model must actually lower bf16 matmuls.
4. **host-sync lint** — no device round-trips inside the trainer's timed
   loop outside sanctioned boundaries (see :mod:`hostsync`).
5. **recompile fingerprint** — a compiled entry point executes from ONE
   executable: cold exactly one backend compile, steady zero.

Three ISSUE-14 families extend the set:

6. **numerics / dtype-flow** — the declared precision policy actually
   lowered (bf16 matmuls under ``bf16_mixed``, no cast-then-dot upcast
   leaks, no per-layer param-cast churn in the scan body) and the
   fp32-mandatory islands (softmax/LN-variance exp+rsqrt, the loss
   value, fp32 AdamW moments and master weights, no bf16 collectives
   under an fp32 policy) never downcast — see :mod:`numerics`.
7. **static memory plan** — the per-entry HBM byte decomposition
   (params / masters / moments / activations / comm buffers) reproduces
   the compiled module's entry layout, the bf16_mixed plan contains the
   masters + bf16 params it promises, and the total sits in a warn-band
   of ``utils/metrics.train_memory_bytes`` — see :mod:`memory`.
8. **dtype-literal lint** — no hard-coded ``jnp.float32``-style literals
   in model/op hot paths outside the sanctioned mandated-precision
   scopes — see :mod:`dtypelint`.
"""

from __future__ import annotations

import dataclasses

from dtc_tpu.analysis import dtypelint, hlo, memory, numerics
from dtc_tpu.analysis.hostsync import lint_file, unsanctioned
from dtc_tpu.analysis.lowering import Artifact

#: Finding severities, gate-relevant order. Only ``error`` fails the audit.
SEVERITIES = ("error", "warn", "info")


@dataclasses.dataclass
class Finding:
    rule: str        # family.check, e.g. "census.required_collective"
    severity: str    # error | warn | info
    artifact: str    # entry-point name, or "trainer" for the source lint
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


#: Per-mode required collectives (presence; the baseline pins counts).
#: FSDP's reduce-scatter is special-cased below for the CPU decomposition.
REQUIRED_COLLECTIVES: dict[str, tuple[str, ...]] = {
    "train_dp": ("all-reduce",),
    "train_tp": ("all-reduce", "all-gather"),
    "train_fsdp": ("all-gather",),
    "train_ep": ("all-to-all",),
    "train_ep_sort": ("all-to-all",),
    "train_ulysses": ("all-to-all",),
    # The DP×FSDP×TP overlapped mode keeps the Megatron activation
    # all-reduces (the explicit psums); its ring transport is checked
    # separately below.
    "train_3d": ("all-reduce",),
    # bf16_mixed rides the same dp mesh as train_dp: the (bf16) gradient
    # all-reduce must still be there — losing it under the new precision
    # mode would be the replicated-fallback class with a dtype twist.
    "train_bf16": ("all-reduce",),
}

#: ISSUE 12 entries whose FSDP traffic rides the overlap ring: the
#: census must see the ring TRANSPORT — collective-permute (decomposed /
#: CPU lowering) or the Pallas custom-calls (fused TPU kernels; the
#: remote-copy DMAs never lower to named HLO collectives) — and must NOT
#: see the serialized per-layer kernel all-gathers the ring replaces.
OVERLAPPED_ENTRIES = ("train_fsdp_overlapped", "train_3d")

#: census ops that can carry an overlapped entry's ring traffic at the
#: XLA level; the fused-kernel form is checked via
#: ``hlo.overlap_kernel_custom_calls`` (kernel-NAME matched — a generic
#: tpu_custom_call count would be satisfied by flash/decode kernels and
#: make the check vacuous on TPU).
RING_TRANSPORT_OPS = ("collective-permute",)

#: Census-bytes vs comm_bytes_per_step cross-check tolerance (ratio band).
CROSS_CHECK_BAND = (1 / 8, 8.0)


def _err(rule: str, art: str, msg: str) -> Finding:
    return Finding(rule, "error", art, msg)


def _warn(rule: str, art: str, msg: str) -> Finding:
    return Finding(rule, "warn", art, msg)


# -- family 1: collective census ------------------------------------------

def audit_census(a: Artifact) -> list[Finding]:
    out: list[Finding] = []
    census = hlo.collective_census(a.hlo_text)
    counts = {op: row["count"] for op, row in census.items()}

    for op in REQUIRED_COLLECTIVES.get(a.name, ()):
        if counts.get(op, 0) == 0:
            out.append(_err(
                "census.required_collective", a.name,
                f"{a.name} lost its {op}s — the partitioner fell back to a "
                f"replicated program (census: {counts})",
            ))
    if a.name == "train_fsdp":
        # ZeRO-3 gradient reduce-scatter: literal instruction, or the CPU
        # pipeline's all-reduce + partition-id dynamic-slice decomposition.
        # Demand the partition-id fingerprint so a plain replicated
        # all-reduce (DP, not ZeRO) cannot pass.
        if counts.get("reduce-scatter", 0) == 0 and not (
            counts.get("all-reduce", 0) > 0 and hlo.has_partition_id(a.hlo_text)
        ):
            out.append(_err(
                "census.required_collective", a.name,
                "FSDP lost its gradient reduce-scatter (neither the literal "
                f"instruction nor the all-reduce+partition-id decomposition "
                f"is present; census: {counts})",
            ))
    if a.name in OVERLAPPED_ENTRIES:
        # Both the param gathers AND the grad reduce-scatter ride the
        # ring here: the transport must be present in one of its two
        # lowered forms — collective-permute (decomposed) or the overlap
        # KERNELS' custom-calls (name-matched; any other Pallas kernel
        # does not count) — or the overlap silently degraded to a
        # replicated program.
        ring_kernels = hlo.overlap_kernel_custom_calls(a.hlo_text)
        if not (
            any(counts.get(op, 0) for op in RING_TRANSPORT_OPS)
            or ring_kernels["count"]
        ):
            out.append(_err(
                "census.required_collective", a.name,
                f"{a.name} lost its overlap ring — neither "
                "collective-permute (decomposed transport) nor the "
                "overlap ring kernels' custom-calls are present "
                f"(census: {counts})",
            ))

    out.extend(_audit_gathers(a))
    out.extend(_cross_check_bytes(a, census))
    return out


def _audit_gathers(a: Artifact) -> list[Finding]:
    """The forbidden-gather rules — replicate-and-slice fingerprints."""
    out: list[Finding] = []
    gathers = hlo.all_gather_dims(a.hlo_text)
    param_shapes = {(d, dims) for d, dims in a.param_shapes if len(dims) >= 2}

    if a.kind == "train" and a.parallel != "fsdp":
        # "No full-parameter all-gather outside FSDP": a gather landing a
        # buffer exactly shaped like the FULL form of a param that is
        # declared SHARDED means the partitioner is rebuilding replicated
        # weights every step. (Replicated params never enter
        # ``param_shapes`` — their gradients are legitimately assembled
        # by param-shaped gathers; see lowering._sharded_param_shapes.)
        bad = [g for g in gathers if g in param_shapes]
        if bad:
            out.append(_err(
                "census.full_param_gather", a.name,
                f"full-parameter all-gather(s) outside FSDP: "
                f"{[f'{d}{list(dims)}' for d, dims in bad[:4]]}",
            ))
    if a.parallel == "fsdp":
        # Inside FSDP, per-layer rank-2 gathers at use are the design; a
        # gather landing EXACTLY a stacked param's full (L, ...) shape
        # means XLA hoisted the whole parameter out of the layer scan and
        # the ZeRO memory win is gone. (dtype, dims) membership, not a
        # bare leading-dim test: incidental rank-3 buffers (the wte
        # scatter-add's s32 index gather) can share the leading dim with
        # n_layers on small meshes (ISSUE 12 found it at data=4). The
        # accepted dtypes are the param dtype AND the model's compute
        # dtype — XLA routinely sinks the fp32->bf16 convert below the
        # gather to halve wire bytes, so a hoisted gather may land the
        # CAST of a stacked param.
        hlo_compute = {
            "float32": "f32", "bfloat16": "bf16", "float16": "f16",
        }.get(a.compute_dtype, "f32")
        stacked_shapes = set()
        for d, dims in a.param_shapes:
            if len(dims) >= 3 and dims[0] == a.n_layers:
                stacked_shapes.add((d, dims))
                stacked_shapes.add((hlo_compute, dims))
        stacked = [g for g in gathers if g in stacked_shapes]
        if stacked:
            out.append(_err(
                "census.stacked_param_gather", a.name,
                "full stacked-parameter all-gather(s) outside the FSDP "
                f"layer scan: {[f'{d}{list(dims)}' for d, dims in stacked[:4]]}",
            ))
    if a.name in OVERLAPPED_ENTRIES:
        # The whole point of the mode: the serialized per-layer gathers
        # must be GONE from the layer scan (replaced by the ring). Keyed
        # on the gathers' op_name SCOPE, not shapes: shape matching
        # false-positives on the tiny audit model (lm_head's TP-local
        # (64,64) == q_proj's per-layer shape), while the scope is
        # unambiguous — a healthy overlapped module's only "/blocks/"
        # gathers are the rank-1 bias/LN assemblies, and a degraded one
        # shows rank-2 kernel gathers OR rank-3 activation gathers there
        # (XLA serializes FSDP either way; both are forbidden).
        bad = [
            (d, dims, scope)
            for d, dims, scope in hlo.all_gather_entries(a.hlo_text)
            if "/blocks/" in scope and len(dims) >= 2
        ]
        if bad:
            out.append(_err(
                "census.serialized_layer_gather", a.name,
                "overlapped mode still emits serialized layer-scan "
                "all-gather(s): "
                f"{[(f'{d}{list(dims)}', s.split('/')[-1]) for d, dims, s in bad[:4]]}"
                " — the ring did not take these matmuls over",
            ))
    if a.moe_experts > 0:
        # EP: a gather landing a full leading-E expert tensor (B,E,...) or
        # (B,T,E,...) is the replicate-everything fallback the EP rule
        # rows exist to prevent.
        b, e = a.batch, a.moe_experts
        bad = [
            (d, dims) for d, dims in gathers
            if d == "f32" and len(dims) >= 3 and dims[0] == b
            and (dims[1] == e or (len(dims) >= 4 and dims[2] == e))
        ]
        if bad:
            out.append(_err(
                "census.expert_gather", a.name,
                f"EP gathered full expert tensors: "
                f"{[f'{d}{list(dims)}' for d, dims in bad[:4]]}",
            ))
    return out


def _cross_check_bytes(a: Artifact, census: dict) -> list[Finding]:
    """Census result-bytes vs the analytic comm_bytes_per_step estimate.

    Wide-band sanity only (warn): the census sums per-instruction result
    buffers while the estimator models ring wire traffic, and the CPU
    backend decomposes reduce-scatter — but a DP mode whose all-reduce
    bytes are 100x off the gradient estimate is structurally wrong in a
    way the presence checks cannot see."""
    est = a.comm_estimate or {}
    checks: list[tuple[str, tuple[str, ...], float, float]] = []
    if est.get("dp_allreduce"):
        dp_ops: tuple[str, ...] = ("all-reduce", "reduce-scatter", "all-gather")
        extra_bytes = 0.0
        if a.name in OVERLAPPED_ENTRIES:
            # The FSDP bytes ride the ring transport in this mode — the
            # cross-check must count them or every overlapped entry would
            # warn vacuously (the estimator models the same wire bytes
            # re-phased, not removed). Fused-kernel bytes are matched by
            # kernel NAME so foreign Pallas kernels (flash/decode) never
            # pollute the measurement.
            dp_ops = dp_ops + RING_TRANSPORT_OPS
            extra_bytes = float(
                hlo.overlap_kernel_custom_calls(a.hlo_text)["bytes"]
            )
        checks.append((
            "dp_allreduce", dp_ops,
            est["dp_allreduce"], extra_bytes,
        ))
    if est.get("tp_allreduce"):
        checks.append((
            "tp_allreduce", ("all-reduce", "all-gather", "all-to-all"),
            est["tp_allreduce"], 0.0,
        ))
    out: list[Finding] = []
    lo, hi = CROSS_CHECK_BAND
    for label, ops, estimate, extra in checks:
        measured = extra + float(
            sum(census.get(op, {}).get("bytes", 0) for op in ops)
        )
        if measured == 0:
            continue  # presence checks already cover a missing collective
        ratio = measured / estimate
        if not (lo <= ratio <= hi):
            out.append(_warn(
                "census.bytes_cross_check", a.name,
                f"{label}: census bytes {measured:.3e} vs "
                f"comm_bytes_per_step estimate {estimate:.3e} "
                f"(ratio {ratio:.2f} outside [{lo:.3f}, {hi:.1f}])",
            ))
    return out


# -- family 2: donation audit ---------------------------------------------

def audit_donation(a: Artifact) -> list[Finding]:
    aliased = hlo.input_output_alias_count(a.hlo_text)
    if a.expected_donated and aliased < a.expected_donated:
        return [_err(
            "donation.dropped", a.name,
            f"{a.expected_donated} leaves donated but only {aliased} appear "
            "in input_output_alias — XLA dropped donation(s); peak memory "
            "doubles for every dropped buffer (PR 1 out-shardings bug class)",
        )]
    if aliased > a.expected_donated:
        return [_warn(
            "donation.unexpected", a.name,
            f"{aliased} aliased buffers but only {a.expected_donated} "
            "donated — the alias map covers something the entry point "
            "never donated",
        )]
    return []


# -- family 3: dtype / promotion audit ------------------------------------

def audit_dtypes(a: Artifact) -> list[Finding]:
    out: list[Finding] = []
    f64 = hlo.count_dtype(a.hlo_text, "f64")
    if f64:
        out.append(_err(
            "dtype.f64", a.name,
            f"{f64} f64 buffer(s) in the compiled module — a Python-float "
            "or x64 leak (TPU would emulate or reject)",
        ))
    if a.weak_outputs:
        out.append(_err(
            "dtype.weak_type", a.name,
            f"{a.weak_outputs} weak-typed output(s) in the jaxpr — the next "
            "call's signature will not match and the step recompiles "
            "(canonicalize_state_placement bug class)",
        ))
    dots = hlo.dot_dtype_counts(a.stablehlo_text)
    if a.compute_dtype == "bfloat16" and dots["bf16_dots"] == 0:
        out.append(_err(
            "dtype.bf16_region", a.name,
            "model declares compute_dtype=bfloat16 but zero bf16 "
            f"dot_generals were lowered ({dots}) — every matmul silently "
            "upcast to f32",
        ))
    return out


# -- family 6: numerics / dtype-flow audit (ISSUE 14) ----------------------

#: Memory-plan vs analytic-model cross-check band (ratio) — same wide-band
#: philosophy as the census bytes check: the analytic model is structural
#: (XLA fuses/reuses buffers), the band catches 100x accounting bugs, and
#: the committed baselines pin the exact numbers.
MEMORY_CROSS_CHECK_BAND = (1 / 8, 8.0)

#: Entry-layout decomposition slack: the classified state + batch bytes
#: must reproduce the module's entry-parameter bytes within this
#: fraction (plus a small constant for stray scalars the classifier
#: lumps differently than the layout pads them).
ENTRY_DECOMP_TOL = 0.02
ENTRY_DECOMP_SLACK_BYTES = 4096


def audit_numerics(a: Artifact) -> list[Finding]:
    """Dtype-flow rules over the StableHLO lowering (ISSUE 14): the
    declared precision policy must have ACTUALLY lowered — bf16 matmuls
    under ``bf16_mixed`` with no cast-then-dot leaks and no per-layer
    param-cast churn — and the fp32-mandatory islands (softmax/LN
    variance via exp/rsqrt, the loss value, fp32 optimizer moments and
    masters) must stay fp32 under EVERY policy."""
    out: list[Finding] = []
    dots = numerics.dot_signature_census(a.stablehlo_text)
    regions = numerics.fp32_region_census(a.stablehlo_text)
    converts = numerics.scan_convert_census(a.stablehlo_text)

    if a.precision == "bf16_mixed":
        bf16_dots = dots["bf16_bf16"] + dots["bf16_mixed"]
        if bf16_dots == 0:
            out.append(_err(
                "numerics.matmul_region", a.name,
                "policy declares bf16_mixed but ZERO matmuls lowered with "
                f"a bf16 operand ({dots}) — the policy did not reach the "
                "model (params/compute still fp32?)",
            ))
        if converts["param_slice_downcast"]:
            out.append(_err(
                "numerics.cast_churn", a.name,
                f"{converts['param_slice_downcast']} per-layer param-slice "
                "downcast(s) inside the layer scan under bf16_mixed — "
                "params should already be STORED bf16 (the whole point); "
                "a scan-body cast means fp32 params leaked through",
            ))
    elif converts["param_slice_downcast"]:
        # fp32-policy models with a bf16 compute dtype pay this cast L
        # times per step (the flagship default before bf16_mixed) — warn,
        # with the fix named; the baseline pins the count either way.
        out.append(_warn(
            "numerics.cast_churn", a.name,
            f"{converts['param_slice_downcast']} per-layer param-slice "
            "downcast(s) inside the layer scan: fp32-stored params are "
            "re-cast to the compute dtype EVERY layer, every step — "
            "precision: bf16_mixed stores bf16 params and hoists the "
            "cast out of the step entirely",
        ))
    if dots["f32_upcast"]:
        out.append(_err(
            "numerics.upcast_leak", a.name,
            f"{dots['f32_upcast']} matmul(s) run on f32 UPCASTS of bf16 "
            "values (both operands cast-then-dot) — compute the dot in "
            "bf16 with preferred_element_type=f32 if f32 accumulation "
            "was the goal",
        ))
    for op, row in regions.items():
        low = {dt: n for dt, n in row.items() if dt in ("bf16", "f16")}
        if low:
            out.append(_err(
                "numerics.fp32_mandatory", a.name,
                f"{op} lowered in reduced precision {low} — softmax/LN "
                "variance are fp32-mandatory under every policy "
                "(dangerous downcast)",
            ))
    if a.kind == "train" and a.loss_dtype and a.loss_dtype != "f32":
        out.append(_err(
            "numerics.loss_dtype", a.name,
            f"loss output is {a.loss_dtype}, not f32 — the CE/logsumexp "
            "reduction is fp32-mandatory",
        ))
    sd = a.state_dtypes or {}
    if sd.get("opt_moments") and sd["opt_moments"] != ["f32"]:
        out.append(_err(
            "numerics.optimizer_state", a.name,
            f"AdamW moments hold {sd['opt_moments']} — moment "
            "accumulation is fp32-mandatory under every policy",
        ))
    if a.precision == "bf16_mixed":
        if sd.get("opt_master", []) != ["f32"]:
            out.append(_err(
                "numerics.optimizer_state", a.name,
                f"bf16_mixed master weights hold {sd.get('opt_master')} "
                "— masters must be exactly fp32 (with_master_weights)",
            ))
    elif a.kind == "train" and a.precision == "fp32":
        cd = hlo.collective_dtype_census(a.hlo_text)
        bf16_colls = {
            op: row["bf16"] for op, row in cd.items() if row.get("bf16")
        }
        if bf16_colls:
            out.append(_err(
                "numerics.grad_accum_downcast", a.name,
                f"fp32 policy but bf16 collective(s) on the wire "
                f"{bf16_colls} — cross-replica gradient accumulation "
                "silently downcast",
            ))
    return out


# -- family 7: static memory plan (ISSUE 14) -------------------------------

def audit_memory(a: Artifact) -> list[Finding]:
    """Static-HBM-plan rules: the state-byte decomposition must reproduce
    the compiled module's entry-layout bytes (the proof the plan
    describes THIS program), the bf16_mixed plan must actually contain
    the fp32 masters + halved bf16 params it promises, and the plan
    total must sit in a wide warn-band of the analytic model."""
    out: list[Finding] = []
    if not a.state_bytes:
        return out
    plan = memory.hbm_plan(a)
    known = int(sum(a.state_bytes.values())) + int(a.batch_bytes or 0)
    ins = plan["entry_inputs"]
    if ins and abs(ins - known) > (
        ENTRY_DECOMP_TOL * ins + ENTRY_DECOMP_SLACK_BYTES
    ):
        out.append(_err(
            "memory.entry_decomposition", a.name,
            f"classified state+batch bytes {known} do not reproduce the "
            f"module's entry-parameter bytes {ins} — the params/master/"
            "moments split has rotted away from the program it claims to "
            "describe",
        ))
    if a.precision == "bf16_mixed":
        params = plan.get("params", 0)
        master = plan.get("opt_master", 0)
        if master == 0:
            out.append(_err(
                "memory.master_weights", a.name,
                "bf16_mixed declared but the state holds NO master-weight "
                "bytes — the optimizer is not running the fp32-master "
                "schedule (told bf16_mixed over an fp32 program?)",
            ))
        elif not master // 2 <= params <= master:
            # bf16 params are exactly half their fp32 masters, except the
            # always-fp32 LN leaves (master == params for those) — so
            # params must land in [master/2, master], both ends inclusive
            # (all-bf16 tree at the low end, degenerate all-fp32-island
            # tree at the high end).
            out.append(_err(
                "memory.master_weights", a.name,
                f"bf16_mixed param bytes {params} vs master bytes "
                f"{master}: expected params in [master/2, master] (bf16 "
                "payload + fp32 LN islands) — the param tree is not "
                "actually stored bf16",
            ))
    est = a.mem_estimate or {}
    if est.get("total"):
        lo, hi = MEMORY_CROSS_CHECK_BAND
        ratio = plan["total"] / est["total"]
        if not (lo <= ratio <= hi):
            out.append(_warn(
                "memory.bytes_cross_check", a.name,
                f"static plan total {plan['total']:.3e} vs analytic "
                f"train_memory_bytes {est['total']:.3e} (ratio {ratio:.2f} "
                f"outside [{lo:.3f}, {hi:.1f}])",
            ))
    return out


# -- family 8: dtype-literal source lint (ISSUE 14) ------------------------

def audit_dtype_literals() -> list[Finding]:
    """Source-level twin of the host-sync lint: hard-coded dtype literals
    in ``models/``/``ops/`` hot paths outside the sanctioned
    mandated-precision scopes (see :mod:`dtc_tpu.analysis.dtypelint`).
    One finding list for the tree, like :func:`audit_hostsync`."""
    return [
        _err(
            "dtypelint.hardcoded", "tree",
            f"{s.rel}:{s.lineno}: {s.code} in "
            f"{'/'.join(s.scope) or '<module>'} bypasses the precision "
            "policy (not in dtypelint.ALLOWLIST; if this is a new "
            "mandated-fp32 region, allowlist it WITH its justification)",
        )
        for s in dtypelint.unsanctioned(dtypelint.lint_tree())
    ]


# -- family 4: host-sync lint ---------------------------------------------

def audit_hostsync(path: str | None = None) -> list[Finding]:
    """Lint the trainer source (or ``path``) for unsanctioned hot-loop
    syncs. Source-level, so it is one finding list per file, not per
    lowered artifact."""
    sites = lint_file(path) if path else lint_file()
    return [
        _err(
            "hostsync.hot_loop", "trainer",
            f"{s.path}:{s.lineno}: {s.call} in the timed loop outside any "
            f"sanctioned boundary ({s.code})",
        )
        for s in unsanctioned(sites)
    ]


# -- family 5: recompile fingerprint ---------------------------------------

def audit_recompile(a: Artifact) -> list[Finding]:
    out: list[Finding] = []
    if a.steady_compiles is not None and a.steady_compiles > 0:
        out.append(_err(
            "recompile.steady", a.name,
            f"second identical call compiled {a.steady_compiles} more "
            "executable(s) — signature churn (shape/dtype/donation drift)",
        ))
    if a.cold_compiles is not None and a.cold_compiles > 1:
        out.append(_err(
            "recompile.cold", a.name,
            f"first call compiled {a.cold_compiles} executables — the "
            "double-compile class the obs watcher caught in PR 1 "
            "(out_shardings no longer pin the state's shardings?)",
        ))
    return out


def audit_artifact(
    a: Artifact, *, numerics: bool = True, memory: bool = True
) -> list[Finding]:
    """All per-artifact rule families (1-3, 5-7; the source lints in
    families 4 and 8 are per-file/tree — see :func:`audit_hostsync` and
    :func:`audit_dtype_literals`). ``numerics``/``memory`` disable the
    ISSUE-14 families — the audit_graph.py --no-numerics/--no-memory
    escape hatches must actually bypass the passes, not just their
    baselines."""
    out = (
        audit_census(a) + audit_donation(a) + audit_dtypes(a)
        + audit_recompile(a)
    )
    if numerics:
        out += audit_numerics(a)
    if memory:
        out += audit_memory(a)
    return out
