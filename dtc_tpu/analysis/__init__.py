"""Graph auditor: static analysis over lowered jaxprs and compiled HLO.

The paper's DP/TP/PP comparison is only as honest as the compiled
programs behind it: GSPMD derives every collective from sharding
annotations, so a drifted annotation silently turns "shard the experts"
into "replicate everything and slice" — numerically identical, and
invisible to every loss-parity test in the suite. This package makes the
compiled program itself an asserted artifact:

- :mod:`lowering` — one registry of auditable entry points (the train
  step per parallel mode on the 8-virtual-device CPU mesh, the greedy
  decode path), each lowered/compiled exactly the way the trainer runs
  it (committed input shardings — in this env the in-graph logical
  constraints are no-ops and placement flows entirely from committed
  arguments, which the audit of record must mirror);
- :mod:`hlo` — text-level parsing of the optimized HLO: collective
  census with result-buffer byte estimates, ``input_output_alias``
  donation map, dtype scans;
- :mod:`hostsync` — AST lint of the trainer's timed loop for host
  synchronization (``device_get`` / ``block_until_ready`` / ``.item()``)
  outside the sanctioned boundaries;
- :mod:`numerics` — dtype-flow over the StableHLO lowering (ISSUE 14):
  dot-operand-signature census, fp32-mandatory region checks, and the
  origin-matched per-layer cast-placement lint — the pass that certifies
  the ``bf16_mixed`` training mode actually lowered;
- :mod:`memory` — the static per-entry HBM plan (params / masters /
  moments / activations / comm buffers), verified against the module's
  entry layout and warn-band cross-checked against
  ``utils/metrics.train_memory_bytes``;
- :mod:`dtypelint` — hostsync-style AST lint for hard-coded dtype
  literals in model/op hot paths outside the sanctioned
  mandated-precision scopes;
- :mod:`rules` — the rule engine: eight families (collective census +
  forbidden gathers, donation audit, dtype/promotion audit, host-sync
  lint, recompile fingerprint, numerics/dtype-flow, static memory plan,
  dtype-literal lint) producing severity-ranked findings;
- :mod:`report` — JSON report assembly, per-entry-point fingerprints
  (graph + ``.numerics`` + ``.memory`` sections, each its own committed
  file), committed-baseline read/write/diff (the drift gate).

``scripts/audit_graph.py`` is the CLI; ``scripts/verify_tier1.sh`` runs
it as a pre-gate; ``tests/test_collectives_hlo.py`` asserts through the
same engine so the one-off round-5 HLO checks and the permanent audit
cannot drift apart.
"""

from dtc_tpu.analysis.hlo import (  # noqa: F401
    all_gather_shapes,
    collective_census,
    collective_counts,
    input_output_alias_count,
)
from dtc_tpu.analysis.lowering import (  # noqa: F401
    Artifact,
    build_artifacts,
    compiled_train_hlo,
)
from dtc_tpu.analysis.report import (  # noqa: F401
    BASELINE_DIR,
    check_baselines,
    build_report,
    write_baselines,
)
from dtc_tpu.analysis.hostsync import lint_file, lint_source, unsanctioned  # noqa: F401
from dtc_tpu.analysis.rules import Finding, audit_artifact, audit_hostsync  # noqa: F401
