"""Report assembly and the committed-baseline drift gate.

A fingerprint is the STRUCTURED summary of one entry point's lowered
graph — collective census (counts + bytes), donation coverage, dtype
counts, recompile counts, mesh — not a hash of the HLO text (text carries
incidental metadata; the structured fields are the invariants). Baselines
are those fingerprints committed under ``dtc_tpu/analysis/baselines/``:
the gate recomputes and diffs, so ANY graph change — even one no rule
hard-fails, like two extra all-gathers or a dot flipping f32 — fails
loudly with a per-field diff until a human re-blesses it with
``--write-baseline``.

Baselines record the jax version that produced them: a version mismatch
downgrades drift to a warning (XLA's CPU pipeline legitimately changes
between releases; the gate is only authoritative on the env it was
blessed on — this container's jax, per tests/known_env_failures.json).
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterable

from dtc_tpu.analysis import hlo, memory, numerics
from dtc_tpu.analysis.lowering import Artifact
from dtc_tpu.analysis.rules import Finding

BASELINE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baselines")

#: ISSUE-14 baseline sections: each audited entry additionally commits a
#: ``<entry>.numerics.json`` (dtype-flow fingerprint) and a
#: ``<entry>.memory.json`` (static HBM plan). Separate FILES on purpose:
#: the pre-existing ``<entry>.json`` graph fingerprints stay
#: byte-identical — the new families extend the gate without re-blessing
#: eleven committed baselines whose graphs did not change.
SECTIONS = ("numerics", "memory")


def artifact_fingerprint(a: Artifact) -> dict[str, Any]:
    """The drift-gated invariants of one lowered entry point."""
    return {
        "kind": a.kind,
        "mesh": a.mesh_shape,
        "batch": a.batch,
        "seq_len": a.seq_len,
        "n_layers": a.n_layers,
        "moe_experts": a.moe_experts,
        "compute_dtype": a.compute_dtype,
        "census": hlo.collective_census(a.hlo_text),
        "alias_count": hlo.input_output_alias_count(a.hlo_text),
        "expected_donated": a.expected_donated,
        "partition_id": hlo.has_partition_id(a.hlo_text),
        "f64_buffers": hlo.count_dtype(a.hlo_text, "f64"),
        "weak_outputs": a.weak_outputs,
        "dots": hlo.dot_dtype_counts(a.stablehlo_text),
        "cold_compiles": a.cold_compiles,
        "steady_compiles": a.steady_compiles,
    }


def numerics_fingerprint(a: Artifact) -> dict[str, Any]:
    """The dtype-flow invariants of one entry (ISSUE 14) — committed as
    ``<entry>.numerics.json``."""
    return numerics.numerics_fingerprint(
        a.stablehlo_text,
        precision=a.precision,
        loss_dtype=a.loss_dtype,
        state_dtypes=a.state_dtypes,
        collective_dtypes=hlo.collective_dtype_census(a.hlo_text),
    )


def memory_fingerprint(a: Artifact) -> dict[str, Any]:
    """The static HBM plan of one entry (ISSUE 14) — committed as
    ``<entry>.memory.json``. None for artifacts without the byte
    evidence (state_bytes unrecorded)."""
    if not a.state_bytes:
        return {}
    return memory.hbm_plan(a)


def build_report(
    artifacts: Iterable[Artifact],
    findings: Iterable[Finding],
    *,
    sections: tuple[str, ...] = SECTIONS,
) -> dict[str, Any]:
    """Assemble the serializable audit report: per-entry fingerprints
    (graph + the ISSUE-14 numerics/memory sections) plus severity-ranked
    findings (per-artifact and source-level alike). ``sections`` narrows
    the extra sections (audit_graph.py's --no-numerics/--no-memory)."""
    import jax

    artifacts = list(artifacts)
    findings = sorted(
        findings, key=lambda f: ("error", "warn", "info").index(f.severity)
    )
    by_sev: dict[str, int] = {}
    for f in findings:
        by_sev[f.severity] = by_sev.get(f.severity, 0) + 1
    report = {
        "jax": jax.__version__,
        "platform": jax.devices()[0].platform,
        "device_count": jax.device_count(),
        "entries": {a.name: artifact_fingerprint(a) for a in artifacts},
        "findings": [f.as_dict() for f in findings],
        "summary": by_sev,
    }
    if "numerics" in sections:
        report["numerics"] = {
            a.name: numerics_fingerprint(a) for a in artifacts
        }
    if "memory" in sections:
        report["memory"] = {
            a.name: fp for a in artifacts
            if (fp := memory_fingerprint(a))
        }
    return report


def _baseline_path(name: str, directory: str, section: str = "") -> str:
    suffix = f".{section}" if section else ""
    return os.path.join(directory, f"{name}{suffix}.json")


def write_baselines(
    report: dict[str, Any], directory: str = BASELINE_DIR
) -> list[str]:
    """Bless the report's fingerprints as the committed baselines (one
    file per entry — plus one per ISSUE-14 section present in the report
    — so a drift diff names the entry AND the family in `git status`)."""
    os.makedirs(directory, exist_ok=True)
    written = []
    for name, fp in report["entries"].items():
        path = _baseline_path(name, directory)
        with open(path, "w") as f:
            json.dump(
                {"jax": report["jax"], "platform": report["platform"],
                 "fingerprint": fp},
                f, indent=1, sort_keys=True,
            )
            f.write("\n")
        written.append(path)
    for section in SECTIONS:
        for name, fp in report.get(section, {}).items():
            path = _baseline_path(name, directory, section)
            with open(path, "w") as f:
                json.dump(
                    {"jax": report["jax"], "platform": report["platform"],
                     "fingerprint": fp},
                    f, indent=1, sort_keys=True,
                )
                f.write("\n")
            written.append(path)
    return written


def _diff(base: Any, cur: Any, prefix: str = "") -> list[str]:
    """Recursive field diff, one human-readable line per changed leaf."""
    if isinstance(base, dict) and isinstance(cur, dict):
        lines = []
        for key in sorted(set(base) | set(cur)):
            sub = f"{prefix}.{key}" if prefix else str(key)
            if key not in base:
                lines.append(f"{sub}: (absent) -> {cur[key]!r}")
            elif key not in cur:
                lines.append(f"{sub}: {base[key]!r} -> (absent)")
            else:
                lines.extend(_diff(base[key], cur[key], sub))
        return lines
    if base != cur:
        return [f"{prefix}: {base!r} -> {cur!r}"]
    return []


def check_baselines(
    report: dict[str, Any],
    directory: str = BASELINE_DIR,
    *,
    require: bool = True,
) -> list[Finding]:
    """Drift gate: diff the report's fingerprints against the committed
    baselines. Missing baseline -> error when ``require`` (the CI
    pre-gate) else warn; drift -> error with the per-field diff, unless
    the baseline was blessed under a different jax version (warn: the
    graph legitimately moves across XLA releases)."""
    out: list[Finding] = []
    checks: list[tuple[str, str, dict]] = [
        ("", name, fp) for name, fp in report["entries"].items()
    ]
    for section in SECTIONS:
        checks.extend(
            (section, name, fp)
            for name, fp in report.get(section, {}).items()
        )
    for section, name, fp in checks:
        label = f"{name}.{section}" if section else name
        rule_kind = f"{section} fingerprint" if section else "graph"
        path = _baseline_path(name, directory, section)
        if not os.path.exists(path):
            out.append(Finding(
                "baseline.missing", "error" if require else "warn", label,
                f"no committed baseline at {path} — bless the current graph "
                "with scripts/audit_graph.py --write-baseline",
            ))
            continue
        with open(path) as f:
            base = json.load(f)
        lines = _diff(base["fingerprint"], fp)
        if not lines:
            continue
        same_env = base.get("jax") == report["jax"] and (
            base.get("platform") == report["platform"]
        )
        sev = "error" if same_env else "warn"
        env_note = "" if same_env else (
            f" [baseline blessed on jax {base.get('jax')}/"
            f"{base.get('platform')}, running {report['jax']}/"
            f"{report['platform']} — drift downgraded to warn]"
        )
        out.append(Finding(
            "baseline.drift", sev, label,
            f"{rule_kind} drifted from committed baseline "
            f"({len(lines)} field(s))"
            f"{env_note}:\n    " + "\n    ".join(lines)
            + "\n  re-bless with scripts/audit_graph.py --write-baseline "
            "if intended",
        ))
    return out
