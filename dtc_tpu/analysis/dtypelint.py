"""AST lint: hard-coded dtype literals in model/op hot paths (ISSUE 14).

The mixed-precision policy flows from config (``param_dtype`` /
``compute_dtype`` / ``OptimConfig.precision``) through
``models/gpt._dtype`` and flax's ``promote_dtype``; a hard-coded
``jnp.float32`` or ``.astype(jnp.bfloat16)`` in a hot path BYPASSES the
policy — the layer silently runs one dtype while the config (and the
auditor reading the config) claims another. The ``hostsync.py`` pattern
applies: the lint is not "no dtype literals" but "no dtype literals
outside a sanctioned scope", because the mandated-fp32 islands are
SUPPOSED to hard-code fp32 — softmax and LayerNorm variance, the CE
loss, MoE routing numerics, quantization scale math, Pallas kernel
accumulators.

The allowlist below names (file, enclosing-scope) pairs, matched on any
enclosing function or class name — the same contract as hostsync's
SANCTIONED_CONDITIONS table: renaming a scope without updating the table
fails loudly in tests/test_numerics.py, and a NEW literal in an
unsanctioned scope trips the lint on the pristine-tree assertion. Pure
``ast`` on source text — no JAX import, lints any file.
"""

from __future__ import annotations

import ast
import dataclasses
import os

#: dtype attribute names whose literal use the lint tracks.
DTYPE_NAMES = frozenset({
    "float32", "float64", "float16", "bfloat16", "int8",
})

#: Sanctioned scopes per hot-path file (relative to ``dtc_tpu/``). A
#: site is sanctioned when ANY enclosing function/class name appears in
#: its file's set; ``"*"`` sanctions the whole file (the pure Pallas
#: kernel files, whose fp32 online-softmax stats and accumulators are
#: the kernels' DESIGN — their numerics are pinned by the kernel parity
#: tests, not by dtype-policy plumbing); ``"<module>"`` sanctions
#: module-level dtype tables. Every entry is a mandated-precision
#: region: fp32-mandatory numerics (softmax/LN variance/loss/routing),
#: kernel accumulators, dtype plumbing helpers whose JOB is naming
#: dtypes, or int8 quantization scale math.
ALLOWLIST: dict[str, frozenset[str]] = {
    "models/gpt.py": frozenset({
        "_dtype",            # THE policy resolver (name -> jnp dtype)
        "ln",                # pre-LN blocks: fp32-mandated LayerNorm
        "MoEMLP",            # router softmax numerics: fp32-mandated
        "GPTHead",           # ln_f: fp32-mandated LayerNorm
        "GPT",               # decode cache index bookkeeping (int32)
        "CausalSelfAttention",  # int8 KV scale cache (fp32 scales)
        "OverlapDense",      # param_dtype field default, = nn.Dense's
    }),
    "ops/attention.py": frozenset({
        "decode_attention",  # fp32 scores/softmax — the mandated island
    }),
    "ops/fused_ce.py": frozenset({
        # fp32 logsumexp/loss statistics, fwd + bwd.
        "_stats_loss", "head_logits", "fused_head_ce", "_fhc_fwd",
        "_fhc_bwd",
    }),
    # Pure Pallas kernel files: fp32 stats/accumulators throughout, by
    # design (flash online softmax, zigzag-ring merge stats).
    "ops/flash_attention.py": frozenset({"*"}),
    "ops/ring_attention.py": frozenset({"*"}),
    "ops/ulysses_attention.py": frozenset({
        "ulysses_causal_attention",
    }),
    "ops/decode_attention.py": frozenset({
        # fp32 one-pass softmax + int8 quantization scale arithmetic.
        "fused_decode_attention", "_head_kv", "_decode_kernel_single",
        "_decode_kernel_blocked", "quantize_kv", "dequantize_kv",
    }),
    "ops/decode_fused.py": frozenset({
        # The megakernel's in-register fp32 LN/softmax + int8 dequant;
        # the module-level table is the kernel's dtype-name map.
        "<module>", "_fused_layers_kernel", "_fused_layers_call",
        "supports_fused_layers",
    }),
    "ops/moe_dispatch.py": frozenset({
        # Routing probs/aux loss fp32; slot-map scatter arithmetic.
        "top_k_routing", "load_balance_loss", "dispatch_combine_tensors",
        "sort_dispatch", "sort_combine", "einsum_dispatch",
        "slot_to_token",
    }),
    "ops/overlap_collectives.py": frozenset({
        # fp32 MXU accumulation (preferred_element_type) in both ring
        # kernels and the decomposed twin.
        "_contract", "_grad_partial", "_pallas_ag_matmul",
        "_pallas_rs_matmul", "_decomposed_ag_matmul",
        "_decomposed_rs_matmul",
    }),
}

#: Default lint roots: the model + ops hot paths.
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_ROOTS = ("models", "ops")


@dataclasses.dataclass
class DtypeSite:
    """One hard-coded dtype literal."""

    path: str            # file path as given
    rel: str             # allowlist key (path relative to dtc_tpu/)
    lineno: int
    dtype: str           # the DTYPE_NAMES member
    code: str            # unparsed expression context
    scope: tuple[str, ...]  # enclosing class/function names, outermost first
    sanctioned: bool


def _literal_dtypes(node: ast.AST) -> list[tuple[ast.AST, str]]:
    """(node, dtype) for dtype-literal uses inside ``node`` WITHOUT
    recursing (the caller walks). Two forms:

    - an Attribute ``jnp.float32`` / ``np.bfloat16``;
    - a Constant STRING naming a dtype in a dtype position — the
      ``.astype("float32")`` argument or any ``dtype="bfloat16"``
      keyword. (Position-restricted on purpose: bare string comparisons
      like ``cfg.param_dtype == "float32"`` are config PLUMBING, not a
      policy bypass.)
    """
    out: list[tuple[ast.AST, str]] = []
    if isinstance(node, ast.Attribute) and node.attr in DTYPE_NAMES:
        base = node.value
        if isinstance(base, ast.Name) and base.id in ("jnp", "np", "jax"):
            out.append((node, node.attr))
    if isinstance(node, ast.Call):
        f = node.func
        if (
            isinstance(f, ast.Attribute) and f.attr == "astype"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value in DTYPE_NAMES
        ):
            out.append((node.args[0], node.args[0].value))
        for kw in node.keywords:
            if (
                kw.arg == "dtype"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value in DTYPE_NAMES
            ):
                out.append((kw.value, kw.value.value))
    return out


def lint_source(
    source: str, path: str = "<string>", rel: str = ""
) -> list[DtypeSite]:
    """All dtype-literal sites in ``source`` with their enclosing scope
    chain and sanction status (``rel`` selects the allowlist row)."""
    tree = ast.parse(source, filename=path)
    allowed = ALLOWLIST.get(rel, frozenset())
    sites: list[DtypeSite] = []

    def visit(node: ast.AST, scope: tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            scope = scope + (node.name,)
        for lit, dtype in _literal_dtypes(node):
            ok = (
                "*" in allowed
                or (not scope and "<module>" in allowed)
                or any(s in allowed for s in scope)
            )
            sites.append(DtypeSite(
                path=path,
                rel=rel,
                lineno=getattr(lit, "lineno", getattr(node, "lineno", 0)),
                dtype=dtype,
                code=ast.unparse(lit),
                scope=scope,
                sanctioned=ok,
            ))
        for child in ast.iter_child_nodes(node):
            visit(child, scope)

    visit(tree, ())
    return sites


def lint_tree(pkg_dir: str = _PKG_DIR) -> list[DtypeSite]:
    """Lint every hot-path file under ``pkg_dir`` (``dtc_tpu/``)."""
    sites: list[DtypeSite] = []
    for root in DEFAULT_ROOTS:
        base = os.path.join(pkg_dir, root)
        if not os.path.isdir(base):
            continue
        for name in sorted(os.listdir(base)):
            if not name.endswith(".py"):
                continue
            path = os.path.join(base, name)
            rel = f"{root}/{name}"
            with open(path) as f:
                sites.extend(lint_source(f.read(), path, rel))
    return sites


def unsanctioned(sites: list[DtypeSite]) -> list[DtypeSite]:
    return [s for s in sites if not s.sanctioned]
