"""Static HBM plan per audited entry (ISSUE 14).

One deterministic byte budget per compiled program, decomposed the way an
HBM capacity question is actually asked: params / optimizer state (master
weights vs AdamW moments) / activations / communication buffers / IO.
Three independent sources feed it, cross-checked against each other:

- **The compiled module**: entry parameter + result buffer bytes parsed
  from the ``entry_computation_layout`` header (per-device LOCAL shapes —
  GSPMD has already split the tree), the donation alias map, and the
  collective census' result-buffer bytes (:mod:`dtc_tpu.analysis.hlo`).
  XLA's own ``memory_analysis()`` numbers (argument/output/temp/alias
  bytes) ride along — this CPU backend DOES report temp for real
  modules, so the activation row is usually MEASURED even off-TPU; where
  a backend reports 0/none the row falls back to the analytic estimate
  and says so (``activations_source``), the wired-but-unmeasured honesty
  rule the bench tables follow.
- **The live state**: exact per-leaf local bytes of the placed TrainState,
  classified by pytree path into params / fp32 masters / AdamW moments /
  other (counts, clip state) — computed in lowering.py where the arrays
  exist, recorded on the Artifact. The decomposition is VERIFIED against
  the module: state + batch + rng bytes must equal the entry layout's
  input bytes (``entry_decomposition`` check), so the classification can
  never silently rot away from the program it describes.
- **The analytic model**: ``utils/metrics.train_memory_bytes`` — the
  closed-form params + masters + moments + grads + activation estimate +
  comm-buffer budget. The plan total is cross-checked against it in a
  wide warn-band (same [1/8, 8] philosophy as the collective census
  cross-check: the estimate is structural, the band catches 100x
  accounting bugs, the committed baselines pin the exact numbers).

The obs ``memory_stats`` watermark closes the loop where a real device is
available (:func:`device_watermark_bytes` — PJRT reports no stats on this
CPU host, so the audit prints the wired-but-unmeasured note instead).

Pure string/dict processing except the explicitly-lazy device query — no
module-level JAX import, same contract as :mod:`dtc_tpu.analysis.hlo`.
"""

from __future__ import annotations

import re
from typing import Any

from dtc_tpu.analysis import hlo

#: entry_computation_layout={(IN...)->(OUT...)} on the HloModule header.
_ENTRY_LAYOUT = re.compile(r"entry_computation_layout=\{\((.*?)\)->")


def _entry_io_split(hlo_text: str) -> tuple[str, str]:
    """(inputs text, outputs text) of the header's entry layout. The
    output side can itself be a tuple ``(...)``; split on the ``)->``
    that separates the two top-level groups."""
    header = hlo_text.split("\n", 1)[0]
    m = _ENTRY_LAYOUT.search(header)
    ins = m.group(1) if m else ""
    outs = ""
    if m:
        rest = header[m.end():]
        # Output group: everything to the layout attribute's closing
        # brace. Buffer regexes don't care about trailing attrs, so a
        # greedy cut to the next '}' top-level is fine for byte sums.
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "{":
                depth += 1
            elif ch == "}":
                if depth == 0:
                    outs = rest[:i]
                    break
                depth -= 1
        else:
            outs = rest
    return ins, outs


def entry_input_bytes(hlo_text: str) -> int:
    """Total bytes of the module's entry parameters (per-device local
    shapes in a GSPMD module)."""
    ins, _ = _entry_io_split(hlo_text)
    return hlo._buffer_bytes(ins)


def entry_output_bytes(hlo_text: str) -> int:
    """Total bytes of the module's entry results."""
    _, outs = _entry_io_split(hlo_text)
    return hlo._buffer_bytes(outs)


def hbm_plan(a: Any) -> dict[str, Any]:
    """The static HBM plan of one lowered entry (``a`` is an
    :class:`~dtc_tpu.analysis.lowering.Artifact`). All integers, all
    deterministic — report.py commits it as ``<entry>.memory.json``.

    Components (per-device bytes):

    - ``params`` / ``opt_master`` / ``opt_moments`` / ``opt_other``: the
      live state's exact local bytes by class (plus ``cache`` /
      ``lora_stack`` for the serving entries).
    - ``batch_io``: the non-state entry inputs (token batch, rng, slot
      indices).
    - ``comm_buffers``: collective result-buffer bytes from the census —
      the transient buffers the collectives land in.
    - ``activations``: XLA's measured temp bytes when the backend reports
      them (TPU), else the analytic activation estimate
      (``activations_source`` says which — "xla_temp" or "analytic").
    - ``entry_inputs`` / ``entry_outputs`` / ``alias_count``: the
      module-side ground truth the decomposition is checked against.
    - ``undonated_output``: result bytes not aliased onto an input — the
      extra residency a step with dropped donations would pay (the
      donation rule errors on that separately; this is the byte view).
    - ``total``: state + batch_io + activations + comm_buffers — the
      static residency estimate for one in-flight step.
    """
    # Per-artifact memo: the rule pass, the baseline fingerprint, and the
    # CLI's byte-table print all need this identical deterministic plan —
    # computing it once also guarantees they can never be built from
    # divergent inputs. (Evidence fields never mutate after lowering.)
    cached = getattr(a, "_hbm_plan_cache", None)
    if cached is not None:
        return cached
    census = hlo.collective_census(a.hlo_text)
    comm = int(sum(row["bytes"] for row in census.values()))
    sb = dict(a.state_bytes or {})
    mem = a.mem_stats or {}
    est = a.mem_estimate or {}
    temp = int(mem.get("temp", 0) or 0)
    if temp > 0:
        acts, acts_src = temp, "xla_temp"
    else:
        acts, acts_src = int(est.get("activations", 0)), "analytic"
    ins = entry_input_bytes(a.hlo_text)
    outs = entry_output_bytes(a.hlo_text)
    state_total = int(sum(sb.values()))
    # Donated outputs reuse their input buffers; anything beyond the
    # aliased byte count is fresh residency. alias bytes come from
    # memory_analysis when present, else assume full donation coverage
    # of the state (the donation rule audits the count separately).
    alias_bytes = int(mem.get("alias", 0) or 0)
    if alias_bytes == 0 and hlo.input_output_alias_count(a.hlo_text):
        alias_bytes = min(state_total, outs)
    plan = {
        **{k: int(v) for k, v in sorted(sb.items())},
        "batch_io": int(a.batch_bytes or 0),
        "comm_buffers": comm,
        "activations": acts,
        "activations_source": acts_src,
        "entry_inputs": ins,
        "entry_outputs": outs,
        "alias_count": hlo.input_output_alias_count(a.hlo_text),
        "undonated_output": max(outs - alias_bytes, 0),
        "total": state_total + int(a.batch_bytes or 0) + acts + comm,
    }
    try:
        a._hbm_plan_cache = plan
    except (AttributeError, TypeError):
        pass  # frozen/slotted artifact stand-ins in tests: just recompute
    return plan


def device_watermark_bytes() -> int | None:
    """Peak device memory from PJRT ``memory_stats`` — the obs
    watermark's source (obs/device.py). None when the backend keeps no
    stats (this CPU host): the audit then prints the wired-but-unmeasured
    note instead of a fake cross-check. Lazy jax import on purpose — the
    rest of this module stays importable without a backend."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
    return int(peak) if peak else None
