"""Kernel auditor — DMA happens-before race detection, static VMEM
plans, and the kernel lint family (ISSUE 20; the PR 5/13 auditor
stack's fourth leg).

Why this exists: every Pallas kernel in the repo ships on hand-
maintained DMA discipline ("per-chunk recv slots, chained dma.wait()")
while interpret mode — the only execution channel with the TPU tunnel
down — has no barrier primitive and **no races**: the emulator
sequences remote DMAs deterministically, so a slot-reuse bug or a
missing send wait is structurally invisible to every test we can run.
This module machine-checks the discipline the way happens-before race
detectors do (Lamport 1978; FastTrack, Flanagan & Freund 2009), and
turns VMEM from a hand-rolled estimate into a committed, drift-gated
static plan — the "certify each rung before a chip is spent" pattern.

Three families:

1. **DMA happens-before race detector.** The ring kernels in
   ``ops/overlap_collectives.py`` carry a recording seam
   (``_SCHED_LOG``): when :class:`capture_schedule` installs a list,
   every ``make_async_remote_copy`` start/wait and every shared-buffer
   load/store appends one STATIC event at kernel trace time (under
   shard_map the body traces once, with slots recorded symbolically —
   ("rel", off) = ``(device + off) % ring``, or ("abs", k)).
   :func:`check_ring_schedule` instantiates the events for every ring
   position, rebuilds the CONCURRENT schedule — a send is in flight
   from its ``start`` until the wait that covers it, overlapping the
   next step's compute — and vector-clock-checks:

   - ``kernel.race.recv_before_wait`` — a receive slot is read (or
     forwarded as a DMA source) without the wait covering its fill
     happening-before the access;
   - ``kernel.race.send_rewrite`` — a send's source buffer is
     rewritten while that send may still be reading it;
   - ``kernel.race.slot_reuse`` — two DMAs land in the same
     (device, buffer, slot): the per-chunk write-once discipline is
     what makes the ring safe without flow-control semaphores;
   - ``kernel.race.unwaited_dma`` — a DMA still in flight when the
     kernel returns;
   - ``kernel.race.unfilled_read`` / ``kernel.race.unmatched_wait`` —
     a receive-slot read no DMA ever fills / a wait no fill matches.

   Semaphore semantics modeled: ``dma.wait()`` is a chained FIFO wait —
   the device's k-th wait covers its OWN k-th send (send semaphore) and
   the k-th INCOMING fill (receive semaphore), exactly the discipline
   the kernels' comments promise. Fabricated broken schedules in
   tests/test_kernel_audit.py prove every rule fires; the shipped
   kernels must produce zero findings.

2. **Static VMEM plans across the model ladder.** The shared planner is
   :mod:`dtc_tpu.ops.vmem` (the kernels' own gates consult it; the
   megakernel's BlockSpecs are literally built from it). This module
   evaluates it per ladder rung — flagship, ~350M, ~1B
   (configs/model_ladder_*.yaml) — plus the analytic HBM plan
   (``utils.metrics.train_memory_bytes``), and commits the result as
   ``kernels_<rung>.json`` baselines under ``analysis/baselines/`` with
   the report.py drift gate. This answers PR 10's open megakernel
   double-buffer question as a static number per rung
   (``fits_double_buffered`` + bytes).

3. **Kernel lint family.** :func:`lint_grid_plan` checks index-map
   purity and the pipelining contract (weight blocks b-invariant —
   "weights re-fetch per layer, not per row" — row blocks actually
   advancing with the row coordinate, scalars in SMEM);
   :func:`lint_gate_coverage` AST-checks that every ops/ module
   launching a ``pallas_call`` gates it behind a ``supports*`` /
   ``_pallas_ok`` predicate that consults the shared planner, so gate
   and kernel cannot drift (flash_attention carries a documented
   waiver: its tile sizes are config-validated, not planner-gated).

``scripts/audit_graph.py --kernels`` is the CLI;
``scripts/verify_tier1.sh`` runs it as a pre-gate. Everything here is
CPU-only and static — it certifies schedule discipline and byte plans,
NOT hardware timing (PERF.md's TPU columns stay wired-but-unmeasured).
"""

from __future__ import annotations

import ast
import contextlib
import json
import os
from typing import Any, Iterable, Iterator

from dtc_tpu.analysis.report import BASELINE_DIR, _baseline_path, _diff
from dtc_tpu.analysis.rules import Finding
from dtc_tpu.ops import vmem

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_OPS_DIR = os.path.join(_REPO_ROOT, "dtc_tpu", "ops")
_CONFIG_DIR = os.path.join(_REPO_ROOT, "configs")

#: The audited ladder rungs: the measured flagship plus the two
#: static-audit-only scale points (no training run — the point is to
#: certify the kernel plans BEFORE a chip is spent on them).
LADDER_RUNGS = ("flagship", "ladder_350m", "ladder_1b")

#: ops/ modules allowed to launch a pallas_call without consulting the
#: shared VMEM planner, with the reason (emitted as an info finding so
#: the waiver stays visible in every audit run).
PALLAS_GATE_WAIVERS = {
    "flash_attention.py": (
        "tile sizes are user config (attention_block_*), validated by "
        "ModelConfig and bounded by the flash gate's own shape checks — "
        "a planner consult would duplicate the config validation"
    ),
}


# ---------------------------------------------------------------------------
# 1. DMA happens-before race detector
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def capture_schedule() -> Iterator[list[dict]]:
    """Install the recording seam: inside the block, every ring-kernel
    trace appends its DMA/buffer events to the yielded list."""
    from dtc_tpu.ops import overlap_collectives as oc

    log: list[dict] = []
    prev = oc._SCHED_LOG
    oc._SCHED_LOG = log
    try:
        yield log
    finally:
        oc._SCHED_LOG = prev


def split_schedule_segments(log: Iterable[dict]) -> list[list[dict]]:
    """One segment per kernel trace: events belong to the most recent
    ``kind == "kernel"`` marker (jit may trace an op more than once —
    duplicate segments are checked independently and harmlessly)."""
    segments: list[list[dict]] = []
    for ev in log:
        if ev.get("kind") == "kernel":
            segments.append([ev])
        elif segments:
            segments[-1].append(ev)
    return segments


def _resolve_slot(expr: Any, device: int, ring: int) -> Any:
    if expr is None:
        return None
    tag, val = expr
    if tag == "rel":
        return (device + val) % ring
    if tag == "abs":
        return int(val)
    raise ValueError(f"unknown slot expr {expr!r}")


def _vc_leq(a: tuple[int, ...], b: tuple[int, ...]) -> bool:
    return all(x <= y for x, y in zip(a, b))


def check_ring_schedule(
    segment: list[dict], *, artifact: str | None = None,
) -> list[Finding]:
    """Happens-before audit of one recorded kernel schedule.

    The symbolic per-step events are instantiated at every ring position
    and replayed under the semaphore model (k-th wait covers the k-th
    own send and the k-th incoming fill, FIFO per the single incoming
    channel each ring device has), assigning every event a vector clock;
    the race rules are then pure VC comparisons — an access is safe iff
    the operation that makes it safe *happens-before* it, not merely
    precedes it in interpret mode's serialized execution."""
    if not segment or segment[0].get("kind") != "kernel":
        raise ValueError("segment must start with a 'kernel' event")
    head = segment[0]
    name = head.get("name", "?")
    ring = int(head["ring"])
    body = [e for e in segment[1:] if e.get("kind") != "kernel"]
    artifact = artifact or f"ops/overlap_collectives.py::{name}"
    findings: list[Finding] = []

    def race(rule: str, message: str) -> None:
        findings.append(
            Finding(f"kernel.race.{rule}", "error", artifact, message)
        )

    # --- instantiate the symbolic trace at every ring position --------
    events: list[list[dict]] = []
    for d in range(ring):
        devs = []
        for ev in body:
            e = dict(ev)
            if "slot" in e:
                e["slot"] = _resolve_slot(e["slot"], d, ring)
            if e["kind"] == "dma_start":
                e["src_slot"] = _resolve_slot(e.get("src_slot"), d, ring)
                e["dst_slot"] = _resolve_slot(e.get("dst_slot"), d, ring)
                e["receiver"] = (d + e.get("dst_device", 1)) % ring
            devs.append(e)
        events.append(devs)
    recv_bufs = {e["dst_buf"] for e in body if e["kind"] == "dma_start"}

    # --- replay: assign vector clocks under the semaphore model -------
    vc = [[0] * ring for _ in range(ring)]
    pc = [0] * ring
    waits_done = [0] * ring
    fills: list[list[dict]] = [[] for _ in range(ring)]  # arrival order
    sends: list[list[dict]] = [[] for _ in range(ring)]
    accesses: list[dict] = []  # every local read/write, with VC

    def step(d: int) -> None:
        ev = events[d][pc[d]]
        vc[d][d] += 1
        kind = ev["kind"]
        if kind in ("read", "write"):
            accesses.append({
                "device": d, "kind": kind, "buf": ev["buf"],
                "slot": ev.get("slot"), "step": ev.get("step"),
                "vc": tuple(vc[d]),
            })
        elif kind == "dma_start":
            snap = tuple(vc[d])
            # The DMA reads its source until the covering wait: model
            # the start as a read too (catches forwarding a slot whose
            # own fill has not landed).
            accesses.append({
                "device": d, "kind": "read", "buf": ev["src_buf"],
                "slot": ev.get("src_slot"), "step": ev.get("step"),
                "vc": snap, "via": "dma_src",
            })
            sends[d].append({
                "src": (ev["src_buf"], ev.get("src_slot")),
                "step": ev.get("step"), "start_vc": snap, "wait_vc": None,
            })
            fills[ev["receiver"]].append({
                "buf": ev["dst_buf"], "slot": ev.get("dst_slot"),
                "sender": d, "step": ev.get("step"),
                "start_vc": snap, "wait_vc": None,
            })
        elif kind == "dma_wait":
            k = waits_done[d]
            if k < len(fills[d]):
                fill = fills[d][k]
                vc[d] = [max(a, b) for a, b in zip(vc[d], fill["start_vc"])]
                fill["wait_vc"] = tuple(vc[d])
            else:
                race(
                    "unmatched_wait",
                    f"device {d} step {ev.get('step')}: dma.wait() #{k + 1} "
                    "has no matching incoming DMA — nothing ever signals "
                    "this semaphore (hardware would hang here)",
                )
            if k < len(sends[d]):
                sends[d][k]["wait_vc"] = tuple(vc[d])
            waits_done[d] += 1
        pc[d] += 1

    # Waits block until their fill exists (the sender must progress
    # first); everything else is non-blocking. If the whole ring is
    # stuck, the blocked wait is unmatched — flag it and force on.
    while True:
        progress = False
        for d in range(ring):
            while pc[d] < len(events[d]):
                ev = events[d][pc[d]]
                if (
                    ev["kind"] == "dma_wait"
                    and waits_done[d] >= len(fills[d])
                    and any(pc[o] < len(events[o]) for o in range(ring)
                            if o != d)
                ):
                    break
                step(d)
                progress = True
        if all(pc[d] >= len(events[d]) for d in range(ring)):
            break
        if not progress:
            stuck = next(d for d in range(ring) if pc[d] < len(events[d]))
            step(stuck)  # emits unmatched_wait, releases the deadlock

    # --- rule checks over the clocked schedule ------------------------
    # slot reuse: the per-chunk discipline is write-ONCE per slot.
    for d in range(ring):
        seen: dict[tuple, dict] = {}
        for fill in fills[d]:
            key = (fill["buf"], fill["slot"])
            if key in seen:
                race(
                    "slot_reuse",
                    f"device {d}: recv slot {fill['buf']}[{fill['slot']}] "
                    f"filled twice (sender step {seen[key]['step']} and "
                    f"step {fill['step']}) — per-chunk slots must be "
                    "written exactly once; reuse races the un-consumed "
                    "previous chunk",
                )
            else:
                seen[key] = fill

    # in-flight DMA at kernel end / send-source rewrite while in flight.
    for d in range(ring):
        for i, send in enumerate(sends[d]):
            if send["wait_vc"] is None:
                race(
                    "unwaited_dma",
                    f"device {d}: DMA started at step {send['step']} "
                    f"(send #{i + 1}) is never covered by a dma.wait() — "
                    "still in flight when the kernel returns",
                )
            buf, slot = send["src"]
            for acc in accesses:
                if (
                    acc["device"] == d and acc["kind"] == "write"
                    and (acc["buf"], acc["slot"]) == (buf, slot)
                    and acc["vc"][d] > send["start_vc"][d]
                    and (send["wait_vc"] is None
                         or acc["vc"][d] < send["wait_vc"][d])
                ):
                    race(
                        "send_rewrite",
                        f"device {d} step {acc['step']}: {buf}"
                        f"[{slot}] rewritten while the step-"
                        f"{send['step']} send is still reading it (no "
                        "covering dma.wait() between start and rewrite)",
                    )

    # recv-slot reads must happen-after the wait covering their fill.
    for acc in accesses:
        if acc["kind"] != "read" or acc["buf"] not in recv_bufs:
            continue
        d = acc["device"]
        matching = [
            f for f in fills[d]
            if (f["buf"], f["slot"]) == (acc["buf"], acc["slot"])
        ]
        what = (
            "forwarded as a DMA source" if acc.get("via") == "dma_src"
            else "read"
        )
        if not matching:
            race(
                "unfilled_read",
                f"device {d} step {acc['step']}: {acc['buf']}"
                f"[{acc['slot']}] {what} but no DMA ever fills that slot "
                "— the access observes uninitialized VMEM",
            )
        elif not any(
            f["wait_vc"] is not None and _vc_leq(f["wait_vc"], acc["vc"])
            for f in matching
        ):
            race(
                "recv_before_wait",
                f"device {d} step {acc['step']}: {acc['buf']}"
                f"[{acc['slot']}] {what} without the wait covering its "
                "fill happening-before the access — interpret mode "
                "serializes the DMA and hides this; hardware reads a "
                "partially-landed chunk",
            )
    return findings


def record_ring_schedules(ring: int = 4) -> list[list[dict]]:
    """Drive every shipped ring kernel under the recording seam and
    return the captured schedule segments.

    Runs the REAL kernels (interpret mode on the CPU mesh, the same path
    tests/test_overlap_collectives.py executes): the fused all-gather-
    matmul forward in both shard modes, both backward legs (dx re-gather
    + dw reduce-scatter) via ``jax.grad``, and the standalone
    matmul+reduce-scatter in both scatter modes — every ``pallas_call``
    site the module owns. Events are appended at trace time, so one jit
    per op suffices; shapes are tiny (the schedule is shape-independent:
    the ring length is the only structural parameter)."""
    import jax
    import jax.numpy as jnp

    from dtc_tpu.ops import overlap_collectives as oc

    if jax.device_count() < ring:
        raise RuntimeError(
            f"race audit needs {ring} devices, have {jax.device_count()} "
            "(run under the 8-virtual-device CPU mesh)"
        )
    mesh = jax.make_mesh((ring,), ("data",))
    k_full, n_full = 4 * ring, 2 * ring
    with capture_schedule() as log:
        with mesh:
            x = jnp.ones((ring, 2, k_full), jnp.float32)
            for shard_axis in (0, 1):
                def loss(xx, ww, _sa=shard_axis):
                    y = oc.overlap_dense_matmul(
                        xx, ww, shard_axis=_sa, axis_name="data",
                        mesh=mesh, backend="pallas",
                    )
                    return jnp.sum(y * y)

                w = jnp.ones((k_full, n_full), jnp.float32)
                jax.jit(jax.grad(loss, argnums=(0, 1)))(x, w)
            a = jnp.ones((ring, 2, k_full), jnp.float32)
            b = jnp.ones((ring, 2, n_full), jnp.float32)
            for shard_axis in (0, 1):
                jax.jit(
                    lambda aa, bb, _sa=shard_axis: oc.reduce_scatter_matmul(
                        aa, bb, shard_axis=_sa, axis_name="data",
                        mesh=mesh, backend="pallas",
                    )
                )(a, b)
    return split_schedule_segments(log)


def audit_ring_kernels(ring: int = 4) -> list[Finding]:
    """Record + check every shipped ring kernel's schedule. The seam
    itself is asserted: a refactor that silently drops the recording
    hooks turns the race audit into a vacuous pass, so zero captured
    segments (or a missing kernel) is an error, not a clean bill."""
    segments = record_ring_schedules(ring=ring)
    findings: list[Finding] = []
    names = {seg[0].get("name") for seg in segments}
    for expected in ("ag_matmul", "rs_matmul"):
        if expected not in names:
            findings.append(Finding(
                "kernel.race.no_schedule", "error",
                f"ops/overlap_collectives.py::{expected}",
                "recording seam captured no schedule for this kernel — "
                "the _sched() hooks were dropped or the kernel no longer "
                "launches under the audit harness",
            ))
    for seg in segments:
        findings.extend(check_ring_schedule(seg))
    return findings


# ---------------------------------------------------------------------------
# 2. kernel lint family
# ---------------------------------------------------------------------------


def lint_grid_plan(
    plan: dict[str, Any], *, artifact: str = "ops/decode_fused.py::fused_layers",
) -> list[Finding]:
    """Index-map / SMEM lints over a symbolic grid plan (the structure
    :func:`dtc_tpu.ops.vmem.fused_layers_grid_plan` returns — also the
    structure the kernel's actual BlockSpecs are built from, so linting
    the plan IS linting the launch).

    - **purity**: an index map must be a pure function of the grid
      coords — same coords, same block index, with rank matching the
      block shape (Mosaic silently mis-tiles otherwise).
    - **b-invariance**: layer-streamed blocks (the 16 per-layer weights,
      shared LoRA factors) must NOT vary with the row coordinate —
      "weights re-fetch per layer, not per row" is the pipelining
      contract that keeps per-row grid steps weight-traffic-free — and
      MUST advance with the layer coordinate (else every layer reads
      layer 0's stacked block).
    - **row blocks** (x, cache rows, outputs) must advance with the row
      coordinate (else rows alias one block) — the b-variance dual.
    - **SMEM discipline**: scalar operands (the frontier) ride SMEM as
      whole-array scalar-prefetch specs; VMEM operands must carry a
      block shape + index map.
    """
    findings: list[Finding] = []

    def err(rule: str, msg: str) -> None:
        findings.append(Finding(rule, "error", artifact, msg))

    grid = plan.get("grid", ())
    if len(grid) != 2:
        err("kernel.lint.grid", f"expected a (layers, rows) grid, got {grid}")
        return findings
    n_l, n_b = int(grid[0]), int(grid[1])
    probe_l = 1 if n_l > 1 else 0
    probe_b = 1 if n_b > 1 else 0

    for io, specs in (("in", plan["in_specs"]), ("out", plan["out_specs"])):
        for entry in specs:
            name, shape, imap, space, _nbytes = entry
            label = f"{io}:{name}"
            if space == "smem":
                if shape is not None or imap is not None:
                    err(
                        "kernel.lint.smem",
                        f"{label}: SMEM operands are whole-array scalar "
                        "prefetch — a block shape/index map has no meaning "
                        "there",
                    )
                continue
            if shape is None or imap is None:
                err(
                    "kernel.lint.smem",
                    f"{label}: VMEM operand without a block shape + index "
                    "map — only SMEM scalars may omit them",
                )
                continue
            base = imap(0, 0)
            if imap(0, 0) != base:
                err(
                    "kernel.lint.index_map",
                    f"{label}: index map is impure — two calls with the "
                    "same grid coords returned different block indices",
                )
                continue
            if len(base) != len(shape):
                err(
                    "kernel.lint.index_map",
                    f"{label}: index map rank {len(base)} != block rank "
                    f"{len(shape)} — Mosaic would mis-tile the operand",
                )
                continue
            layer_streamed = name in vmem.WEIGHT_BLOCK_NAMES or (
                name.endswith(("_a", "_b")) and len(shape) == 3
            )
            if layer_streamed:
                if probe_b and imap(0, 0) != imap(0, probe_b):
                    err(
                        "kernel.lint.index_map",
                        f"{label}: weight block varies with the ROW "
                        "coordinate — weights must re-fetch per layer, "
                        "not per row (b-invariance is the megakernel's "
                        "pipelining contract; a b-variant map re-streams "
                        f"{name} for every row in the batch)",
                    )
                if probe_l and imap(0, 0) == imap(probe_l, 0):
                    err(
                        "kernel.lint.index_map",
                        f"{label}: weight block does not advance with the "
                        "layer coordinate — every layer would read layer "
                        "0's stacked block",
                    )
            else:
                if probe_b and imap(0, 0) == imap(0, probe_b):
                    err(
                        "kernel.lint.index_map",
                        f"{label}: row block does not advance with the row "
                        "coordinate — all rows would alias one block",
                    )
    smem_in = [e for e in plan["in_specs"] if e[3] == "smem"]
    if not smem_in:
        err(
            "kernel.lint.smem",
            "no SMEM scalar operand: the frontier lengths must ride SMEM "
            "scalar prefetch, not a VMEM block",
        )
    return findings


def lint_fused_layers(cfg, *, t: int = 1, b: int = 2) -> list[Finding]:
    """Lint the megakernel's grid plan for a concrete config (b=2 so
    b-invariance is actually probed; LoRA sites included when the config
    carries an adapter)."""
    plan = vmem.fused_layers_grid_plan(
        cfg, t=t, b=b, lora_sites=vmem.lora_sites_for(cfg),
    )
    return lint_grid_plan(plan)


def _module_calls_pallas(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr == "pallas_call":
            return True
        if isinstance(node, ast.Name) and node.id == "pallas_call":
            return True
    return False


def _module_imports_vmem(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "dtc_tpu.ops" and any(
                a.name == "vmem" for a in node.names
            ):
                return True
            if node.module == "dtc_tpu.ops.vmem":
                return True
        if isinstance(node, ast.Import) and any(
            a.name == "dtc_tpu.ops.vmem" for a in node.names
        ):
            return True
    return False


def _gate_functions(tree: ast.AST) -> list[ast.FunctionDef]:
    return [
        node for node in ast.walk(tree)
        if isinstance(node, ast.FunctionDef)
        and (node.name.startswith("supports") or node.name == "_pallas_ok")
    ]


def _references_vmem(fn: ast.FunctionDef) -> bool:
    return any(
        isinstance(node, ast.Name) and node.id == "vmem"
        for node in ast.walk(fn)
    )


def lint_gate_coverage(
    ops_dir: str = _OPS_DIR,
    waivers: dict[str, str] | None = None,
) -> list[Finding]:
    """Every ops/ module that launches a ``pallas_call`` must gate it:
    define a ``supports*`` / ``_pallas_ok`` predicate that consults the
    shared planner (:mod:`dtc_tpu.ops.vmem`). This is what keeps the
    gate and the kernel from drifting apart — the PR 11 bug class where
    the estimate said "fits" and Mosaic said otherwise. Waived modules
    surface as info findings so the waiver stays reviewed."""
    if waivers is None:
        waivers = PALLAS_GATE_WAIVERS
    findings: list[Finding] = []
    for fname in sorted(os.listdir(ops_dir)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(ops_dir, fname)
        artifact = f"ops/{fname}"
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        if not _module_calls_pallas(tree):
            continue
        if fname in waivers:
            findings.append(Finding(
                "kernel.lint.gate_coverage", "info", artifact,
                f"pallas_call without a planner-consulting gate — waived: "
                f"{waivers[fname]}",
            ))
            continue
        gates = _gate_functions(tree)
        if not gates:
            findings.append(Finding(
                "kernel.lint.gate_coverage", "error", artifact,
                "module launches a pallas_call but defines no supports*/"
                "_pallas_ok gate — the kernel is reachable with no VMEM "
                "fit check at all",
            ))
            continue
        if not _module_imports_vmem(tree) or not any(
            _references_vmem(g) for g in gates
        ):
            findings.append(Finding(
                "kernel.lint.gate_coverage", "error", artifact,
                "gate does not consult the shared planner "
                "(dtc_tpu.ops.vmem) — a hand-rolled estimate here is the "
                "drift the planner exists to end",
            ))
    return findings


# ---------------------------------------------------------------------------
# 3. static plans across the model ladder + the drift-gated baselines
# ---------------------------------------------------------------------------


def rung_config(name: str):
    """The ModelConfig of one ladder rung. ``flagship`` is built from
    the ONE bench definition (bench.flagship_model_cfg) at its serving
    deployment (megakernel decode); the ladder rungs load from
    configs/model_ladder_*.yaml."""
    if name == "flagship":
        import dataclasses

        from bench import flagship_model_cfg

        return dataclasses.replace(
            flagship_model_cfg(dropout=0.0),
            decode_attention="fused_layers",
        )
    from dtc_tpu.config.loader import load_yaml_dataclass
    from dtc_tpu.config.schema import ModelConfig

    path = os.path.join(_CONFIG_DIR, f"model_{name}.yaml")
    return load_yaml_dataclass(path, ModelConfig)


#: The deployment shape all rung plans are priced at: the 8-device ring
#: of the audited train entries / the b8 reference, seq at the config
#: max, bf16 wire dtype (the bf16_mixed stack — fp32-sharded rings
#: simply double the itemsize term).
_PLAN_RING = 8
_PLAN_BATCH = 8


def _overlap_sites(cfg) -> dict[str, dict[str, Any]]:
    """Static overlap-ring plans for every OverlapDense site of one
    transformer layer, at the deployment shape: per-site fit answers
    "which matmuls ride the fused kernels at this rung" without a
    chip."""
    from dtc_tpu.config.schema import DTYPE_BYTES

    dm, ff = cfg.d_model, cfg.d_ff
    hd = cfg.n_heads * cfg.head_dim
    itemsize = DTYPE_BYTES.get(cfg.compute_dtype, 4)
    m = _PLAN_BATCH * cfg.max_seq_len // _PLAN_RING
    # (k, n, shard_axis) mirrors models/gpt.py's _dense sites: shard
    # axis 0 = contraction (d_model in), 1 = output (d_model out).
    sites = {
        "qkv_proj": (dm, hd, 0),
        "out_proj": (hd, dm, 1),
        "fc1": (dm, ff, 0),
        "fc2": (ff, dm, 1),
    }
    return {
        site: vmem.overlap_plan(m, k, n, _PLAN_RING, sa, itemsize)
        for site, (k, n, sa) in sites.items()
    }


def rung_fingerprint(name: str) -> dict[str, Any]:
    """The drift-gated static plan of one ladder rung: config dims,
    every kernel's VMEM plan (megakernel t=1 + the widest spec window,
    both per-layer decode kernels, every overlap site), and the
    analytic HBM plan at the deployment shape."""
    from dtc_tpu.utils.metrics import train_memory_bytes

    cfg = rung_config(name)
    dims = {
        "vocab_size": cfg.vocab_size,
        "d_model": cfg.d_model,
        "n_layers": cfg.n_layers,
        "n_heads": cfg.n_heads,
        "head_dim": cfg.head_dim,
        "d_ff": cfg.d_ff,
        "max_seq_len": cfg.max_seq_len,
        "param_dtype": cfg.param_dtype,
        "compute_dtype": cfg.compute_dtype,
        "kv_store_dtype": cfg.kv_store_dtype,
    }
    kernels = {
        "fused_layers_t1": vmem.fused_layers_plan(cfg, t=1, b=_PLAN_BATCH),
        f"fused_layers_spec_k{vmem.SPEC_MAX_K}": vmem.fused_layers_plan(
            cfg, t=vmem.SPEC_MAX_K, b=_PLAN_BATCH
        ),
        "decode_single": vmem.decode_single_plan(cfg),
        "decode_blocked": vmem.decode_blocked_plan(cfg),
    }
    for site, plan in _overlap_sites(cfg).items():
        kernels[f"overlap_{site}"] = plan
    hbm = train_memory_bytes(
        cfg, _PLAN_BATCH, cfg.max_seq_len, {"data": _PLAN_RING}, "fsdp",
        precision="bf16_mixed",
    )
    return {
        "config": dims,
        "kernels": kernels,
        "hbm_fsdp8_b8_bf16_mixed": {k: int(v) for k, v in hbm.items()},
    }


def kernel_report() -> dict[str, Any]:
    import jax

    return {
        "jax": jax.__version__,
        "platform": jax.devices()[0].platform,
        "rungs": {name: rung_fingerprint(name) for name in LADDER_RUNGS},
    }


def write_kernel_baselines(
    report: dict[str, Any] | None = None, directory: str = BASELINE_DIR,
) -> list[str]:
    """Bless the per-rung kernel plans as ``kernels_<rung>.json``
    baselines (same file format + drift semantics as the graph
    fingerprints)."""
    if report is None:
        report = kernel_report()
    os.makedirs(directory, exist_ok=True)
    written = []
    for name, fp in report["rungs"].items():
        path = _baseline_path(f"kernels_{name}", directory)
        with open(path, "w") as f:
            json.dump(
                {"jax": report["jax"], "platform": report["platform"],
                 "fingerprint": fp},
                f, indent=1, sort_keys=True,
            )
            f.write("\n")
        written.append(path)
    return written


def check_kernel_baselines(
    report: dict[str, Any] | None = None,
    directory: str = BASELINE_DIR,
    *,
    require: bool = True,
) -> list[Finding]:
    """Drift gate over the committed per-rung kernel plans. Unlike the
    graph baselines these are PURE ARITHMETIC over config dims — no XLA
    in the loop — so drift is an error regardless of jax version: if
    the bytes moved, someone changed a kernel layout or the planner, and
    the baseline must be consciously re-blessed."""
    if report is None:
        report = kernel_report()
    out: list[Finding] = []
    for name, fp in report["rungs"].items():
        label = f"kernels_{name}"
        path = _baseline_path(label, directory)
        if not os.path.exists(path):
            out.append(Finding(
                "baseline.missing", "error" if require else "warn", label,
                f"no committed kernel-plan baseline at {path} — bless with "
                "scripts/audit_graph.py --kernels --write-baseline",
            ))
            continue
        with open(path) as f:
            base = json.load(f)
        lines = _diff(base["fingerprint"], fp)
        if lines:
            out.append(Finding(
                "baseline.drift", "error", label,
                f"static kernel plan drifted from committed baseline "
                f"({len(lines)} field(s)):\n    " + "\n    ".join(lines)
                + "\n  re-bless with scripts/audit_graph.py --kernels "
                "--write-baseline if intended",
            ))
    return out


def run_kernel_audit(
    *,
    ring: int = 4,
    write_baseline: bool = False,
    require_baselines: bool = False,
    race: bool = True,
) -> tuple[list[Finding], dict[str, Any]]:
    """The full kernel audit: static plans (+ baseline gate or bless),
    the lint family per rung, and the happens-before race detector over
    every shipped ring kernel. Returns (findings, kernel report)."""
    findings: list[Finding] = []
    report = kernel_report()
    if write_baseline:
        report["written"] = write_kernel_baselines(report)
    else:
        findings.extend(
            check_kernel_baselines(report, require=require_baselines)
        )
    for name in LADDER_RUNGS:
        cfg = rung_config(name)
        for f in lint_fused_layers(cfg) + lint_fused_layers(
            cfg, t=vmem.SPEC_MAX_K
        ):
            findings.append(Finding(
                f.rule, f.severity, f"{f.artifact}@{name}", f.message
            ))
    findings.extend(lint_gate_coverage())
    if race:
        findings.extend(audit_ring_kernels(ring=ring))
    return findings, report
