"""Anomaly guard: loss-health checks with a recovery policy ladder.

Checks run at LOG BOUNDARIES on the window of losses the trainer already
fetched for logging — zero additional per-step device syncs. Two anomaly
classes:

- **non-finite** — any NaN/inf loss in the window (a poisoned update, a
  fused-kernel bug, bad data);
- **spike** — window mean above ``spike_factor`` x the trailing median of
  healthy window means (``spike_factor: 0`` disables; loss is noisy early
  in training, so this is opt-in).

The policy ladder (MegaScale-style, cheapest rung first):

1. **tolerate/skip** — when device-side update skipping is on
   (``skip_nonfinite_updates``, see ``optax.apply_if_finite`` in
   ``train/optimizer.py``), a non-finite window may be transient: the
   optimizer already dropped the bad updates, so the guard tolerates up to
   ``max_consecutive_skips`` consecutive bad windows before escalating.
2. **rollback** — restore the last *verified* checkpoint and re-seek the
   data stream (the trainer owns the mechanics); at most ``max_rollbacks``
   per run — or per *incident* when ``clean_steps_to_forgive`` is set:
   that many consecutive healthy log windows reset the counter, so
   well-separated transients on a long run never exhaust the ladder.
3. **abort** — raise :class:`AnomalyAbort` so a supervisor restarts the
   job from the last good checkpoint instead of burning accelerator time
   on a diverged run.

Without a checkpoint manager there is nothing to roll back to: the guard
then only reports (``anomaly`` events) — silently continuing is today's
behavior and aborting would destroy the very state a human might inspect.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass
from typing import Any


@dataclass
class GuardDecision:
    action: str          # "ok" | "warn" | "tolerate" | "rollback" | "abort"
    reason: str = ""

    @property
    def anomalous(self) -> bool:
        return self.action != "ok"


class AnomalyGuard:
    def __init__(self, cfg: Any, *, can_rollback: bool):
        self.cfg = cfg
        self.can_rollback = can_rollback
        self.rollbacks_done = 0
        self._consecutive_bad = 0
        # Consecutive healthy windows since the last anomaly — drives the
        # forgiveness knob (clean_steps_to_forgive): a long-enough clean
        # streak resets the rollback counter, so max_rollbacks bounds
        # rollbacks per INCIDENT instead of per run lifetime (a week-long
        # run used to die on its Nth well-separated transient).
        self._clean_windows = 0
        # Trailing window means of HEALTHY windows only — an anomaly must
        # not drag the median toward itself.
        self._means: deque[float] = deque(maxlen=max(int(cfg.spike_window), 2))

    # -- detection ---------------------------------------------------------
    def _trailing_median(self) -> float | None:
        # Minimum history before the median is trusted — capped at the
        # deque's own maxlen so a small spike_window cannot silently
        # disable the check the user just configured.
        if len(self._means) < min(4, self._means.maxlen):
            return None
        return sorted(self._means)[len(self._means) // 2]

    def _classify(self, losses: list[float]) -> str | None:
        if any(not math.isfinite(v) for v in losses):
            return "non-finite loss"
        if self.cfg.spike_factor > 0:
            med = self._trailing_median()
            mean = sum(losses) / len(losses)
            if med is not None and mean > self.cfg.spike_factor * med:
                return (
                    f"loss spike: window mean {mean:.4g} > "
                    f"{self.cfg.spike_factor}x trailing median {med:.4g}"
                )
        return None

    def healthy_loss(self, value: float) -> bool:
        """Single-value health check for off-boundary decisions (the
        trainer's checkpoint gate): same criteria as the window check —
        non-finite always unhealthy, spike-mode also rejects finite
        divergence, since a verified-but-diverged checkpoint would become
        the rollback target and trap the ladder."""
        if not self.cfg.enabled:
            return True
        return self._classify([value]) is None

    # -- ladder ------------------------------------------------------------
    def check_window(self, step: int, losses: list[float]) -> GuardDecision:
        """Judge one log window. The caller (trainer) executes the action
        and emits the telemetry; the guard only decides and keeps score."""
        if not self.cfg.enabled or not losses:
            return GuardDecision("ok")
        reason = self._classify(losses)
        if reason is None:
            self._consecutive_bad = 0
            self._means.append(sum(losses) / len(losses))
            self._clean_windows += 1
            forgive = int(self.cfg.clean_steps_to_forgive)
            if (
                forgive > 0
                and self.rollbacks_done > 0
                and self._clean_windows >= forgive
            ):
                self.rollbacks_done = 0
                self._clean_windows = 0
            return GuardDecision("ok")
        self._clean_windows = 0
        self._consecutive_bad += 1
        if (
            self.cfg.skip_nonfinite_updates
            and reason == "non-finite loss"
            and self._consecutive_bad <= self.cfg.max_consecutive_skips
        ):
            return GuardDecision(
                "tolerate",
                f"{reason} @ step {step}; updates skipped device-side "
                f"({self._consecutive_bad}/{self.cfg.max_consecutive_skips} "
                "windows tolerated)",
            )
        if not self.can_rollback:
            return GuardDecision("warn", f"{reason} @ step {step}; no "
                                 "checkpoint to roll back to")
        if self.rollbacks_done >= self.cfg.max_rollbacks:
            return GuardDecision(
                "abort",
                f"{reason} @ step {step} after {self.rollbacks_done} "
                "rollbacks — policy ladder exhausted",
            )
        return GuardDecision("rollback", f"{reason} @ step {step}")

    def note_rollback(self) -> None:
        """The trainer completed a rollback this guard ordered."""
        self.rollbacks_done += 1
        self._consecutive_bad = 0

    def note_rollback_failed(self) -> None:
        """The trainer could NOT execute an ordered rollback (no intact
        checkpoint). Burns a ladder rung anyway: without this, a run whose
        checkpoints are all gone re-decides 'rollback' at every boundary
        forever and the abort rung is unreachable — it would train on NaN
        params to completion."""
        self.rollbacks_done += 1
