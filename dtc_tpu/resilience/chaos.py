"""Deterministic fault injection (the chaos harness).

Every recovery path in the trainer is exercisable on CPU by configuring a
``resilience.chaos`` block: at a chosen step (or raw-document index) the
injector raises a transient data-stream error, stalls the stream, corrupts
the just-written checkpoint, poisons the model state + loss with NaN, or
delivers a real SIGTERM (the preemption signal). Each fault fires EXACTLY
once per injector instance, so a healed run does not re-injure itself after
rollback — and the tier-1 tests can assert one ``recovery`` event per
injection.

The injector sits on the production code paths, never beside them: the data
fault is raised underneath the same retry wrapper that heals real network
errors, the checkpoint corruption hits real Orbax files on disk, and the
simulated preemption goes through the process signal handler.

The serving runtime (``dtc_tpu/serve/``) consults the ``serve_*`` hooks at
its iteration boundaries: mid-request preemption and KV cache-block
corruption drive the evict→re-prefill recovery path, the scheduler stall
drives the serving hung-step watchdog, and poisoned logits drive the
finite-check + retry-from-pre-step-cache path — all asserted
token-for-token identical to an uninjected run in tests/test_serve.py.

The fleet router (``dtc_tpu/serve/router.py``) consults the ``fleet_*``
hooks the same way at ITS boundaries: replica kill drives cross-replica
failover (re-prefill on survivors, token-identical, zero silent drops),
the replica stall drives the replica-level hung-step watchdog + degraded
routing, and the partition drives retry-with-backoff, missed-heartbeat
accounting, and the dead-replica escalation — tests/test_router.py and
scripts/fleet_smoke.py.
"""

from __future__ import annotations

import os
import time
from typing import Any, Iterator

from dtc_tpu.resilience.errors import ChaosInjectedError
from dtc_tpu.resilience.events import RecoveryBus


class ChaosInjector:
    """Config-driven, fire-once fault injection hooks.

    Construct one per training run from ``ResilienceConfig.chaos``; the
    trainer threads it into the data pipeline and consults it at step
    boundaries. With ``cfg.enabled`` false every hook is an inert no-op
    (the trainer normally skips constructing one at all).
    """

    def __init__(self, cfg: Any, bus: RecoveryBus | None = None):
        self.cfg = cfg
        self.bus = bus
        self._fired: set[str] = set()

    def _fire(self, key: str, **fields: Any) -> bool:
        """True exactly once per fault key; posts a ``chaos`` event."""
        if not self.cfg.enabled or key in self._fired:
            return False
        self._fired.add(key)
        if self.bus is not None:
            self.bus.post("chaos", kind=key, **fields)
        print(f"[dtc_tpu] CHAOS: injecting {key} ({fields})")
        return True

    # ---- data plane (runs on the stream/prefetch thread) -----------------
    def wrap_raw_documents(
        self, it: Iterator[Any], start_index: int
    ) -> Iterator[Any]:
        """Wrap a raw document iterator whose first item has absolute index
        ``start_index``. Raises a transient :class:`ChaosInjectedError`
        (or sleeps ``stall_s``) immediately BEFORE the configured 1-based
        document index — i.e. after ``N-1`` documents were consumed, which
        is exactly where a mid-stream network fault lands."""
        index = start_index
        for item in it:
            if index + 1 == self.cfg.data_stall_at_doc and self._fire(
                "data_stall", doc=index + 1, stall_s=self.cfg.stall_s
            ):
                time.sleep(self.cfg.stall_s)
            if index + 1 == self.cfg.data_error_at_doc and self._fire(
                "data_error", doc=index + 1
            ):
                raise ChaosInjectedError(
                    f"chaos: injected transient stream fault before raw "
                    f"document {index + 1}"
                )
            index += 1
            yield item

    # ---- trainer plane ---------------------------------------------------
    def maybe_poison(self, step: int, state: Any, loss: Any):
        """After the update at ``step``: replace the loss with NaN and blow
        up the parameters (NaN), simulating a diverged/poisoned update the
        anomaly guard must detect and roll back. Shapes and shardings are
        preserved so the step executable is untouched."""
        if step != self.cfg.nan_at_step or not self._fire("nan_loss", step=step):
            return state, loss
        import jax
        import jax.numpy as jnp

        nan_params = jax.tree.map(
            lambda p: p * jnp.asarray(float("nan"), dtype=p.dtype), state.params
        )
        return state.replace(params=nan_params), loss * float("nan")

    def should_preempt(self, step: int) -> bool:
        """Simulated preemption: the trainer delivers a real SIGTERM to the
        process, exercising the graceful-stop handler end to end."""
        return step == self.cfg.sigterm_at_step and self._fire(
            "sigterm", step=step
        )

    # ---- serving plane (dtc_tpu/serve/ — iteration numbers are 1-based
    # scheduler iterations; the engine consults these at iteration
    # boundaries so every fault lands on the production scheduler path) --
    def serve_stall(self, it: int) -> float:
        """Seconds the scheduler loop must stall at iteration ``it`` (0 =
        no fault). The engine sleeps INSIDE its timed iteration, so the
        serving hung-step watchdog sees a real outlier."""
        if it == self.cfg.serve_stall_at_step and self._fire(
            "serve_stall", iteration=it, stall_s=self.cfg.stall_s
        ):
            return self.cfg.stall_s
        return 0.0

    def serve_preempt(self, it: int) -> bool:
        """Mid-request preemption: the engine evicts its newest active
        request (pages freed, requeued) and must recover it bit-exactly
        via re-prefill. Fires once at the FIRST iteration >= the
        configured step where the engine consults it — the engine only
        asks when it has an active request to preempt, so the shot is
        never consumed (nor a chaos event emitted) with nothing to act
        on."""
        return (
            0 < self.cfg.serve_preempt_at_step <= it
            and self._fire("serve_preempt", iteration=it)
        )

    def serve_corrupt_page(self, it: int) -> bool:
        """KV cache-block corruption: the engine damages a COMPLETED page
        of its oldest active request on device, which the page-checksum
        verifier must catch before the next token computed from it is
        emitted. Same deferred-fire contract as :meth:`serve_preempt`
        (consulted only when a completed page exists)."""
        return (
            0 < self.cfg.serve_corrupt_page_at_step <= it
            and self._fire("serve_corrupt_page", iteration=it)
        )

    def serve_poison_logits(self, it: int) -> bool:
        """Poisoned decode logits: the step's observed finite-check reads
        false (as if the device returned NaN), driving the engine's
        production retry path; the retry recomputes from the pre-step
        cache and must land token-identical. Same deferred-fire contract
        (the engine consults inside a decode attempt, so an in-flight
        batch exists)."""
        return (
            0 < self.cfg.serve_poison_logits_at_step <= it
            and self._fire("serve_poison_logits", iteration=it)
        )

    # ---- fleet plane (dtc_tpu/serve/router.py — iteration numbers are
    # 1-based ROUTER iterations; the router consults these at its own
    # boundaries so every fault lands on the production routing paths) --
    def fleet_kill_replica(self, it: int) -> bool:
        """Kill one replica mid-traffic: the router declares
        ``fleet_target_replica`` dead and fails its queued AND in-flight
        requests over to survivors (re-submitting prompt+generated-so-far
        through the re-prefill path — completed requests must come out
        token-identical, the rest typed; zero silent drops). Deferred-fire
        contract like :meth:`serve_preempt`: the router consults only
        while traffic is in flight."""
        return (
            0 < self.cfg.fleet_kill_replica_at_step <= it
            and self._fire(
                "fleet_kill_replica", iteration=it,
                replica=self.cfg.fleet_target_replica,
            )
        )

    def fleet_stall_replica(self, it: int) -> float:
        """Seconds ``fleet_target_replica``'s next step must stall (0 =
        no fault). The stall lands OUTSIDE the engine's timed iteration —
        a wedged transport, not a slow kernel — so the REPLICA-level
        hung-step watchdog must flag it and the router's health machine
        mark the replica degraded (routed around, not killed)."""
        if 0 < self.cfg.fleet_stall_replica_at_step <= it and self._fire(
            "fleet_stall_replica", iteration=it,
            replica=self.cfg.fleet_target_replica, stall_s=self.cfg.stall_s,
        ):
            return self.cfg.stall_s
        return 0.0

    def fleet_partition(self, it: int) -> int:
        """Network partition: ``fleet_target_replica`` is unreachable for
        the returned number of router iterations (0 = no fault). Short
        partitions heal (retry-with-backoff + missed-heartbeat
        accounting); one outliving ``heartbeat_miss_limit`` escalates to
        the kill/failover path."""
        if 0 < self.cfg.fleet_partition_at_step <= it and self._fire(
            "fleet_partition", iteration=it,
            replica=self.cfg.fleet_target_replica,
            iters=self.cfg.fleet_partition_iters,
        ):
            return self.cfg.fleet_partition_iters
        return 0

    # ---- elastic plane (dtc_tpu/resilience/elastic.py + snapshot.py,
    # ISSUE 15 — step numbers are trainer loop steps; the trainer consults
    # these each step so every fault lands on the production elastic
    # paths: heartbeat detection, ring-mirror fallback, cold-tier
    # verification) ----------------------------------------------------
    def kill_host(self, step: int) -> int | None:
        """Victim virtual host to kill at ``step`` (it stops heartbeating
        forever; the monitor must detect it and the trainer must shrink
        and continue from the in-memory snapshot). None = no fault."""
        if step == self.cfg.kill_host_at_step and self._fire(
            "kill_host", step=step, host=self.cfg.elastic_target_host
        ):
            return self.cfg.elastic_target_host
        return None

    def slow_host(self, step: int) -> tuple[int, int] | None:
        """``(host, straggle_iters)`` when the victim host's heartbeats
        start arriving late at ``step`` — the straggler case: the monitor
        must flag ``host_slow`` and NOT declare it lost (straggle length
        below ``heartbeat_miss_limit`` heals in place)."""
        if step == self.cfg.slow_host_at_step and self._fire(
            "slow_host", step=step, host=self.cfg.elastic_target_host,
            iters=self.cfg.slow_host_iters,
        ):
            return self.cfg.elastic_target_host, self.cfg.slow_host_iters
        return None

    def lose_snapshot(self, step: int) -> int | None:
        """Victim host whose PRIMARY in-memory snapshot copy vanishes at
        ``step`` (host memory loss without host loss): the next restore
        that needs its shards must fall back to the ring mirror."""
        if step == self.cfg.lose_snapshot_at_step and self._fire(
            "lose_snapshot", step=step, host=self.cfg.elastic_target_host
        ):
            return self.cfg.elastic_target_host
        return None

    def maybe_tear_cold_spill(self, step: int, step_dir: str) -> bool:
        """Torn cold-tier spill: truncate the largest file of the
        just-written cold (Orbax) checkpoint at ``step`` — a preemption
        mid-spill. The verified-checkpoint fallback must reject the step
        on the next restore instead of resuming from torn bytes."""
        if step != self.cfg.torn_cold_spill_at_step or not self._fire(
            "torn_cold_spill", step=step
        ):
            return False
        return self._damage_dir(step_dir, "truncate")

    def maybe_corrupt_checkpoint(self, step: int, step_dir: str) -> bool:
        """After the checkpoint at ``step`` was fully written (manifest
        included): damage the largest file under its directory —
        ``truncate`` chops it in half, ``flip`` inverts a mid-file byte
        window — so integrity verification must catch it later."""
        if step != self.cfg.corrupt_ckpt_at_step or not self._fire(
            "ckpt_corrupt", step=step, mode=self.cfg.corrupt_mode
        ):
            return False
        return self._damage_dir(step_dir, self.cfg.corrupt_mode)

    # ---- pool plane (dtc_tpu/pool/ — step numbers are POOL ticks; the
    # PoolManager consults these at its transition boundaries so every
    # fault lands on the production resize/spawn/retire paths) ----------
    def pool_spike_mid_grow(self, it: int) -> int:
        """Request burst (returned size, 0 = no fault) injected while a
        trainer GROW transition is in flight: the pool must either abort
        the grow cleanly (devices return to serving, parked requests
        drain) or complete it and immediately shrink back — in-flight
        requests are never shed silently either way. Deferred-fire
        contract like :meth:`serve_preempt`: the pool consults this only
        while a grow is actually mid-transition, so the shot is never
        wasted on steady state."""
        if 0 < self.cfg.pool_spike_mid_grow_at <= it and self._fire(
            "pool_spike_mid_grow", iteration=it,
            requests=self.cfg.pool_spike_requests,
        ):
            return self.cfg.pool_spike_requests
        return 0

    def pool_kill_mid_shrink(self, it: int) -> int | None:
        """Victim host (None = no fault) that dies while the trainer is
        SURRENDERING devices (shrink in flight): the host's snapshot
        primaries vanish with it, so the restore onto the smaller mesh
        must come from the ring mirror — the surrender is safe because
        redundancy, not the victim, holds the bytes. Deferred-fire: the
        pool consults this only while a shrink is mid-transition."""
        if 0 < self.cfg.pool_kill_mid_shrink_at <= it and self._fire(
            "pool_kill_mid_shrink", iteration=it,
            host=self.cfg.elastic_target_host,
        ):
            return self.cfg.elastic_target_host
        return None

    def pool_kill_draining_replica(self, it: int) -> bool:
        """Kill the replica being retired mid-drain: its in-flight
        requests must fail over to surviving replicas via the PR 12
        router path, token-identical, zero silent drops. Deferred-fire:
        the pool consults this only while a retirement drain is in
        flight (so a draining replica with live requests exists)."""
        return (
            0 < self.cfg.pool_kill_draining_replica_at <= it
            and self._fire("pool_kill_draining_replica", iteration=it)
        )

    @staticmethod
    def _damage_dir(step_dir: str, mode: str) -> bool:
        """Damage the largest file under ``step_dir`` (shared by the
        checkpoint-corruption and torn-cold-spill faults)."""
        target, size = None, -1
        for root, _, files in os.walk(step_dir):
            for name in files:
                p = os.path.join(root, name)
                s = os.path.getsize(p)
                if s > size:
                    target, size = p, s
        if target is None:
            return False
        if mode == "truncate":
            with open(target, "r+b") as f:
                f.truncate(size // 2)
        else:  # flip
            with open(target, "r+b") as f:
                f.seek(size // 2)
                chunk = f.read(min(64, max(size - size // 2, 1)))
                f.seek(size // 2)
                f.write(bytes(b ^ 0xFF for b in chunk))
        return True
