"""Hung-step watchdog.

Two layers, both off the hot path:

- **flagging** (host-side, post-step): each completed step's duration is
  compared against ``factor`` x the trailing median; outliers emit a
  ``hung_step`` telemetry event and can arm a profiler window over the
  following steps so the trace shows WHAT was slow (``profile_on_flag``).
- **hard timeout** (background thread, opt-in via ``hard_timeout_s > 0``):
  a step that never completes — a wedged collective, a deadlocked host —
  cannot be observed post-hoc. The monitor thread dumps every thread's
  stack (the post-mortem a hung pod job never leaves) and interrupts the
  main thread; the trainer converts that into :class:`WatchdogTimeout`
  so the abort is clean (telemetry flushed, signal handlers restored).
"""

from __future__ import annotations

import faulthandler
import os
import threading
import time
from collections import deque
from typing import Any, Callable


class StepWatchdog:
    def __init__(
        self,
        cfg: Any,
        *,
        interrupt: Callable[[], None] | None = None,
        escalate: Callable[[], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg
        self._durations: deque[float] = deque(maxlen=64)
        self._clock = clock
        self.timed_out = False
        self.flags = 0
        # hard-timeout monitor state
        self._armed_at: float | None = None
        self._armed_step: int | None = None
        self._armed_budget: float = cfg.hard_timeout_s
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if interrupt is None:
            import _thread

            interrupt = _thread.interrupt_main
        self._interrupt = interrupt
        if escalate is None:
            def escalate() -> None:
                import signal as _signal

                os.kill(os.getpid(), _signal.SIGABRT)
        self._escalate = escalate

    # -- flagging ----------------------------------------------------------
    def trailing_median(self) -> float | None:
        if len(self._durations) < max(int(self.cfg.min_samples), 1):
            return None
        vals = sorted(self._durations)
        return vals[len(vals) // 2]

    def observe(self, step: int, duration_s: float) -> dict | None:
        """Record a completed step; return flag details when it was a
        ``factor``-x outlier vs the trailing median (else None). The outlier
        itself is NOT added to the history — one hang must not license the
        next."""
        self.disarm()
        med = self.trailing_median()
        if (
            med is not None
            and med > 0
            and duration_s > self.cfg.factor * med
        ):
            self.flags += 1
            return {
                "step": step,
                "duration_s": round(duration_s, 4),
                "median_s": round(med, 4),
                "factor": round(duration_s / med, 2),
            }
        self._durations.append(duration_s)
        return None

    # -- hard timeout ------------------------------------------------------
    def start(self) -> None:
        if self.cfg.hard_timeout_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._monitor, name="dtc-step-watchdog", daemon=True
        )
        self._thread.start()

    def arm(self, step: int, budget_s: float | None = None) -> None:
        """Start the hard-timeout clock for one unit of blocking work.
        ``budget_s`` overrides ``hard_timeout_s`` for work whose healthy
        duration is not step-scale (the trainer's log-boundary fetch waits
        out the whole dispatched window under async dispatch)."""
        if self._thread is None:
            return
        with self._lock:
            self._armed_at = self._clock()
            self._armed_step = step
            self._armed_budget = (
                budget_s if budget_s is not None else self.cfg.hard_timeout_s
            )

    def disarm(self) -> None:
        if self._thread is None:
            return
        with self._lock:
            self._armed_at = None
            self._armed_step = None

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _monitor(self) -> None:
        # Poll at a fraction of the timeout: cheap, and the abort path is
        # seconds-scale anyway.
        poll = max(self.cfg.hard_timeout_s / 10.0, 0.05)
        while not self._stop.wait(poll):
            with self._lock:
                armed_at, step = self._armed_at, self._armed_step
                budget = self._armed_budget
            if armed_at is None:
                continue
            waited = self._clock() - armed_at
            if waited <= budget:
                continue
            self.timed_out = True
            print(
                f"[dtc_tpu] WATCHDOG: step {step} exceeded hard timeout "
                f"({waited:.1f}s > {budget}s); dumping "
                "stacks and aborting"
            )
            try:
                faulthandler.dump_traceback(all_threads=True)
            except Exception:
                pass
            self._interrupt()
            # interrupt_main only lands between Python bytecodes: a main
            # thread wedged INSIDE a C call (a hung collective — the very
            # case this watchdog exists for) never sees it. Give the clean
            # abort a grace window, then escalate to a process kill; the
            # flushed JSONL/CSV prefixes are the crash-survival contract.
            grace = min(30.0, max(self.cfg.hard_timeout_s / 4.0, 1.0))
            if not self._stop.wait(grace):
                with self._lock:
                    still_armed = self._armed_at is not None
                if still_armed:
                    print(
                        "[dtc_tpu] WATCHDOG: clean abort did not land within "
                        f"{grace:.0f}s (main thread wedged in native code); "
                        "escalating to SIGABRT"
                    )
                    self._escalate()
            return
