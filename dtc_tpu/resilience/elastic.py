"""Elastic training: virtual hosts, failure detection, shrink planning.

The reference paper (and PR 2's recovery story) assumes a fixed, healthy
device mesh for the whole run; at pod scale, host loss is the steady
state. This module supplies the pieces the trainer composes into
shrink-and-continue (MegaScale / Gemini style):

- :class:`VirtualHosts` — the in-process emulation of pod hosts: the N
  devices are split into ``n_hosts`` contiguous groups, each "host"
  owning its group plus an in-memory snapshot store
  (``dtc_tpu.resilience.snapshot``). The same seam the serving fleet's
  ``EngineReplica`` handles model (dtc_tpu/serve/replica.py): a real
  multi-host deployment replaces the device-group bookkeeping with
  process indices and the stores with a DCN transport; the trainer's
  recovery logic is unchanged.

  HONESTY: on CPU the "hosts" share one process and a killed host's
  devices keep computing until detection (a real pod would hang in the
  next collective — the watchdog hard-timeout path). What IS real:
  detection runs on heartbeats alone (never by peeking at the kill
  flag), recovery reads ONLY surviving hosts' stores, and the restored
  trajectory is bit-checked against a snapshot-replay reference.

- :class:`HostMonitor` — heartbeat failure detection layered on the
  PR 2 watchdog: every live host beats each step; ``miss_limit``
  consecutive missed beats declare the host lost (typed ``host_lost``).
  A hung-step flag from the step watchdog counts as a collective-stall
  signal and ESCALATES detection (one missed beat suffices) — the
  "collective stalled, someone is gone" fast path. A host that beats
  late (chaos ``slow_host_at_step``, a straggler) is flagged
  ``host_slow`` exactly once and must NOT be declared lost.

- :func:`resize_mesh` — rebuild the mesh from a target host set, SHRINK
  or GROW: pipe/model axis sizes are preserved (elastic resize moves
  whole data-parallel groups), the data axis absorbs the targets.
  Raises :class:`ElasticAbort` when no valid mesh exists (targets not
  divisible by the model axis, pipeline runs, dead targets).
  :func:`shrink_mesh` is the survivors-only delegate the trainer's
  shrink-and-continue path has always used.
"""

from __future__ import annotations

from typing import Any

from dtc_tpu.resilience.errors import ElasticAbort


class VirtualHosts:
    """``n_hosts`` contiguous device groups over the process's devices."""

    def __init__(self, n_hosts: int, devices: list | None = None):
        import jax

        devices = list(devices if devices is not None else jax.devices())
        if n_hosts < 2:
            raise ValueError(f"n_virtual_hosts must be >= 2, got {n_hosts}")
        if len(devices) % n_hosts != 0:
            raise ValueError(
                f"{len(devices)} devices do not split into {n_hosts} "
                "equal virtual hosts"
            )
        self.n_hosts = n_hosts
        self.devices = sorted(devices, key=lambda d: d.id)
        self.per_host = len(devices) // n_hosts
        self._host_of = {
            d.id: i // self.per_host for i, d in enumerate(self.devices)
        }
        self.alive: set[int] = set(range(n_hosts))

    def host_of(self, device: Any) -> int:
        return self._host_of[device.id]

    def devices_of(self, host: int) -> list:
        return self.devices[host * self.per_host:(host + 1) * self.per_host]

    def survivor_devices(self) -> list:
        return [d for d in self.devices if self._host_of[d.id] in self.alive]

    def kill(self, host: int) -> None:
        self.alive.discard(host)

    def revive(self, host: int) -> None:
        """Return ``host`` to the alive set (pool GROW hands a host back
        after the serving tenant released it — the emulation of a fresh
        host joining at the same pod slot). The caller is responsible for
        monitor admission: ``alive`` is capacity, not health history."""
        if not 0 <= host < self.n_hosts:
            raise ValueError(f"host {host} outside pool of {self.n_hosts}")
        self.alive.add(host)

    def ring_next(self, host: int) -> int:
        return (host + 1) % self.n_hosts


class HostMonitor:
    """Heartbeat + collective-stall failure detection over virtual hosts.

    ``tick(step)`` records a beat for every host that is actually alive
    (and not mid-straggle); ``poll(step)`` judges by the BEAT HISTORY
    alone — detection never consults the emulation's kill flag, so the
    detector is the same code a real heartbeat transport would drive.
    """

    def __init__(self, hosts: VirtualHosts, *, miss_limit: int = 2):
        self.hosts = hosts
        self.miss_limit = max(int(miss_limit), 1)
        # Roster frozen at CONSTRUCTION (after ``elastic.dead_hosts`` was
        # applied, before any chaos fires): a shrunk RESTART must not
        # "detect" its already-gone hosts, but a host the chaos kills
        # before the first tick — the trainer applies kills ahead of the
        # tick in the same iteration — must still be monitored, or a
        # kill_host_at_step on the first step is never detected at all.
        self._roster = sorted(hosts.alive)
        self._last_beat: dict[int, int] = {}
        self._slow_until: dict[int, int] = {}
        self._lost: set[int] = set()
        self._slow_flagged: set[int] = set()
        self._started_at: int | None = None

    def mark_slow(self, host: int, until_step: int) -> None:
        """Chaos ``slow_host_at_step``: ``host`` beats late (no beats
        through ``until_step``) — straggler fodder for ``poll``."""
        self._slow_until[host] = max(self._slow_until.get(host, 0), until_step)

    def tick(self, step: int) -> None:
        if self._started_at is None:
            # Seed beats for the construction-time roster, NOT the
            # current alive set: a host killed between construction and
            # the first tick must enter the beat table (and then miss
            # every beat) to be detectable.
            self._started_at = step - 1
            for h in self._roster:
                self._last_beat[h] = step - 1
        for h in self.hosts.alive:
            if h not in self._roster:
                continue  # another tenant's host (pool): not ours to beat
            if self._slow_until.get(h, 0) >= step:
                continue  # straggling: the beat does not arrive this step
            self._last_beat[h] = step

    def poll(self, step: int, *, stalled: bool = False) -> list[dict]:
        """Typed detection events for this step.

        ``stalled`` — the step watchdog flagged the current step as hung
        (a wedged collective): escalate, any host already missing a beat
        is declared lost immediately instead of waiting out
        ``miss_limit``. Each host is reported lost (or slow) exactly
        once."""
        events: list[dict] = []
        if self._started_at is None:
            return events
        limit = 1 if stalled else self.miss_limit
        for h in sorted(self._last_beat):
            if h in self._lost:
                continue
            missed = step - self._last_beat[h]
            if missed >= limit:
                self._lost.add(h)
                events.append({
                    "kind": "host_lost", "host": h, "missed": missed,
                    "last_beat": self._last_beat[h], "detected_at": step,
                    "escalated": bool(stalled),
                })
            elif missed >= 1 and h not in self._slow_flagged:
                self._slow_flagged.add(h)
                events.append({
                    "kind": "host_slow", "host": h, "missed": missed,
                    "last_beat": self._last_beat[h], "detected_at": step,
                })
        return events

    @property
    def lost(self) -> set[int]:
        return set(self._lost)

    # ---- roster transitions (pool GROW/SHRINK) ---------------------------
    def admit(self, host: int, *, step: int) -> None:
        """Add ``host`` to the monitored roster (pool GROW: the serving
        tenant released the host and the trainer is absorbing it).

        A host this monitor has DECLARED LOST is refused: a grow must
        never resurrect a host the detector believes dead — the pool's
        emulation would silently launder a failure into fresh capacity.
        The pool hands back a different host (or nothing) instead."""
        if host in self._lost:
            raise ElasticAbort(
                f"cannot admit host {host}: declared lost at beat "
                f"{self._last_beat.get(host, '?')} — a grow must not "
                "resurrect a dead host"
            )
        if host not in self._roster:
            self._roster = sorted(set(self._roster) | {host})
        # Seed the beat NOW: the host is healthy at admission, and the
        # next missed beat (not the whole pre-admission gap) starts the
        # miss count.
        self._last_beat[host] = step
        self._slow_flagged.discard(host)

    def retire(self, host: int) -> None:
        """Remove ``host`` from the roster (pool SHRINK: the trainer is
        deliberately surrendering the host to the serving tenant).
        Deliberate surrender is not death: the host leaves the beat
        table entirely so ``poll`` never declares it lost, and a later
        ``admit`` of the same host is legal."""
        self._roster = sorted(set(self._roster) - {host})
        self._last_beat.pop(host, None)
        self._slow_until.pop(host, None)
        self._slow_flagged.discard(host)


def resize_mesh(
    mesh: Any, hosts: VirtualHosts, target_hosts: set[int] | None = None
) -> Any:
    """Rebuild the mesh over ``target_hosts``' devices — SHRINK or GROW.

    ``target_hosts=None`` means "every currently alive host" (the
    shrink-and-continue path: survivors absorb the data axis). An
    explicit host set is the pool's resize seam: GROW is
    shrink-and-continue in reverse — the caller restores the newest
    complete snapshot onto the larger mesh with fresh NamedShardings.

    Resize happens along the "data" axis only (whole DP/FSDP groups
    enter or leave); "model" (TP) groups must stay intact — a target
    set that breaks every TP group leaves no valid mesh.
    """
    from dtc_tpu.parallel.mesh import build_mesh

    if target_hosts is None:
        devices = hosts.survivor_devices()
    else:
        bad = set(target_hosts) - hosts.alive
        if bad:
            raise ElasticAbort(
                f"resize targets dead/unknown hosts {sorted(bad)} "
                f"(alive: {sorted(hosts.alive)})"
            )
        devices = [
            d for h in sorted(target_hosts) for d in hosts.devices_of(h)
        ]
    if not devices:
        raise ElasticAbort("no surviving target hosts to rebuild a mesh from")
    shape = dict(mesh.shape)
    pipe = int(shape.get("pipe", 1))
    model = int(shape.get("model", 1))
    if pipe > 1:
        raise ElasticAbort(
            "elastic resize is not supported under pipeline parallelism "
            "(stage-chunked params cannot re-shard onto a different "
            "stage count); use a mesh with pipe == 1"
        )
    if len(devices) % model != 0:
        raise ElasticAbort(
            f"{len(devices)} target devices do not preserve the "
            f"model={model} (TP) axis; no valid resized mesh exists"
        )
    new_data = len(devices) // model
    return build_mesh((1, new_data, model), devices=devices)


def shrink_mesh(mesh: Any, hosts: VirtualHosts) -> Any:
    """Rebuild the mesh over the surviving hosts' devices (the original
    shrink-and-continue entrypoint — now a thin delegate of
    :func:`resize_mesh` with the survivors as the target set)."""
    return resize_mesh(mesh, hosts, target_hosts=None)
