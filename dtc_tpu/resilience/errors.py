"""Typed failure taxonomy for the resilience subsystem.

Every recovery path needs a *catchable* error class: the trainer's policy
ladder, the prefetch consumer, and external launchers all branch on these
types instead of string-matching arbitrary exceptions. ``ChaosInjectedError``
deliberately subclasses ``ConnectionError`` so injected data faults travel
the exact same retry/classification path a real HuggingFace network error
would — the chaos harness tests the production path, not a parallel one.
"""

from __future__ import annotations


class ResilienceError(RuntimeError):
    """Base class for failures raised by the resilience subsystem itself."""


class DataStreamError(ResilienceError):
    """The input stream is dead beyond repair: retries exhausted, or the
    prefetch worker thread died without delivering its error sentinel.
    Carries the last underlying exception as ``__cause__`` when known."""


class AnomalyAbort(ResilienceError):
    """The anomaly guard's policy ladder is exhausted (``max_rollbacks``
    rollbacks already spent, training still diverging) — a clean abort so
    an external supervisor can restart from the last verified checkpoint."""


class WatchdogTimeout(ResilienceError):
    """A training step exceeded the watchdog's hard timeout."""


class SnapshotIncompleteError(ResilienceError):
    """An in-memory snapshot cannot be reconstructed from the surviving
    hosts' stores (a needed shard's primary owner and ring mirror are
    both gone, or every surviving copy fails its integrity hash). The
    trainer falls back to the cold (disk) tier."""


class ElasticAbort(ResilienceError):
    """Elastic recovery is impossible: no valid smaller mesh exists for
    the survivors (TP groups broken, pipeline runs), or the batch cannot
    shard over the shrunk data axis. A supervisor must restart the job
    on a reprovisioned slice from the last cold-tier checkpoint."""


class ChaosTargetError(ResilienceError):
    """A chaos fault became actionable but its configured victim does not
    exist at FIRE time (e.g. ``fleet_target_replica`` names a replica id
    that was never spawned or has already been retired). With spawn/
    retire the replica set is dynamic, so this is judged when the fault
    fires, not at config construction — and a stale target is a typed
    error, never a silent no-op: a chaos drill that silently skips its
    injection would report a vacuous pass."""


class ChaosInjectedError(ConnectionError):
    """Deterministic fault raised by the chaos harness into the data plane.

    Subclasses ``ConnectionError`` on purpose: the stream retry wrapper
    must treat it exactly like a real transient network failure."""
