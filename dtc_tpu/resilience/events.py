"""Thread-safe recovery event bus.

Recovery actions happen in places that have no telemetry handle: the stream
retry wrapper fires on the prefetch worker thread, checkpoint fallback fires
inside ``CheckpointManager`` before the trainer's ``Telemetry`` even exists.
They post here; the trainer drains the bus at step/log boundaries into the
telemetry stream (``chaos`` / ``recovery`` / ``anomaly`` event kinds), so
every recovery action lands in the JSONL shard with a step attribution and
nothing in the data plane ever imports the obs subsystem.
"""

from __future__ import annotations

import threading
from typing import Any


class RecoveryBus:
    """Bounded, thread-safe list of pending (etype, fields) event tuples."""

    def __init__(self, maxlen: int = 1024):
        self._lock = threading.Lock()
        self._events: list[tuple[str, dict[str, Any]]] = []
        self._dropped = 0
        self._maxlen = maxlen

    def post(self, etype: str, **fields: Any) -> None:
        with self._lock:
            if len(self._events) >= self._maxlen:
                # A runaway retry loop must not turn the bus into a memory
                # leak; drops are counted and surfaced on the next drain.
                self._dropped += 1
                return
            self._events.append((etype, dict(fields)))

    def drain(self) -> list[tuple[str, dict[str, Any]]]:
        with self._lock:
            out, self._events = self._events, []
            if self._dropped:
                out.append(("recovery", {
                    "action": "bus_overflow", "dropped": self._dropped,
                }))
                self._dropped = 0
            return out
