"""Position-preserving retry for re-openable streams.

HuggingFace streaming iterators die on transient network faults and cannot
be resumed in place — but they CAN be re-opened with ``ds.skip(n)``. The
wrapper here exploits that: it tracks the absolute index of the next item
to consume, and on failure re-invokes a ``factory(index)`` that must return
a fresh iterator starting at exactly that index. Consumers therefore see
one uninterrupted, exactly-once item sequence across any number of
underlying re-opens — which is what keeps a healed training run bit-exact
with an unfaulted one (the chaos tests assert this parity).

Backoff is exponential with jitter and bounded attempts; ``sleep`` and
``rng`` are injectable so tests run in microseconds.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Iterator

from dtc_tpu.resilience.errors import DataStreamError


def backoff_schedule(
    attempt: int, base_s: float, max_s: float, jitter: float,
    rng: random.Random | None = None,
) -> float:
    """Delay before retry ``attempt`` (1-based): ``base * 2**(attempt-1)``
    capped at ``max_s``, +/- ``jitter`` fraction of itself."""
    delay = min(base_s * (2.0 ** (attempt - 1)), max_s)
    if jitter > 0:
        r = rng if rng is not None else random
        delay *= 1.0 + jitter * (2.0 * r.random() - 1.0)
    return max(delay, 0.0)


def resilient_iterator(
    factory: Callable[[int], Iterator[Any]],
    *,
    start_index: int = 0,
    max_attempts: int = 5,
    backoff_s: float = 1.0,
    backoff_max_s: float = 30.0,
    jitter: float = 0.1,
    transient: tuple[type[BaseException], ...] = (Exception,),
    on_event: Callable[..., None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    cancel: Any = None,
) -> Iterator[Any]:
    """Yield ``factory(start_index)``'s items; self-heal on transient faults.

    ``factory(index)`` must return an iterator whose first item is the
    stream's absolute item ``index`` — re-opens never replay or drop items.
    The consecutive-failure counter resets after every successful yield, so
    ``max_attempts`` bounds attempts per fault, not per stream lifetime.
    ``on_event(etype, **fields)`` (a :class:`RecoveryBus` post) receives one
    ``recovery``/``stream_retry`` record per re-open.

    Raises :class:`DataStreamError` (with the last fault as ``__cause__``)
    once ``max_attempts`` consecutive attempts fail. ``StopIteration`` from
    the source is genuine end-of-stream and is never retried.

    ``cancel`` (a ``threading.Event``) makes the backoff interruptible: a
    consumer tearing the pipeline down (trainer rollback) sets it, and the
    wrapper ends the stream immediately instead of sleeping out up to
    ``backoff_max_s`` as an orphan that would re-open the source and post
    stale retry events.
    """
    index = start_index
    attempts = 0
    it = None
    while True:
        try:
            if it is None:
                it = factory(index)
            item = next(it)
        except StopIteration:
            return
        except transient as e:
            attempts += 1
            if attempts >= max_attempts:
                raise DataStreamError(
                    f"data stream failed {attempts} consecutive attempts at "
                    f"item {index}; giving up ({type(e).__name__}: {e})"
                ) from e
            if cancel is not None and cancel.is_set():
                return  # pipeline torn down: no event, no re-open
            delay = backoff_schedule(attempts, backoff_s, backoff_max_s, jitter, rng)
            if on_event is not None:
                on_event(
                    "recovery", action="stream_retry", index=index,
                    attempt=attempts, backoff_s=round(delay, 3),
                    error=f"{type(e).__name__}: {e}",
                )
            if cancel is not None:
                if cancel.wait(delay):
                    return  # cancelled mid-backoff
            else:
                sleep(delay)
            it = None  # re-open at the exact failure position
            continue
        attempts = 0
        index += 1
        yield item
