"""Position-preserving retry for re-openable streams.

HuggingFace streaming iterators die on transient network faults and cannot
be resumed in place — but they CAN be re-opened with ``ds.skip(n)``. The
wrapper here exploits that: it tracks the absolute index of the next item
to consume, and on failure re-invokes a ``factory(index)`` that must return
a fresh iterator starting at exactly that index. Consumers therefore see
one uninterrupted, exactly-once item sequence across any number of
underlying re-opens — which is what keeps a healed training run bit-exact
with an unfaulted one (the chaos tests assert this parity).

Backoff is exponential with jitter and bounded attempts; ``sleep`` and
``rng`` are injectable so tests run in microseconds.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Iterator

from dtc_tpu.resilience.errors import DataStreamError


def backoff_schedule(
    attempt: int, base_s: float, max_s: float, jitter: float,
    rng: random.Random | None = None,
) -> float:
    """Delay before retry ``attempt`` (1-based): ``base * 2**(attempt-1)``
    capped at ``max_s``, +/- ``jitter`` fraction of itself."""
    delay = min(base_s * (2.0 ** (attempt - 1)), max_s)
    if jitter > 0:
        r = rng if rng is not None else random
        delay *= 1.0 + jitter * (2.0 * r.random() - 1.0)
    return max(delay, 0.0)


def resilient_iterator(
    factory: Callable[[int], Iterator[Any]],
    *,
    start_index: int = 0,
    max_attempts: int = 5,
    backoff_s: float = 1.0,
    backoff_max_s: float = 30.0,
    jitter: float = 0.1,
    max_elapsed_s: float = 0.0,
    transient: tuple[type[BaseException], ...] = (Exception,),
    on_event: Callable[..., None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    cancel: Any = None,
    clock: Callable[[], float] = time.monotonic,
) -> Iterator[Any]:
    """Yield ``factory(start_index)``'s items; self-heal on transient faults.

    ``factory(index)`` must return an iterator whose first item is the
    stream's absolute item ``index`` — re-opens never replay or drop items.
    The consecutive-failure counter resets after every successful yield, so
    ``max_attempts`` bounds attempts per fault, not per stream lifetime.
    ``on_event(etype, **fields)`` (a :class:`RecoveryBus` post) receives one
    ``recovery``/``stream_retry`` record per re-open.

    Raises :class:`DataStreamError` (with the last fault as ``__cause__``)
    once ``max_attempts`` consecutive attempts fail. ``StopIteration`` from
    the source is genuine end-of-stream and is never retried.

    ``cancel`` (a ``threading.Event``) makes the backoff interruptible: a
    consumer tearing the pipeline down (trainer rollback) sets it, and the
    wrapper ends the stream immediately instead of sleeping out up to
    ``backoff_max_s`` as an orphan that would re-open the source and post
    stale retry events.

    ``max_elapsed_s`` (> 0) caps ONE fault episode in wall-clock terms: the
    time since the episode's first failure, plus the delay a further retry
    would add, may not exceed it. ``max_attempts`` alone lets a stalled
    dependency hold the consumer for attempts x ``backoff_max_s`` — and
    because the attempt counter resets on every successful yield, a source
    that limps (one item per near-exhausted episode) can stall the
    consumer unboundedly in aggregate while never exhausting attempts. The
    elapsed cap turns "how long can a fault stall us" into one number.
    """
    index = start_index
    attempts = 0
    episode_start: float | None = None
    it = None
    while True:
        try:
            if it is None:
                it = factory(index)
            item = next(it)
        except StopIteration:
            return
        except transient as e:
            attempts += 1
            now = clock()
            if episode_start is None:
                episode_start = now
            if attempts >= max_attempts:
                raise DataStreamError(
                    f"data stream failed {attempts} consecutive attempts at "
                    f"item {index}; giving up ({type(e).__name__}: {e})"
                ) from e
            if cancel is not None and cancel.is_set():
                return  # pipeline torn down: no event, no re-open
            delay = backoff_schedule(attempts, backoff_s, backoff_max_s, jitter, rng)
            if (
                max_elapsed_s > 0
                and (now - episode_start) + delay > max_elapsed_s
            ):
                raise DataStreamError(
                    f"data stream fault episode exceeded max_elapsed_s="
                    f"{max_elapsed_s} at item {index} (attempt {attempts}, "
                    f"{now - episode_start:.3f}s elapsed + {delay:.3f}s "
                    f"backoff pending); giving up ({type(e).__name__}: {e})"
                ) from e
            if on_event is not None:
                on_event(
                    "recovery", action="stream_retry", index=index,
                    attempt=attempts, backoff_s=round(delay, 3),
                    error=f"{type(e).__name__}: {e}",
                )
            if cancel is not None:
                if cancel.wait(delay):
                    return  # cancelled mid-backoff
            else:
                sleep(delay)
            it = None  # re-open at the exact failure position
            continue
        attempts = 0
        episode_start = None
        index += 1
        yield item


def retry_call(
    fn: Callable[[], Any],
    *,
    max_attempts: int = 3,
    backoff_s: float = 0.05,
    backoff_max_s: float = 1.0,
    jitter: float = 0.0,
    max_elapsed_s: float = 0.0,
    transient: tuple[type[BaseException], ...] = (Exception,),
    on_event: Callable[..., None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    clock: Callable[[], float] = time.monotonic,
) -> Any:
    """Call ``fn`` with the same backoff/attempt/elapsed discipline as
    :func:`resilient_iterator`, for one-shot operations instead of streams
    — the serving runtime's transient-fault wrapper (a decode step whose
    logits read back non-finite, a prefill hit by an injected fault).

    ``fn`` must be safe to re-invoke from scratch (the serving engine
    re-runs its step from the pre-step cache, which JAX immutability keeps
    alive for free). Returns ``fn()``'s value on the first success; after
    ``max_attempts`` consecutive failures — or when the episode would
    outlive ``max_elapsed_s`` (> 0) — re-raises the LAST underlying
    exception unchanged, so callers keep their typed-error taxonomy.
    ``on_event`` receives one ``("recovery", action="call_retry", ...)``
    record per re-attempt (a :class:`RecoveryBus` post signature).
    """
    start = clock()
    attempt = 0
    while True:
        try:
            return fn()
        except transient as e:
            attempt += 1
            delay = backoff_schedule(attempt, backoff_s, backoff_max_s, jitter, rng)
            exhausted = attempt >= max_attempts or (
                max_elapsed_s > 0 and (clock() - start) + delay > max_elapsed_s
            )
            if exhausted:
                raise
            if on_event is not None:
                on_event(
                    "recovery", action="call_retry", attempt=attempt,
                    backoff_s=round(delay, 3), error=f"{type(e).__name__}: {e}",
                )
            sleep(delay)
