"""Fault-tolerant training subsystem (SURVEY §5 "failure detection" row).

The production posture (ROADMAP north star: preemptible pods, heavy
traffic) treats recovery as a first-class subsystem, not an afterthought:

- :mod:`~dtc_tpu.resilience.chaos` — deterministic fault injection so every
  recovery path runs in tier-1 CPU tests;
- :mod:`~dtc_tpu.resilience.guard` — loss-anomaly policy ladder
  (skip-update -> rollback to verified checkpoint -> clean abort);
- :mod:`~dtc_tpu.resilience.retry` — position-preserving stream retry
  (heals transient HF-streaming faults bit-exactly) + the generic
  elapsed-capped ``retry_call`` the serving runtime reuses;
- :mod:`~dtc_tpu.resilience.watchdog` — hung-step flagging + hard timeout;
- :mod:`~dtc_tpu.resilience.snapshot` — async in-memory snapshots with
  peer-redundant (ring-mirrored) per-host shard stores — the hot recovery
  tier (ISSUE 15);
- :mod:`~dtc_tpu.resilience.elastic` — virtual hosts, heartbeat failure
  detection, and shrink-mesh planning for elastic shrink-and-continue;
- :mod:`~dtc_tpu.resilience.events` — thread-safe bus that feeds recovery
  actions into the telemetry stream;
- :mod:`~dtc_tpu.resilience.errors` — the catchable failure taxonomy.

See README "Fault tolerance" for recovery semantics and the chaos config
reference.
"""

from dtc_tpu.resilience.chaos import ChaosInjector
from dtc_tpu.resilience.elastic import HostMonitor, VirtualHosts, shrink_mesh
from dtc_tpu.resilience.errors import (
    AnomalyAbort,
    ChaosInjectedError,
    DataStreamError,
    ElasticAbort,
    ResilienceError,
    SnapshotIncompleteError,
    WatchdogTimeout,
)
from dtc_tpu.resilience.events import RecoveryBus
from dtc_tpu.resilience.guard import AnomalyGuard, GuardDecision
from dtc_tpu.resilience.retry import resilient_iterator, retry_call
from dtc_tpu.resilience.snapshot import (
    InMemorySnapshot,
    RedundancyPlan,
    SnapshotStore,
)
from dtc_tpu.resilience.watchdog import StepWatchdog

__all__ = [
    "AnomalyAbort",
    "AnomalyGuard",
    "ChaosInjectedError",
    "ChaosInjector",
    "DataStreamError",
    "ElasticAbort",
    "GuardDecision",
    "HostMonitor",
    "InMemorySnapshot",
    "RecoveryBus",
    "RedundancyPlan",
    "ResilienceError",
    "SnapshotIncompleteError",
    "SnapshotStore",
    "StepWatchdog",
    "VirtualHosts",
    "WatchdogTimeout",
    "resilient_iterator",
    "retry_call",
    "shrink_mesh",
]
