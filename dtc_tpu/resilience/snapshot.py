"""Async in-memory snapshots with peer-redundant shard stores.

Gemini-style (SOSP '23) hot-tier checkpointing for the trainer: on a step
cadence the TrainState is copied device->host WITHOUT blocking the hot
loop, tagged with step + sha256, and stored per *virtual host* (the
in-process emulation of a pod host — ``dtc_tpu.resilience.elastic``).
Recovery from a poisoned update or a lost host then costs at most one
step of lost work, instead of a rollback to the (now cold-tier, slower
cadence) Orbax checkpoint on disk.

Zero-blocking-sync contract (the hostsync lint stays green on the
trainer): :meth:`SnapshotStore.begin` dispatches a DEVICE-side copy of
every leaf (``jnp.copy`` — async dispatch, never a host round-trip; the
copy is what makes the buffers safe against the next step's donation),
starts the device->host transfer with ``copy_to_host_async``, and hands
the copy to a background commit thread. The thread — not the hot loop —
materializes numpy shards, hashes them, and files them into the virtual
hosts' stores. ``begin`` is double-buffered: one commit landing plus one
queued behind it; further cadence ticks are SKIPPED (counted, surfaced
as a ``snapshot`` event field), so a slow commit can never queue
unbounded device copies.

Peer redundancy (computed from the leaf shardings, i.e. from the mesh
axes + rule table — see :func:`RedundancyPlan.from_snapshot`):

- **DP-replicated leaves** — every host's store holds a full copy; any
  one survivor reconstructs them.
- **FSDP-sharded leaves** — each host holds only its own shard, so the
  host's whole shard-set is additionally MIRRORED to its ring neighbor
  ``(h+1) % n_hosts``. Losing host ``h`` is recoverable as long as its
  neighbor survives; :meth:`RedundancyPlan.recovery_set` names the
  minimal surviving host set needed to reconstruct full state (and
  raises :class:`SnapshotIncompleteError` when no such set exists — the
  caller then falls back to the cold tier).

The transport is the same in-process seam the serving fleet's
``EngineReplica`` handles use (dtc_tpu/serve/replica.py): stores are
plain per-host dicts today; a real DCN transport replaces the dict
filing in ``_commit`` without touching the trainer.
"""

from __future__ import annotations

import hashlib
import queue
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from dtc_tpu.resilience.errors import SnapshotIncompleteError

PyTree = Any

#: Per-dimension (start, stop) tuple identifying one shard of a leaf.
ShardKey = tuple


def shard_key(index: tuple, shape: tuple) -> ShardKey:
    """Serialize an ``addressable_shards[i].index`` slice tuple into a
    hashable (start, stop) tuple per dimension (scalars -> ``()``)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _sha(arr: np.ndarray) -> str:
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


@dataclass
class LeafMeta:
    """Static description of one state leaf, enough to re-shard it onto a
    DIFFERENT mesh: global shape/dtype plus the PartitionSpec its array
    carried (axis NAMES survive a mesh resize; sizes do not)."""

    path: str
    shape: tuple
    dtype: Any
    spec: Any  # jax.sharding.PartitionSpec


@dataclass
class InMemorySnapshot:
    """One committed hot-tier snapshot.

    ``primary[host][path][key]`` holds host ``host``'s own numpy shards;
    ``mirror[host]`` holds the full shard-set of its ring-PREVIOUS host
    (i.e. host ``h``'s shards are mirrored at ``(h+1) % n_hosts``).
    ``shard_sha`` records the commit-time hash of every distinct
    ``(path, key)`` shard — restore re-hashes whichever copy it actually
    uses, so a damaged store (chaos ``lose_snapshot``, bit rot) can never
    silently reconstruct wrong state.
    """

    step: int
    n_hosts: int
    meta: dict = field(default_factory=dict)
    leaves: list[LeafMeta] = field(default_factory=list)
    treedef: Any = None
    primary: dict[int, dict[str, dict[ShardKey, np.ndarray]]] = field(
        default_factory=dict
    )
    mirror: dict[int, dict[str, dict[ShardKey, np.ndarray]]] = field(
        default_factory=dict
    )
    shard_sha: dict[tuple[str, ShardKey], str] = field(default_factory=dict)
    sha256: str = ""
    # False when some leaf's filed shards do not tile its full extent —
    # a commit taken AFTER a host died (its shards could not be stored
    # anywhere). Incomplete snapshots are never recovery candidates:
    # :meth:`SnapshotStore.latest` skips them, which is exactly the
    # <=1-step-lost-work bound (the last COMPLETE snapshot predates the
    # kill by at most one cadence tick).
    complete: bool = True

    def nbytes(self) -> int:
        return sum(
            a.nbytes
            for store in self.primary.values()
            for shards in store.values()
            for a in shards.values()
        )


@dataclass
class RedundancyPlan:
    """Which hosts can reconstruct which leaves of a snapshot.

    ``kind[path]`` is ``"replicated"`` (every host holds a full copy —
    the DP case) or ``"sharded"`` (hosts hold disjoint shards — the FSDP
    case, protected by the ring mirror)."""

    n_hosts: int
    kind: dict[str, str]

    @classmethod
    def from_snapshot(cls, snap: InMemorySnapshot) -> "RedundancyPlan":
        kind: dict[str, str] = {}
        for leaf in snap.leaves:
            full = tuple((0, d) for d in leaf.shape)
            # Replicated iff every host's primary holds the full-extent
            # shard of this leaf.
            replicated = all(
                full in snap.primary.get(h, {}).get(leaf.path, {})
                for h in range(snap.n_hosts)
                if snap.primary.get(h)
            ) and any(snap.primary.get(h) for h in range(snap.n_hosts))
            kind[leaf.path] = "replicated" if replicated else "sharded"
        return cls(n_hosts=snap.n_hosts, kind=kind)

    def recovery_set(
        self, snap: InMemorySnapshot, alive: set[int]
    ) -> dict[str, list[tuple[int, str, ShardKey]]]:
        """Minimal surviving source set per leaf: a list of
        ``(host, tier, key)`` reads (tier ``"primary"`` or ``"mirror"``)
        that together reconstruct the leaf's full extent. Raises
        :class:`SnapshotIncompleteError` when some shard survives
        nowhere among ``alive`` (primary AND mirror both gone)."""
        out: dict[str, list[tuple[int, str, ShardKey]]] = {}
        needed = {leaf.path: set() for leaf in snap.leaves}
        for path, key in snap.shard_sha:
            needed[path].add(key)
        for leaf in snap.leaves:
            picks: list[tuple[int, str, ShardKey]] = []
            if self.kind.get(leaf.path) == "replicated":
                full = tuple((0, d) for d in leaf.shape)
                src = self._find(snap, leaf.path, full, alive)
                if src is None:
                    raise SnapshotIncompleteError(
                        f"snapshot step {snap.step}: replicated leaf "
                        f"{leaf.path} survives on no alive host {sorted(alive)}"
                    )
                picks.append((src[0], src[1], full))
            else:
                for key in sorted(needed[leaf.path]):
                    src = self._find(snap, leaf.path, key, alive)
                    if src is None:
                        raise SnapshotIncompleteError(
                            f"snapshot step {snap.step}: shard {key} of "
                            f"{leaf.path} survives on no alive host "
                            f"{sorted(alive)} (primary owner and ring "
                            "mirror both lost)"
                        )
                    picks.append((src[0], src[1], key))
            out[leaf.path] = picks
        return out

    @staticmethod
    def _find(
        snap: InMemorySnapshot, path: str, key: ShardKey, alive: set[int]
    ) -> tuple[int, str] | None:
        for h in sorted(alive):
            if key in snap.primary.get(h, {}).get(path, {}):
                return (h, "primary")
        for h in sorted(alive):
            if key in snap.mirror.get(h, {}).get(path, {}):
                return (h, "mirror")
        return None


class SnapshotStore:
    """Double-buffered async snapshotter over a set of virtual hosts.

    ``hosts`` is a :class:`dtc_tpu.resilience.elastic.VirtualHosts` (or
    anything with ``n_hosts`` and ``host_of(device) -> int``).
    ``on_event`` (typically a :class:`RecoveryBus` post) receives one
    ``snapshot`` record per commit — the commit happens on the worker
    thread, so events ride the bus, never a Telemetry handle.
    """

    def __init__(
        self,
        hosts: Any,
        *,
        keep: int = 4,
        on_event: Callable[..., None] | None = None,
    ):
        self.hosts = hosts
        self.on_event = on_event
        self._committed: deque[InMemorySnapshot] = deque(maxlen=max(keep, 1))
        self._queue: queue.Queue = queue.Queue()
        self._pending = 0
        self._lock = threading.Lock()
        self.skipped = 0          # cadence ticks dropped (commit in flight)
        self.commits = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="dtc-snapshot-commit", daemon=True
        )
        self._thread.start()

    # ---- hot-loop side (no host syncs) -----------------------------------
    def begin(self, step: int, state: PyTree, meta: dict | None = None) -> bool:
        """Dispatch an async snapshot of ``state`` tagged ``step``.

        Device-side ``jnp.copy`` per leaf (the copy, not the live state,
        is transferred — so the next step's donation can reuse the live
        buffers while the transfer is still in flight), then
        ``copy_to_host_async``, then hand-off to the commit thread.
        Returns False (and counts a skip) while a previous commit is
        still pending — double-buffering, bounded memory."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            # Double-buffered: one commit landing + one queued behind it.
            # A third cadence tick is SKIPPED (counted), so a slow commit
            # thread bounds in-flight device copies at two snapshots —
            # and the <=1-step-lost-work gate holds as long as a commit
            # takes under two steps, without ever blocking the hot loop.
            if self._pending >= 2:
                self.skipped += 1
                return False
            self._pending += 1
        flat, treedef = jax.tree_util.tree_flatten_with_path(state)
        paths = ["/".join(_key_names(p)) for p, _ in flat]
        copies = []
        for _, leaf in flat:
            c = jnp.copy(leaf)
            try:
                c.copy_to_host_async()
            except AttributeError:  # older jax.Array without the method
                pass
            copies.append(c)
        # Alive set frozen NOW, on the hot loop's thread: a dead host can
        # store nothing, and the commit thread must judge by the roster as
        # of the snapshot's step, not as of commit time.
        alive = set(getattr(self.hosts, "alive", range(self.hosts.n_hosts)))
        self._queue.put((step, paths, copies, treedef, dict(meta or {}), alive))
        return True

    # ---- commit thread ---------------------------------------------------
    def _worker(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            try:
                self._commit(*job)
            except Exception as e:  # a failed commit must not kill training
                if self.on_event is not None:
                    self.on_event(
                        "recovery", action="snapshot_commit_failed",
                        step=job[0], reason=f"{type(e).__name__}: {e}",
                    )
            finally:
                with self._lock:
                    self._pending -= 1
                self._queue.task_done()

    def _commit(self, step, paths, copies, treedef, meta, alive) -> None:
        n = self.hosts.n_hosts
        snap = InMemorySnapshot(
            step=step, n_hosts=n, meta=meta, treedef=treedef,
            primary={h: {} for h in range(n)},
        )
        digest = hashlib.sha256()
        for path, arr in zip(paths, copies):
            spec = getattr(arr.sharding, "spec", None)
            snap.leaves.append(
                LeafMeta(path=path, shape=tuple(arr.shape),
                         dtype=arr.dtype, spec=spec)
            )
            for shard in arr.addressable_shards:
                host = self.hosts.host_of(shard.device)
                if host not in alive:
                    # A dead host stores nothing. If the shard exists only
                    # there, this snapshot comes out incomplete below and
                    # is excluded from recovery — the honest emulation of
                    # "no complete checkpoint can form after the host died".
                    continue
                key = shard_key(shard.index, arr.shape)
                store = snap.primary[host].setdefault(path, {})
                if key in store:
                    continue  # replicated leaf: one copy per host suffices
                data = np.asarray(shard.data)
                store[key] = data
                if (path, key) not in snap.shard_sha:
                    snap.shard_sha[(path, key)] = _sha(data)
        # Completeness: the distinct filed shards of every leaf must tile
        # its full extent (shards from one sharding are disjoint, so a
        # volume check is exact).
        covered: dict[str, int] = {}
        for (path, key) in snap.shard_sha:
            vol = 1
            for a, b in key:
                vol *= b - a
            covered[path] = covered.get(path, 0) + (vol if key else 1)
        for leaf in snap.leaves:
            full = 1
            for d in leaf.shape:
                full *= d
            if covered.get(leaf.path, 0) < max(full, 1):
                snap.complete = False
                break
        for (path, key), h in sorted(snap.shard_sha.items()):
            digest.update(path.encode())
            digest.update(repr(key).encode())
            digest.update(h.encode())
        snap.sha256 = digest.hexdigest()
        # Ring mirror: host h's shard-set also lives at the next ALIVE
        # host after h (ring order). Dict of references — the arrays are
        # written once and never mutated; a real transport serializes
        # them over DCN here instead.
        live = sorted(h for h in range(n) if snap.primary.get(h))
        for h in live:
            for off in range(1, n):
                peer = (h + off) % n
                if peer in alive:
                    if peer != h:
                        dst = snap.mirror.setdefault(peer, {})
                        for path, shards in snap.primary[h].items():
                            dst.setdefault(path, {}).update(shards)
                    break
        if snap.complete:
            self._committed.append(snap)
        # An incomplete commit (taken after a host died) is REPORTED but
        # never retained: it can never be a recovery target, and letting
        # it into the bounded keep-ring would evict the complete
        # snapshots recovery actually needs (keep=2 with miss_limit=2
        # would otherwise lose both complete candidates to the two
        # post-kill partials before detection even fires).
        self.commits += 1
        if self.on_event is not None:
            self.on_event(
                "snapshot", step=step, sha256=snap.sha256[:16],
                bytes=snap.nbytes(), skipped=self.skipped, tier="memory",
                complete=snap.complete,
            )

    # ---- consumer side ---------------------------------------------------
    def drain(self) -> None:
        """Block until every queued commit has landed (recovery paths call
        this OUTSIDE the hot loop, before choosing a restore target)."""
        self._queue.join()

    def latest(self, max_step: int | None = None) -> InMemorySnapshot | None:
        """Newest COMPLETE committed snapshot (optionally at or below
        ``max_step`` — the anomaly path restores from BEFORE the first
        poisoned loss). Incomplete commits (taken after a host died) are
        never candidates."""
        for snap in reversed(self._committed):
            if not snap.complete:
                continue
            if max_step is None or snap.step <= max_step:
                return snap
        return None

    def drop_primary(self, host: int) -> bool:
        """Chaos hook (``lose_snapshot_at_step``): ``host``'s snapshot
        RAM is lost — its primary store AND the mirror shards it held
        for its ring-previous host vanish from EVERY retained snapshot
        (dropping only the primary would let a drill "recover" from
        mirror bytes the fault claims were destroyed — the emulation
        must never cheat). A recovery before the next complete commit
        must fall back to the victim's OWN mirror at its ring-next host.
        Commits AFTER the drop are fresh writes and land intact, so the
        fault only bites when configured at (or just before) the failure
        it composes with — the tests pin it to the kill step. Pending
        commits are drained first so the drop covers the snapshot a
        recovery would pick."""
        self.drain()
        dropped = False
        for snap in self._committed:
            if snap.primary.get(host) or snap.mirror.get(host):
                snap.primary[host] = {}
                snap.mirror[host] = {}
                dropped = True
        return dropped

    def restore(
        self, snap: InMemorySnapshot, alive: set[int], mesh: Any
    ) -> tuple[PyTree, bool]:
        """Reconstruct the full state from surviving copies and place it on
        ``mesh`` (the CURRENT mesh — possibly smaller than the one the
        snapshot was taken on) via fresh NamedShardings. Returns
        ``(state, used_mirror)``. Every shard read is re-hashed against
        its commit-time sha256; a mismatch excludes that copy (falling
        back to the peer) and, with no intact copy left, raises
        :class:`SnapshotIncompleteError`."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from dtc_tpu.train.train_step import normalize_spec

        plan = RedundancyPlan.from_snapshot(snap)
        sources = plan.recovery_set(snap, alive)
        used_mirror = False
        leaves_out = []
        for leaf in snap.leaves:
            full = tuple((0, d) for d in leaf.shape)
            out: np.ndarray | None = None
            for host, tier, key in sources[leaf.path]:
                store = (snap.primary if tier == "primary" else snap.mirror)
                data = store[host][leaf.path][key]
                if _sha(data) != snap.shard_sha[(leaf.path, key)]:
                    # Damaged copy: try the other tier / another host.
                    alt = self._intact_copy(snap, leaf.path, key, alive)
                    if alt is None:
                        raise SnapshotIncompleteError(
                            f"snapshot step {snap.step}: every surviving "
                            f"copy of {leaf.path} shard {key} fails its "
                            "integrity hash"
                        )
                    host, tier, data = alt
                if tier == "mirror":
                    used_mirror = True
                if key == full:
                    out = data
                    break
                if out is None:
                    out = np.empty(leaf.shape, dtype=data.dtype)
                out[tuple(slice(a, b) for a, b in key)] = data
            spec = normalize_spec(
                leaf.spec if leaf.spec is not None else P(), mesh
            )
            leaves_out.append(
                jax.device_put(out, NamedSharding(mesh, spec))
            )
        state = jax.tree_util.tree_unflatten(snap.treedef, leaves_out)
        return state, used_mirror

    @staticmethod
    def _intact_copy(snap, path, key, alive):
        for h in sorted(alive):
            for tier, store in (("primary", snap.primary),
                                ("mirror", snap.mirror)):
                data = store.get(h, {}).get(path, {}).get(key)
                if data is not None and _sha(data) == snap.shard_sha[(path, key)]:
                    return h, tier, data
        return None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._thread.join(timeout=5.0)


def _key_names(path: tuple) -> list[str]:
    names = []
    for k in path:
        if hasattr(k, "key"):
            names.append(str(k.key))
        elif hasattr(k, "name"):
            names.append(str(k.name))
        elif hasattr(k, "idx"):
            names.append(str(k.idx))
        else:
            names.append(str(k))
    return names
