"""Request model for the serving runtime: state machine, typed failures.

Every request admitted to :class:`dtc_tpu.serve.engine.ServingEngine`
walks one state machine::

    QUEUED --> PREFILL --> DECODE --> DONE
       |          \\          |\\
       |           \\         | +--> EVICTED --> PREFILL  (pages reclaimed /
       |            \\        |      preempted / corrupted: re-queued, then
       |             \\       |      re-prefilled on re-admission —
       |              \\      |      bit-exact resume, a RECOVERY path)
       |               +-----+----> EXPIRED              (deadline/TTL)
       +--> SHED                                          (overload policy)
       +--> EXPIRED                                       (died waiting)

plus FAILED for retry-exhausted internal faults. Terminal states are
DONE / SHED / EXPIRED / FAILED; EVICTED is transient and observable (the
request re-queues holding its already-generated tokens, its state stays
EVICTED while it waits, and re-admission re-prefills; like any queued
request it may still be shed or expire there). Rejection at ``submit()`` (queue full, request cannot fit
the cache) raises immediately and the request never enters the machine.

The failure taxonomy mirrors ``dtc_tpu/resilience/errors.py``: every
non-success outcome is a *catchable type* carried on the
:class:`ServeResult` (or raised at submit), never a silent drop — the
chaos acceptance test asserts exactly this.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Sequence


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    DONE = "done"
    EVICTED = "evicted"
    EXPIRED = "expired"
    SHED = "shed"
    FAILED = "failed"


#: States from which a request will never run again.
TERMINAL_STATES = frozenset(
    {RequestState.DONE, RequestState.EXPIRED, RequestState.SHED,
     RequestState.FAILED}
)


class ServeError(RuntimeError):
    """Base class for serving-runtime failures."""


class QueueFullError(ServeError):
    """Admission control: the bounded queue is at ``queue_depth``. Raised
    at ``submit()`` — typed backpressure, the caller decides whether to
    retry later or surface 429-equivalent to its client."""


class RequestTooLargeError(ServeError):
    """The request cannot ever run: prompt + max_new_tokens exceeds the
    model's ``max_seq_len``, or its page footprint exceeds the whole
    pool. Raised at ``submit()``."""


class ShedError(ServeError):
    """Dropped by the overload-shedding policy (lowest priority / longest
    queued past the shed watermark) — the graceful-degradation path that
    keeps p99 bounded for the requests that remain."""


class DeadlineExceededError(ServeError):
    """The request outlived its deadline/TTL — in the queue or mid-decode
    (cancellation frees its slot and pages immediately)."""


class RequestFailedError(ServeError):
    """An internal fault outlived the retry budget (see
    ``ServeConfig.retry``); carries the last underlying error as
    ``__cause__`` when known."""


class TransientStepError(ServeError):
    """A decode/prefill step produced unusable output (non-finite logits —
    a poisoned device buffer). Retryable: the engine re-runs the step from
    the pre-step cache via ``resilience.retry.retry_call``."""


class UnknownAdapterError(ServeError):
    """The request names an adapter that is not resident in the engine's
    adapter store (or the model was built without adapter support).
    Raised at ``submit()`` — load the adapter first
    (``ServingEngine.load_adapter``). At the fleet level the router
    converts this into re-load-or-reroute (it holds registered factor
    trees); only when no replica holds the factors AND none were
    registered does the request end typed with this as the cause —
    NEVER silently served on slot-0 base weights."""


class EngineClosedError(ServeError):
    """The engine is shut down (``ServingEngine.shutdown()``) or draining:
    new submissions are refused, and any request still unfinished when the
    drain budget runs out ends typed with this error — the graceful-stop
    contract (stop admitting, finish or typed-evict, drain the recovery
    bus, dump the flight recorder) the trainer has had since PR 2."""


class FleetSaturatedError(QueueFullError):
    """Fleet-level backpressure: every live replica's admission queue is
    at depth (or the replica that holds a required resource is full).
    A ``QueueFullError`` subclass so single-engine callers' typed-429
    handling works unchanged against the router."""


class ReplicaUnreachableError(ServeError):
    """A replica did not answer (network partition / dead process in the
    multi-host picture; the chaos ``fleet_partition`` kind in-process).
    Transient from the router's point of view: retried with backoff via
    ``resilience.retry.retry_call``, then routed around; a replica that
    stays unreachable past the heartbeat-miss budget is declared dead and
    its requests fail over to survivors."""


class AdapterStoreFullError(ServeError):
    """``load_adapter`` found every tenant slot held by an adapter with
    in-flight requests — nothing is LRU-evictable. Typed backpressure:
    drain or wait, never a silent overwrite of a live tenant's factors."""


@dataclasses.dataclass
class Request:
    """One generation request.

    ``shared_prefix_len`` marks the first N prompt tokens as a shareable
    prefix (a common system prompt): concurrent requests with an identical
    prefix reuse its KV pages from the prefix store instead of
    re-prefilling it. Prefix entries are scoped PER ADAPTER — the same
    token prefix under two tenants holds two store entries, because their
    KV bytes differ. ``deadline_s`` is relative to submit time and
    overrides the config default (None = use default; 0 = no deadline).
    Higher ``priority`` is better; sheds take the lowest first.
    ``adapter`` names a tenant LoRA adapter previously loaded with
    ``ServingEngine.load_adapter`` (None = the base model); the adapter
    stays pinned in the store from submit to the terminal state.
    """

    rid: str
    prompt: Sequence[int]
    max_new_tokens: int
    priority: int = 0
    deadline_s: float | None = None
    eos_id: int | None = None
    shared_prefix_len: int = 0
    adapter: str | None = None

    def __post_init__(self) -> None:
        if len(self.prompt) < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must be >= 1")
        if not 0 <= self.shared_prefix_len <= len(self.prompt):
            raise ValueError(
                f"request {self.rid}: shared_prefix_len "
                f"{self.shared_prefix_len} outside [0, len(prompt)]"
            )


@dataclasses.dataclass
class ServeResult:
    """Terminal record of one request — tokens, typed error, SLO timings.

    ``tokens`` holds whatever was generated before the terminal state
    (complete for DONE, partial for EXPIRED/SHED/FAILED). ``error`` is
    None iff state is DONE. Wait/latency fields are None until the
    corresponding edge happened.
    """

    rid: str
    state: RequestState
    tokens: list[int]
    error: ServeError | None = None
    submitted_t: float | None = None
    admitted_t: float | None = None      # last (re-)admission
    first_token_t: float | None = None
    finished_t: float | None = None
    n_evictions: int = 0
    n_retries: int = 0
    degraded: bool = False               # max_new_tokens shrunk at admission
    adapter: str | None = None           # tenant adapter (None = base)
    # Cross-replica failover hops (router resubmissions of prompt +
    # generated-so-far onto a survivor). 0 for a request that never left
    # its first replica; in-replica evictions count in n_evictions.
    n_hops: int = 0
    # Eviction re-queue time: the next req.queued trace span starts here
    # instead of at submit (cleared on re-admission; never in summary()).
    # Set per HOP too — a failover resubmission restarts the queued span
    # at the hop, while ttft_s stays anchored at the ORIGINAL submit, so
    # fleet TTFT histograms include (never under-report) failover cost.
    requeued_t: float | None = None
    # Speculative decoding (ISSUE 19): draft proposals this request saw
    # and how many the target accepted — 0/0 on a spec-off engine.
    # Counts survive eviction/failover (they describe work done, and a
    # re-prefill re-derives tokens, not proposals).
    n_spec_proposed: int = 0
    n_spec_accepted: int = 0

    @property
    def queue_wait_s(self) -> float | None:
        if self.submitted_t is None or self.admitted_t is None:
            return None
        return self.admitted_t - self.submitted_t

    @property
    def ttft_s(self) -> float | None:
        """Submit -> first generated token (includes queueing + prefill)."""
        if self.submitted_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t

    @property
    def ms_per_token(self) -> float | None:
        """Mean decode interval after the first token (serving ms/token)."""
        if (
            self.first_token_t is None or self.finished_t is None
            or len(self.tokens) < 2
        ):
            return None
        return (self.finished_t - self.first_token_t) / (len(self.tokens) - 1) * 1e3

    @property
    def accept_rate(self) -> float | None:
        """Accepted / proposed draft tokens (ISSUE 19); None when the
        request never decoded under speculation."""
        if self.n_spec_proposed <= 0:
            return None
        return self.n_spec_accepted / self.n_spec_proposed

    def summary(self) -> dict[str, Any]:
        """JSON-ready record for telemetry / bench rows."""
        r3 = lambda v: None if v is None else round(v, 6)  # noqa: E731
        return {
            "rid": self.rid,
            "state": self.state.value,
            "n_tokens": len(self.tokens),
            "error": type(self.error).__name__ if self.error else None,
            "queue_wait_s": r3(self.queue_wait_s),
            "ttft_s": r3(self.ttft_s),
            "ms_per_token": r3(self.ms_per_token),
            "n_evictions": self.n_evictions,
            "n_retries": self.n_retries,
            "n_hops": self.n_hops,
            "degraded": self.degraded,
            "adapter": self.adapter,
            "n_spec_proposed": self.n_spec_proposed,
            "n_spec_accepted": self.n_spec_accepted,
            "accept_rate": r3(self.accept_rate),
        }
