"""Tenant-aware front-end router over N serving-engine replicas.

The PR 6 single-scheduler guarantees — typed taxonomy (no request ends
without a :class:`ServeResult`), eviction-and-re-prefill as the universal
recovery path, shed/degrade backpressure — lifted from one engine to a
fleet. The router is the piece that turns "an engine" into "a service":

- **placement** — tenant-aware (route a tenant to the replica whose
  AdapterStore already holds its factors: adapter residency as cache
  affinity), prefix-cache-aware (shared system prompts route to the
  replica whose prefix store already holds their KV), least-loaded
  otherwise; degraded / about-to-shed replicas are deprioritized, and a
  replica's own admission bound is *respected*, never overridden — when
  every live replica is full, ``submit()`` raises a typed
  :class:`~dtc_tpu.serve.request.FleetSaturatedError` (fleet-level
  backpressure coordinates the per-replica signals).
- **health** — per-replica heartbeat + the existing hung-step watchdog
  + each engine's SLO monitor drive a ``healthy → degraded → draining →
  dead`` state machine (see :mod:`dtc_tpu.serve.replica`).
- **failover** — the router streams every generated token into its OWN
  per-request record (a transport would too: the router is what returns
  tokens to clients), so a dead replica's queued AND in-flight requests
  re-submit prompt+generated-so-far to survivors through the engine's
  re-prefill path: completed requests come out token-for-token identical
  to a clean run, everything else terminal with a typed ``ServeResult``
  — zero silent drops, chaos-verified (tests/test_router.py,
  scripts/fleet_smoke.py).
- **transient faults** — an unreachable replica (chaos
  ``fleet_partition``) is retried with backoff via
  ``resilience.retry.retry_call``, then routed around; past the
  heartbeat-miss budget it is declared dead and failed over.
- **observability** — each replica's registry carries its replica id as
  the obs process index (per-replica JSONL shards + Perfetto tracks via
  the PR 7 machinery unchanged); the router adds fleet-level
  ``router_ttft_s`` / ``router_ms_per_token`` histograms and a
  ``router_*`` event schema (route / failover / replica_state / reject),
  and the mixed-fleet reducer (:func:`dtc_tpu.obs.aggregate.reduce_shards`)
  rolls per-replica p50/p99 into one fleet view.
"""

from __future__ import annotations

import dataclasses
import signal
import time
from typing import Any, Callable

from dtc_tpu.obs.registry import JsonlSink, MetricsRegistry
from dtc_tpu.obs.trace import FlightRecorder, Tracer
from dtc_tpu.resilience.chaos import ChaosInjector
from dtc_tpu.resilience.events import RecoveryBus
from dtc_tpu.resilience.retry import retry_call
from dtc_tpu.serve.engine import ServingEngine
from dtc_tpu.serve.replica import EngineReplica, ReplicaState
from dtc_tpu.serve.request import (
    TERMINAL_STATES,
    FleetSaturatedError,
    QueueFullError,
    ReplicaUnreachableError,
    Request,
    RequestFailedError,
    RequestState,
    ServeResult,
    UnknownAdapterError,
)

PyTree = Any


@dataclasses.dataclass
class FleetRecord:
    """The router's own copy of one in-flight request's progress — the
    failover source of truth. A dead replica's memory is gone (in the
    multi-host picture); what the router re-submits is what IT observed
    stream back, pulled after every replica step, so the copy is exact
    at every iteration boundary (where kills land)."""

    req: Request
    replica: int
    hops: int = 0
    tokens: list = dataclasses.field(default_factory=list)
    submitted_t: float | None = None
    first_token_t: float | None = None
    n_evictions: int = 0
    n_retries: int = 0
    degraded: bool = False
    n_spec_proposed: int = 0
    n_spec_accepted: int = 0

    def resume_result(self) -> ServeResult:
        """The partial result a survivor resumes from (the engine's
        ``submit(resume=...)`` contract)."""
        return ServeResult(
            rid=self.req.rid, state=RequestState.EVICTED,
            tokens=list(self.tokens), submitted_t=self.submitted_t,
            first_token_t=self.first_token_t, n_evictions=self.n_evictions,
            n_retries=self.n_retries, degraded=self.degraded,
            n_hops=self.hops, adapter=self.req.adapter,
            n_spec_proposed=self.n_spec_proposed,
            n_spec_accepted=self.n_spec_accepted,
        )


class FleetRouter:
    """See module docstring. Construct once per (model, params, config);
    ``submit()`` requests, then drive ``step()`` (or ``run()``) exactly
    like a single engine — the router IS the fleet's scheduler loop."""

    def __init__(
        self,
        model,
        params: PyTree,
        cfg,
        *,
        obs_dir: str = "",
        router_proc: int | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.cfg = cfg
        self.clock = clock
        self.sleep = sleep
        # Retained for dynamic spawn (ISSUE 17): a replica spawned later
        # serves the SAME (model, params) — which is also what makes the
        # engine fn cache a zero-compile spawn.
        self.model = model
        self.params = params
        self._obs_dir = obs_dir
        # Fleet-level registry: process index ONE PAST the replicas by
        # default, so router events/spans land on their own shard/track
        # next to the per-replica ones in every merged view. A caller
        # that spawns replicas dynamically (the pool) passes an explicit
        # router_proc well above any replica id it will ever mint.
        self._proc = cfg.n_replicas if router_proc is None else router_proc
        self.reg = MetricsRegistry(process_index=self._proc)
        if obs_dir:
            self.reg.add_sink(
                JsonlSink(f"{obs_dir}/events.r{self._proc}.jsonl")
            )
        self.tracer = Tracer(self.reg, tid="router")
        self.recorder = self.reg.add_sink(FlightRecorder(256))
        self.bus = RecoveryBus()
        self.chaos = (
            ChaosInjector(cfg.chaos, self.bus) if cfg.chaos.enabled else None
        )

        self.records: dict[str, FleetRecord] = {}   # in flight, fleet-wide
        self.results: dict[str, ServeResult] = {}   # fleet-terminal
        self._adapter_factors: dict[str, PyTree] = {}
        self._bad_it: dict[int, int] = {}   # replica -> last degraded signal
        self._hung_seen: dict[int, int] = {}
        self._rr = 0                        # round-robin cursor
        self._it = 0
        self._sigterm = False
        self._prev_sigterm_handler: Any = None

        # Append-only: a retired/dead replica keeps its slot (DEAD), so
        # replica_id == list index holds across spawn/retire.
        self.replicas: list[EngineReplica] = []
        for _ in range(cfg.n_replicas):
            self.spawn_replica(quiet=True)

    # ------------------------------------------------------------------
    # tenants
    # ------------------------------------------------------------------
    def register_adapter(self, name: str, factors: PyTree) -> None:
        """Make tenant ``name``'s factors available to the FLEET. Loading
        onto a replica is lazy — the first request routed for the tenant
        loads there, and every later request follows the residency
        (adapter affinity). The retained tree is also what failover
        re-loads on a survivor when the tenant's home replica dies."""
        self._adapter_factors[name] = factors

    def _can_serve_adapter(self, rep: EngineReplica, name: str) -> bool:
        return name in rep.resident_adapters() or name in self._adapter_factors

    def _ensure_adapter(self, rep: EngineReplica, req: Request) -> None:
        if req.adapter is None or req.adapter in rep.resident_adapters():
            return
        # May raise AdapterStoreFullError (typed) — the caller routes on.
        rep.engine.load_adapter(
            req.adapter, self._adapter_factors[req.adapter]
        )
        self.reg.counter("router_adapter_loads").inc()
        self.reg.emit(
            "router_adapter_load", adapter=req.adapter,
            replica=rep.replica_id, iteration=self._it,
        )

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _place(
        self, req: Request, exclude: set[int]
    ) -> tuple[EngineReplica | None, str]:
        """Pick a replica for ``req`` (None + a reason when impossible).
        Fleet backpressure by construction: only replicas that would
        ACCEPT the request (accepting state, queue room, able to serve
        its tenant) are candidates — the router coordinates each
        replica's admission/shed/degrade signals, it never overrides
        them."""
        live = [
            r for r in self.replicas
            if r.accepting and r.replica_id not in exclude
        ]
        roomy = [r for r in live if r.queue_room > 0]
        if not roomy:
            return None, "saturated"
        if req.adapter is not None:
            cands = [r for r in roomy if self._can_serve_adapter(r, req.adapter)]
            if not cands:
                return None, "unknown_adapter"
        else:
            cands = roomy

        def cost(r: EngineReplica):
            # Healthy before degraded, headroom before about-to-shed,
            # then least loaded; replica id breaks ties deterministically.
            return (
                r.state is ReplicaState.DEGRADED,
                r.engine.over_shed_watermark,
                r.load,
                r.replica_id,
            )

        if self.cfg.placement == "round_robin":
            self._rr += 1
            return cands[self._rr % len(cands)], "round_robin"
        if self.cfg.placement == "affinity":
            if req.adapter is not None:
                hold = [r for r in cands
                        if req.adapter in r.resident_adapters()]
                if hold:
                    return min(hold, key=cost), "adapter_affinity"
            if req.shared_prefix_len > 0:
                hit = [r for r in cands if r.has_prefix(req)]
                if hit:
                    return min(hit, key=cost), "prefix_affinity"
        return min(cands, key=cost), "least_loaded"

    def _try_submit(
        self, rep: EngineReplica, req: Request, resume: ServeResult | None
    ) -> None:
        """One replica's submit under the transient-fault retry — a
        momentarily unreachable replica (partition healing, transport
        blip) gets ``retry.max_attempts`` with backoff before the router
        moves on to the next candidate."""
        r = self.cfg.retry
        retry_call(
            lambda: rep.submit(req, resume=resume),
            transient=(ReplicaUnreachableError,),
            max_attempts=r.max_attempts, backoff_s=r.backoff_s,
            backoff_max_s=r.backoff_max_s, jitter=r.jitter,
            max_elapsed_s=r.max_elapsed_s, on_event=self._on_retry_event,
            sleep=self.sleep, clock=self.clock,
        )

    def _route(
        self, req: Request, *, resume: ServeResult | None = None,
        exclude: set[int] | None = None,
    ) -> tuple[EngineReplica, str]:
        """Place + submit with route-around: a candidate that turns out
        unreachable (past retries) or full falls out of the pool and the
        next one is tried; when the pool empties the LAST typed error
        (or fleet saturation) surfaces — never a silent drop."""
        tried: set[int] = set(exclude or ())
        last_err: Exception | None = None
        while True:
            rep, reason = self._place(req, exclude=tried)
            if rep is None:
                if last_err is not None:
                    raise last_err
                if reason == "unknown_adapter":
                    raise UnknownAdapterError(
                        f"request {req.rid}: adapter {req.adapter!r} is "
                        "resident on no live replica and no factors were "
                        "registered with the router "
                        "(FleetRouter.register_adapter)"
                    )
                raise FleetSaturatedError(
                    f"request {req.rid}: every live replica's queue is full "
                    f"({len([r for r in self.replicas if r.accepting])} "
                    "accepting)"
                )
            try:
                self._ensure_adapter(rep, req)
                self._try_submit(rep, req, resume)
                return rep, reason
            except (ReplicaUnreachableError, QueueFullError) as e:
                last_err = e
                tried.add(rep.replica_id)
            except Exception as e:
                # AdapterStoreFullError and kin: typed, replica-local —
                # route on; anything genuinely fatal still surfaces when
                # the candidate pool runs dry.
                from dtc_tpu.serve.request import ServeError

                if not isinstance(e, ServeError):
                    raise
                last_err = e
                tried.add(rep.replica_id)

    # ------------------------------------------------------------------
    # the public surface (mirrors ServingEngine)
    # ------------------------------------------------------------------
    def submit(self, req: Request) -> str:
        """Route one request into the fleet. Raises the same typed
        taxonomy as ``ServingEngine.submit`` (plus
        :class:`FleetSaturatedError` — a ``QueueFullError``); an accepted
        rid is guaranteed a terminal fleet ``ServeResult``."""
        if req.rid in self.records:
            raise ValueError(
                f"request {req.rid}: rid already in flight on replica "
                f"{self.records[req.rid].replica}"
            )
        try:
            rep, reason = self._route(req)
        except Exception as e:
            self.reg.counter("router_rejected").inc()
            self.reg.emit(
                "router_reject", rid=req.rid, iteration=self._it,
                error=type(e).__name__,
            )
            raise
        res = rep.engine.results[req.rid]
        self.records[req.rid] = FleetRecord(
            req=req, replica=rep.replica_id, submitted_t=res.submitted_t,
        )
        self.reg.counter("router_routed").inc()
        self.reg.emit(
            "router_route", rid=req.rid, replica=rep.replica_id,
            reason=reason, iteration=self._it, adapter=req.adapter,
        )
        return req.rid

    def step(self) -> bool:
        """One fleet iteration: chaos at the boundary, then one scheduler
        iteration per live replica with token-progress pull, heartbeat
        accounting, and the health state machine. Returns True while any
        request is in flight anywhere."""
        self._it += 1
        if self.chaos is not None:
            # Victims resolve at FIRE time (the replica set is dynamic
            # under spawn/retire): a stale target raises a typed
            # ChaosTargetError instead of clamping to some other replica
            # or silently no-oping.
            stall = self.chaos.fleet_stall_replica(self._it)
            if stall > 0:
                self._chaos_target("fleet_stall_replica").stall(stall)
            part = self.chaos.fleet_partition(self._it)
            if part > 0:
                self._chaos_target("fleet_partition").partition(part)
            # Kill consults only with traffic in flight (the deferred-fire
            # contract: killing an idle fleet would burn the shot on an
            # injection that proves nothing).
            if self.records and self.chaos.fleet_kill_replica(self._it):
                self.kill_replica(
                    self._chaos_target("fleet_kill_replica").replica_id,
                    reason="chaos",
                )
        for rep in self.replicas:
            if rep.state is ReplicaState.DEAD:
                continue
            try:
                rep.step()
            except ReplicaUnreachableError:
                n = rep.miss_beat()
                self.reg.counter("router_missed_heartbeats").inc()
                self.reg.emit(
                    "router_heartbeat_missed", replica=rep.replica_id,
                    missed=n, iteration=self._it,
                )
                if n >= self.cfg.heartbeat_miss_limit:
                    self.kill_replica(
                        rep.replica_id,
                        reason=f"missed {n} heartbeats (partition)",
                    )
                continue
            self._pull(rep)
            self._update_health(rep)
        self._drain_bus()
        return bool(self.records)

    def run(self, *, max_steps: int = 100_000) -> dict[str, ServeResult]:
        """Drive ``step()`` until the fleet is idle or the per-call
        budget runs out; a pending SIGTERM (see ``install_sigterm``)
        triggers the graceful drain instead."""
        for _ in range(max_steps):
            if self._sigterm:
                self.drain()
                break
            if not self.step():
                break
        return self.results

    # ------------------------------------------------------------------
    # progress streaming + terminal accounting
    # ------------------------------------------------------------------
    def _pull(self, rep: EngineReplica) -> None:
        eng = rep.engine
        for rid, rec in self.records.items():
            if rec.replica != rep.replica_id:
                continue
            res = eng.results.get(rid)
            if res is None or res.state in TERMINAL_STATES:
                continue
            rec.tokens = list(res.tokens)
            rec.first_token_t = res.first_token_t
            rec.n_evictions = res.n_evictions
            rec.n_retries = res.n_retries
            rec.degraded = res.degraded
            rec.n_spec_proposed = res.n_spec_proposed
            rec.n_spec_accepted = res.n_spec_accepted
        for rid, res in eng.drain_results().items():
            rec = self.records.pop(rid, None)
            if rec is None:
                continue  # not router-managed (warmup / direct submits)
            self.results[rid] = res
            self._observe_terminal(res, rec.replica)

    def _observe_terminal(self, res: ServeResult, replica: int) -> None:
        self.reg.counter(f"router_{res.state.value}").inc()
        if res.ttft_s is not None:
            self.reg.histogram("router_ttft_s").observe(res.ttft_s)
        if res.state is RequestState.DONE:
            self.reg.counter("router_tokens_out").inc(len(res.tokens))
            if res.ms_per_token is not None:
                self.reg.histogram("router_ms_per_token").observe(
                    res.ms_per_token
                )
        if res.n_hops > 0:
            self.reg.counter("router_failover_terminals").inc()

    # ------------------------------------------------------------------
    # spawn / retire (the pool seam, ISSUE 17)
    # ------------------------------------------------------------------
    def spawn_replica(self, *, quiet: bool = False) -> EngineReplica:
        """Bring up one more replica of the router's (model, params).

        The engine-level fn cache means a same-(model, page_size) spawn
        compiles ZERO times — the new replica shares the already-jitted
        prefill/decode executables, so spawning under load costs queue
        plumbing, not a compile. The new id is the next list slot
        (append-only invariant: replica_id == index, retired replicas
        keep their DEAD slot)."""
        rid = len(self.replicas)
        if rid == self._proc:
            raise ValueError(
                f"replica id {rid} would collide with the router's own "
                f"obs shard (process index {self._proc}); construct the "
                "router with an explicit router_proc above every replica "
                "id it may mint"
            )
        eng = ServingEngine(
            self.model, self.params, self.cfg.serve,
            clock=self.clock, sleep=self.sleep,
        )
        # Per-replica fleet observability rides the existing multi-host
        # machinery: the replica id IS the shard index.
        eng.reg.process_index = rid
        if self._obs_dir:
            eng.reg.add_sink(JsonlSink(f"{self._obs_dir}/events.r{rid}.jsonl"))
        rep = EngineReplica(
            rid, eng, watchdog_cfg=self.cfg.watchdog, clock=self.clock,
        )
        self.replicas.append(rep)
        if not quiet:  # construction-time spawns are not events
            self.reg.counter("router_spawns").inc()
            self.reg.emit(
                "router_replica_spawn", replica=rid, iteration=self._it,
            )
        return rep

    def begin_retire(self, replica_id: int, *, reason: str = "retire") -> None:
        """Stage 1 of retirement: stop routing NEW work to the replica
        (DRAINING is not ``accepting``) while its in-flight requests
        keep decoding through the normal ``step()`` loop. Staged — not
        an atomic drain — so a mid-drain death lands on the production
        failover path instead of inside a blocking loop."""
        rep = self.replicas[replica_id]
        if rep.state in (ReplicaState.DEAD, ReplicaState.DRAINING):
            return
        self._transition(rep, ReplicaState.DRAINING, reason)

    def finish_retire(
        self, replica_id: int, *, reason: str = "retired"
    ) -> bool:
        """Stage 2: once the draining replica is empty, run the engine
        shutdown contract (bus drained, flight dumped) and park it DEAD
        ("retired"). Returns False while in-flight work remains — the
        caller keeps stepping the fleet and asks again."""
        rep = self.replicas[replica_id]
        if rep.state is ReplicaState.DEAD:
            return True
        if rep.state is not ReplicaState.DRAINING:
            raise ValueError(
                f"replica {replica_id} is {rep.state.value}, not draining "
                "(call begin_retire first)"
            )
        if rep.load > 0:
            return False
        rep.engine.shutdown(
            mode="drain", max_steps=self.cfg.drain_max_steps,
            reason=f"{reason} (replica {replica_id})",
        )
        self._pull(rep)
        self._transition(rep, ReplicaState.DEAD, reason)
        self.reg.counter("router_retires").inc()
        return True

    def cancel_retire(
        self, replica_id: int, *, reason: str = "retire_cancelled"
    ) -> None:
        """Roll stage 1 back: a DRAINING replica resumes accepting
        (pool grow-abort — the capacity is needed for serving after
        all). In-flight work was never disturbed, so this is just the
        reverse state edge; anything else than DRAINING is an error
        because there is nothing to cancel."""
        rep = self.replicas[replica_id]
        if rep.state is not ReplicaState.DRAINING:
            raise ValueError(
                f"replica {replica_id} is {rep.state.value}, not draining "
                "(nothing to cancel)"
            )
        self._transition(rep, ReplicaState.HEALTHY, reason)

    @property
    def live_replicas(self) -> list[EngineReplica]:
        return [r for r in self.replicas if r.state is not ReplicaState.DEAD]

    def _chaos_target(self, fault: str) -> EngineReplica:
        """Resolve ``fleet_target_replica`` at FIRE time. With spawn/
        retire the replica set is dynamic, so the bound cannot be judged
        at config construction; a stale/unknown victim is a typed error,
        never a silent no-op (a drill that skips its injection would
        report a vacuous pass)."""
        from dtc_tpu.resilience.errors import ChaosTargetError

        tid = self.cfg.chaos.fleet_target_replica
        rep = self.replicas[tid] if 0 <= tid < len(self.replicas) else None
        if rep is None or rep.state is ReplicaState.DEAD:
            raise ChaosTargetError(
                f"chaos {fault}: fleet_target_replica {tid} is not a live "
                f"replica at fire time (fleet size {len(self.replicas)}, "
                f"live {[r.replica_id for r in self.live_replicas]})"
            )
        return rep

    # ------------------------------------------------------------------
    # health + failover
    # ------------------------------------------------------------------
    def _update_health(self, rep: EngineReplica) -> None:
        rid = rep.replica_id
        hung = rep.hung_flags + (
            rep.engine.reg.counter("serve_hung_steps").value
        )
        bad = hung > self._hung_seen.get(rid, 0) or (
            rep.engine.slo is not None and rep.engine.slo.degrade_active
        )
        self._hung_seen[rid] = hung
        if bad:
            self._bad_it[rid] = self._it
            if rep.state is ReplicaState.HEALTHY:
                self._transition(rep, ReplicaState.DEGRADED, "health_signal")
        elif (
            rep.state is ReplicaState.DEGRADED
            and self._it - self._bad_it.get(rid, 0)
            >= self.cfg.degraded_hold_iters
        ):
            self._transition(rep, ReplicaState.HEALTHY, "recovered")

    def _transition(
        self, rep: EngineReplica, state: ReplicaState, reason: str
    ) -> None:
        prev = rep.state
        rep.mark(state, reason=reason)
        self.reg.counter("router_state_transitions").inc()
        self.reg.emit(
            "router_replica_state", replica=rep.replica_id,
            prev=prev.value, state=state.value, reason=reason,
            iteration=self._it,
        )

    def kill_replica(self, replica_id: int, *, reason: str = "killed") -> None:
        """Declare one replica dead and fail its work over to survivors.
        The chaos ``fleet_kill_replica`` entry point, and what sustained
        heartbeat loss escalates to."""
        rep = self.replicas[replica_id]
        if rep.state is ReplicaState.DEAD:
            return
        # Goodput ledger (ISSUE 16): stamp detection before the failover
        # work starts — the incident bill's wall window opens here.
        t_detect = self.clock()
        self.reg.counter("router_replica_deaths").inc()
        self._transition(rep, ReplicaState.DEAD, reason)
        self._failover(rep, t_detect=t_detect)

    def _failover(
        self, dead: EngineReplica, t_detect: float | None = None
    ) -> None:
        if t_detect is None:
            t_detect = self.clock()
        orphans = [
            (rid, rec) for rid, rec in self.records.items()
            if rec.replica == dead.replica_id
        ]
        for rid, rec in orphans:
            if rec.hops + 1 > self.cfg.failover_max_hops:
                self._terminate(
                    rid, rec, RequestFailedError(
                        f"request {rid}: failover budget exhausted "
                        f"({rec.hops} hops)"
                    ),
                )
                continue
            try:
                rep, _reason = self._route(
                    rec.req, resume=rec.resume_result(),
                    exclude={dead.replica_id},
                )
            except Exception as e:
                from dtc_tpu.serve.request import ServeError

                if not isinstance(e, ServeError):
                    raise
                err = RequestFailedError(
                    f"request {rid}: no survivor could absorb the failover"
                )
                err.__cause__ = e
                self._terminate(rid, rec, err)
                continue
            prev = rec.replica
            rec.replica = rep.replica_id
            rec.hops += 1
            self.reg.counter("router_failovers").inc()
            # t_restored: the orphan is re-homed and resubmitted — the
            # survivor's re-prefill (billed separately, from its span)
            # starts after this. Both stamps are clock reads this path
            # already pays for; the ledger stops inferring the window
            # from neighboring spans.
            self.reg.emit(
                "router_failover", rid=rid, src=prev,
                dst=rep.replica_id, tokens_carried=len(rec.tokens),
                hop=rec.hops, iteration=self._it,
                t_detect=round(t_detect, 6),
                t_restored=round(self.clock(), 6),
            )

    def _terminate(
        self, rid: str, rec: FleetRecord, error: Exception
    ) -> None:
        """Router-side typed terminal for a request NO engine owns any
        more (failover exhausted / no capacity / tenant unservable) —
        the zero-silent-drop backstop: a ``serve_request`` event still
        lands in the stream, from the router's own shard."""
        now = self.clock()
        res = ServeResult(
            rid=rid, state=RequestState.FAILED, tokens=list(rec.tokens),
            error=error, submitted_t=rec.submitted_t,
            first_token_t=rec.first_token_t, finished_t=now,
            n_evictions=rec.n_evictions, n_retries=rec.n_retries,
            n_hops=rec.hops, degraded=rec.degraded, adapter=rec.req.adapter,
            n_spec_proposed=rec.n_spec_proposed,
            n_spec_accepted=rec.n_spec_accepted,
        )
        del self.records[rid]
        self.results[rid] = res
        self._observe_terminal(res, rec.replica)
        self.reg.emit("serve_request", iteration=self._it, **res.summary())
        self.recorder_dump(f"router_terminate: {rid}")

    def recorder_dump(self, reason: str) -> None:
        """In-memory ring only (bare router); kept as a hook so a
        Telemetry-wired deployment can point it at a file path."""

    # ------------------------------------------------------------------
    # drain / shutdown
    # ------------------------------------------------------------------
    def drain(self, *, max_steps: int | None = None) -> dict[str, ServeResult]:
        """Router-initiated graceful drain of the whole fleet: every live
        replica takes the engine shutdown contract (stop admitting,
        finish or typed-evict, bus drained, flight dumped), terminals are
        pulled into the fleet results, and every replica retires DEAD
        ("drained")."""
        ms = self.cfg.drain_max_steps if max_steps is None else max_steps
        for rep in self.replicas:
            if rep.state is ReplicaState.DEAD:
                continue
            self._transition(rep, ReplicaState.DRAINING, "drain")
            rep.engine.shutdown(
                mode="drain", max_steps=ms,
                reason=f"router drain (replica {rep.replica_id})",
            )
            self._pull(rep)
            self._transition(rep, ReplicaState.DEAD, "drained")
        # Anything STILL in records (its replica died unreachable mid-
        # drain) ends typed — draining must leave zero silent drops.
        for rid in list(self.records):
            rec = self.records[rid]
            self._terminate(
                rid, rec,
                RequestFailedError(f"request {rid}: fleet drained"),
            )
        self._drain_bus()
        self.reg.emit("router_drained", iteration=self._it)
        self.reg.flush()
        return self.results

    def install_sigterm(self) -> None:
        """SIGTERM = drain: the serving fleet's preemption contract (the
        trainer has had this since PR 2). The handler only sets a flag —
        ``run()`` performs the drain at the next iteration boundary, so
        no engine state is touched from signal context."""
        def _handler(signum, frame):
            print("[dtc_tpu] SIGTERM: draining serving fleet")
            self._sigterm = True

        self._prev_sigterm_handler = signal.signal(signal.SIGTERM, _handler)

    def restore_sigterm(self) -> None:
        if self._prev_sigterm_handler is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm_handler)
            self._prev_sigterm_handler = None

    # ------------------------------------------------------------------
    # bench/test conveniences
    # ------------------------------------------------------------------
    def warmup(self, prompt, *, max_new_tokens: int = 2) -> None:
        """Run one tiny request through EVERY replica (outside the
        router's records), then reset the latency histograms — the
        fleet-bench equivalent of serve_bench's warm request, so no
        replica pays the jit tax inside a measured window. With the
        engine-level fn cache only the first replica compiles; the rest
        warm their insert/settle paths."""
        for rep in self.replicas:
            if rep.state is ReplicaState.DEAD:
                continue
            rep.engine.submit(Request(
                rid=f"_warm_r{rep.replica_id}", prompt=list(prompt),
                max_new_tokens=max_new_tokens,
            ))
        for _ in range(64):
            busy = False
            for rep in self.replicas:
                if rep.state is not ReplicaState.DEAD:
                    busy |= rep.step()
            if not busy:
                break
        for rep in self.replicas:
            rep.engine.drain_results()
            for name in ("serve_ttft_s", "serve_ms_per_token",
                         "serve_queue_wait_s"):
                rep.engine.reg.histogram(name).reset()
        for name in ("router_ttft_s", "router_ms_per_token"):
            self.reg.histogram(name).reset()

    def fleet_summary(self) -> dict[str, Any]:
        """Fleet + per-replica SLO view (the bench row body): router-level
        p50/p99 over every terminal, per-replica percentiles from each
        engine's own registry histograms."""
        from dtc_tpu.utils.percentile import round_opt as r4

        q = lambda h, p: h.percentile(p)  # noqa: E731
        per = {}
        for rep in self.replicas:
            reg = rep.engine.reg
            per[str(rep.replica_id)] = {
                "state": rep.state.value,
                "dead_reason": rep.dead_reason,
                "done": reg.counter("serve_done").value,
                "evictions": reg.counter("serve_evictions").value,
                "hung_flags": rep.hung_flags,
                "ttft_p50_s": r4(q(reg.histogram("serve_ttft_s"), 0.50)),
                "ttft_p99_s": r4(q(reg.histogram("serve_ttft_s"), 0.99)),
                "ms_per_token_p99": r4(
                    q(reg.histogram("serve_ms_per_token"), 0.99)
                ),
            }
        reg = self.reg
        return {
            "n_replicas": len(self.replicas),
            "replicas": per,
            "routed": reg.counter("router_routed").value,
            "rejected": reg.counter("router_rejected").value,
            "failovers": reg.counter("router_failovers").value,
            "replica_deaths": reg.counter("router_replica_deaths").value,
            "tokens_out": reg.counter("router_tokens_out").value,
            "ttft_p50_s": r4(q(reg.histogram("router_ttft_s"), 0.50)),
            "ttft_p99_s": r4(q(reg.histogram("router_ttft_s"), 0.99)),
            "ms_per_token_p50": r4(
                q(reg.histogram("router_ms_per_token"), 0.50)
            ),
            "ms_per_token_p99": r4(
                q(reg.histogram("router_ms_per_token"), 0.99)
            ),
        }

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------
    def _on_retry_event(self, etype: str, **fields: Any) -> None:
        self.reg.counter("router_retries").inc()
        self.bus.post(etype, **fields)

    def _drain_bus(self) -> None:
        for etype, fields in self.bus.drain():
            if etype == "chaos":
                self.reg.counter("chaos_injections").inc()
            fields.setdefault("iteration", self._it)
            self.reg.emit(etype, **fields)

    def close(self) -> None:
        """Release file sinks (replica shards + the router's own) and
        give back the SIGTERM handler if ``install_sigterm`` took it — a
        retired router must not keep swallowing the process's signals
        (or keep itself alive through the handler closure)."""
        self.restore_sigterm()
        for rep in self.replicas:
            rep.engine.reg.flush()
            rep.engine.reg.close()
        self.reg.flush()
        self.reg.close()
