"""One fleet member: a :class:`ServingEngine` behind a replica handle.

The handle is the seam a multi-host transport plugs into later: the
router only ever talks to ``submit()`` / ``step()`` / the health and
load introspection here, never to the engine's internals — so swapping
the in-process engine for an RPC stub changes this file, not the router.
What the in-process version models faithfully:

- **heartbeat** — a successful ``step()`` IS the beat (it resets the
  ``missed_beats`` counter); a partitioned replica (chaos
  ``fleet_partition``, or a real network fault in the multi-host
  picture) raises
  :class:`~dtc_tpu.serve.request.ReplicaUnreachableError` instead, and
  the router counts the miss toward the death verdict
  (``heartbeat_miss_limit``);
- **hung-step health** — the replica reuses the existing
  :class:`~dtc_tpu.resilience.watchdog.StepWatchdog` (flagging layer)
  over its OWN step durations, one level above the engine's in-loop
  watchdog: an injected fleet stall (or a genuinely wedged replica)
  flags here even when the engine never got to run, and the flag is a
  DEGRADED signal to the router's state machine;
- **state machine** — ``healthy → degraded → draining → dead``:
  degraded replicas keep serving but stop attracting new placements
  (and recover after a clean hold window); draining replicas finish
  their in-flight work then retire; dead replicas are failover sources,
  never targets.

Honesty note: in-process replicas share one host's compute — N replicas
time-slice the same cores, so fleet wall-clocks are SHAPE-only on CPU
(scheduling, failover, accounting are real; absolute throughput is not).
"""

from __future__ import annotations

import enum
import time
from typing import Any, Callable

from dtc_tpu.resilience.watchdog import StepWatchdog
from dtc_tpu.serve.engine import ServingEngine
from dtc_tpu.serve.request import (
    ReplicaUnreachableError,
    Request,
    ServeResult,
)


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"
    DEGRADED = "degraded"    # serving, but routed around for new work
    DRAINING = "draining"    # finishing in-flight; admits nothing new
    DEAD = "dead"            # failover source; never stepped again


class EngineReplica:
    """See module docstring. ``replica_id`` doubles as the obs process
    index: the replica's registry/shard/Perfetto track all carry it, so
    per-replica fleet observability falls out of the existing multi-host
    machinery (PR 7's shard merge) with no new plumbing."""

    def __init__(
        self,
        replica_id: int,
        engine: ServingEngine,
        *,
        watchdog_cfg: Any = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.replica_id = replica_id
        self.engine = engine
        self.clock = clock
        self.state = ReplicaState.HEALTHY
        self.missed_beats = 0
        self.dead_reason: str | None = None
        # Replica-level hung-step flagging over whole step() durations —
        # the fleet stall lands OUTSIDE the engine's timed iteration (a
        # transport stall would too), so the engine's own watchdog cannot
        # see it; this one can.
        self.watchdog = (
            StepWatchdog(watchdog_cfg, clock=clock)
            if watchdog_cfg is not None and watchdog_cfg.enabled else None
        )
        self.hung_flags = 0
        self._stall_s = 0.0        # chaos: next step sleeps this long
        self._partition_left = 0   # chaos: steps of unreachability left

    # -- chaos / transport-fault injection points ------------------------
    def stall(self, seconds: float) -> None:
        self._stall_s = max(self._stall_s, float(seconds))

    def partition(self, iters: int) -> None:
        self._partition_left = max(self._partition_left, int(iters))

    @property
    def partitioned(self) -> bool:
        return self._partition_left > 0

    # -- load / residency introspection (placement inputs) ---------------
    @property
    def accepting(self) -> bool:
        """May receive NEW placements. Degraded replicas still accept
        (they are serving — only deprioritized); draining/dead never."""
        return self.state in (ReplicaState.HEALTHY, ReplicaState.DEGRADED)

    @property
    def queue_room(self) -> int:
        return self.engine.queue_room

    @property
    def load(self) -> int:
        return self.engine.load

    def resident_adapters(self) -> frozenset[str]:
        store = self.engine.adapter_store
        return frozenset(store.snapshot()["resident"]) if store else frozenset()

    def has_prefix(self, req: Request) -> bool:
        return self.engine.has_prefix(req)

    # -- the transport surface -------------------------------------------
    def submit(self, req: Request, *, resume: ServeResult | None = None) -> str:
        if self.state is ReplicaState.DEAD:
            raise ReplicaUnreachableError(
                f"replica {self.replica_id} is dead ({self.dead_reason})"
            )
        if self.partitioned:
            raise ReplicaUnreachableError(
                f"replica {self.replica_id} unreachable (partition, "
                f"{self._partition_left} step(s) left)"
            )
        return self.engine.submit(req, resume=resume)

    def step(self) -> bool:
        """One scheduler iteration on this replica. Raises
        :class:`ReplicaUnreachableError` while partitioned (the router
        counts the missed beat); otherwise stamps the heartbeat and feeds
        the replica-level watchdog. Returns the engine's busy flag."""
        if self.state is ReplicaState.DEAD:
            return False
        if self.partitioned:
            self._partition_left -= 1
            raise ReplicaUnreachableError(
                f"replica {self.replica_id} missed heartbeat (partition)"
            )
        t0 = self.clock()
        stalled = self._stall_s > 0
        if stalled:
            # The injected fleet stall: burns real (injectable) clock time
            # OUTSIDE the engine iteration, like a wedged transport would.
            self.engine.sleep(self._stall_s)
            self._stall_s = 0.0
        busy = self.engine.step()
        dur = self.clock() - t0
        self.missed_beats = 0  # a completed step IS the heartbeat
        # Same discipline as the engine's in-loop watchdog: only WORKING
        # iterations feed the trailing median (idle polling spins are
        # microsecond-scale and would flag every healthy step) — but a
        # stalled step is always observed, idle or not: the stall is the
        # outlier this watchdog exists to flag.
        if self.watchdog is not None and (self.engine._worked or stalled):
            flag = self.watchdog.observe(self.engine._it, dur)
            if flag is not None:
                self.hung_flags += 1
                self.engine.reg.emit(
                    "hung_step", runtime="fleet",
                    replica=self.replica_id, **flag,
                )
        return busy

    def miss_beat(self) -> int:
        """Router-side accounting for a step that never answered."""
        self.missed_beats += 1
        return self.missed_beats

    # -- lifecycle --------------------------------------------------------
    def mark(self, state: ReplicaState, *, reason: str = "") -> None:
        if state is ReplicaState.DEAD:
            self.dead_reason = reason or "killed"
        self.state = state

    def drain(self, *, max_steps: int = 512) -> dict[str, ServeResult]:
        """Router-initiated graceful retirement: the engine's shutdown
        contract (finish or typed-evict, bus drained, flight dumped),
        then DEAD with reason "drained"."""
        self.mark(ReplicaState.DRAINING)
        out = self.engine.shutdown(
            mode="drain", max_steps=max_steps,
            reason=f"replica {self.replica_id} drain",
        )
        self.mark(ReplicaState.DEAD, reason="drained")
        return out
