"""Resilient serving runtime (ROADMAP item 1 — the "millions of users"
layer above the fused decode kernel of PR 4).

- :mod:`~dtc_tpu.serve.engine` — continuous-batching scheduler + the one
  compiled per-slot decode step (admission/eviction never recompile);
- :mod:`~dtc_tpu.serve.paged_cache` — page-pool accounting over the
  packed KV cache, prefix-store pins, integrity-checksum units;
- :mod:`~dtc_tpu.serve.request` — request state machine, typed failure
  taxonomy (rejection/shed/deadline/eviction are typed, never silent);
- :mod:`~dtc_tpu.serve.replica` — one fleet member: an engine behind a
  replica handle with heartbeat, hung-step health, and the
  healthy→degraded→draining→dead state machine;
- :mod:`~dtc_tpu.serve.router` — tenant-aware front-end router over N
  replicas: adapter-residency/prefix cache-affinity placement, fleet
  backpressure, and chaos-verified failover (a dead replica's queued and
  in-flight requests re-prefill on survivors, zero silent drops).

Robustness is the load-bearing design input: overload sheds by policy,
deadlines cancel mid-decode, cache exhaustion / preemption / detected
corruption all take the same verified evict→re-prefill recovery path, and
the chaos harness (``resilience.chaos`` serve hooks) proves each of them
bit-exact in tier-1 CPU tests. See README "Serving runtime".
"""

from dtc_tpu.serve.engine import ServingEngine, init_slot_cache
from dtc_tpu.serve.paged_cache import PageAllocator, pages_for
from dtc_tpu.serve.replica import EngineReplica, ReplicaState
from dtc_tpu.serve.request import (
    AdapterStoreFullError,
    DeadlineExceededError,
    EngineClosedError,
    FleetSaturatedError,
    QueueFullError,
    ReplicaUnreachableError,
    Request,
    RequestFailedError,
    RequestState,
    RequestTooLargeError,
    ServeError,
    ServeResult,
    ShedError,
    TransientStepError,
    UnknownAdapterError,
)
from dtc_tpu.serve.router import FleetRecord, FleetRouter

__all__ = [
    "AdapterStoreFullError",
    "DeadlineExceededError",
    "EngineClosedError",
    "EngineReplica",
    "FleetRecord",
    "FleetRouter",
    "FleetSaturatedError",
    "PageAllocator",
    "QueueFullError",
    "ReplicaUnreachableError",
    "Request",
    "RequestFailedError",
    "RequestState",
    "RequestTooLargeError",
    "ReplicaState",
    "ServeError",
    "ServeResult",
    "ServingEngine",
    "ShedError",
    "TransientStepError",
    "UnknownAdapterError",
    "init_slot_cache",
    "pages_for",
]
