"""Paged KV-cache accounting: a block allocator over the packed cache.

The physical decode cache stays model-native — per slot, one contiguous
packed ``(max_seq_len, H·D)`` row the fused kernel reads directly
(ops/decode_attention.py). What is *paged* is the budget: resident tokens
are accounted in fixed ``page_size`` blocks against one ``total_pages``
pool shared by every in-flight request AND the shared-prefix store, so the
runtime can model (and enforce) a cache smaller than
``slots × max_seq_len`` — the steady state of a loaded server. When the
pool runs out, the engine *evicts*: a victim request's pages are freed and
the request re-queues for bit-exact re-prefill (a verified recovery path,
not a failure).

Pages are also the integrity unit: the engine fingerprints each COMPLETED
page (all ``page_size`` positions written) and re-verifies on a cadence,
so cache-block corruption — injected by chaos or real — is caught and
healed by the same evict→re-prefill path.

Honesty note on prefix sharing: with the dense per-slot layout, a shared
system prompt saves *prefill compute* (computed once, copied device-side
into each slot) and holds ONE pooled copy in the prefix store; the
per-slot copies still occupy their slots' pages and are accounted as
such. True page-level physical sharing needs a gather-capable decode
kernel (future work — the allocator's interface already speaks pages so
that kernel slots in underneath).
"""

from __future__ import annotations

import math
from typing import Iterable


def pages_for(n_tokens: int, page_size: int) -> int:
    """Pages covering ``n_tokens`` resident cache positions."""
    return math.ceil(n_tokens / page_size) if n_tokens > 0 else 0


def kv_token_bytes(model_cfg) -> int:
    """KV payload bytes ONE resident token occupies across all layers —
    the dtype-aware unit the byte-budget pool sizing
    (``ServeConfig.pool_hbm_bytes``) divides by: K + V, packed ``H·D``
    wide, per layer, at ``kv_cache_dtype``. int8 is exactly half bf16
    and a quarter fp32, which is the "quantization doubles page
    capacity" arithmetic the acceptance test pins.

    Honesty note: the int8 scale sidecars (fp32 per position per head,
    ``1/(2·D)`` of the bf16 payload — ~3% at head_dim 32) are metadata
    OUTSIDE this unit, exactly as vLLM-style allocators account block
    storage but not block tables. The decode roofline
    (utils/metrics.decode_step_bytes) counts them, because there they
    are real bandwidth."""
    from dtc_tpu.config.schema import DTYPE_BYTES

    hd = model_cfg.n_heads * model_cfg.head_dim
    return 2 * model_cfg.n_layers * hd * DTYPE_BYTES.get(
        model_cfg.kv_store_dtype, 4
    )


class PageAllocator:
    """Bookkeeping for one page pool: per-owner page counts, free count,
    and LRU-stamped prefix-store pins. Pure host-side accounting — device
    copies are the engine's job — so it unit-tests without a backend."""

    def __init__(self, total_pages: int, page_size: int):
        if total_pages < 1 or page_size < 1:
            raise ValueError("total_pages and page_size must be >= 1")
        self.total_pages = total_pages
        self.page_size = page_size
        self._held: dict[str, int] = {}       # request id -> pages
        self._prefix: dict[tuple, dict] = {}  # prefix key -> {pages, stamp}
        self._stamp = 0                        # LRU clock for prefix entries

    # -- core pool ---------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return self.total_pages - sum(self._held.values()) - sum(
            e["pages"] for e in self._prefix.values()
        )

    def held(self, rid: str) -> int:
        return self._held.get(rid, 0)

    def can_fit(self, n_pages: int) -> bool:
        return n_pages <= self.free_pages

    def alloc(self, rid: str, n_pages: int) -> bool:
        """Grant ``rid`` ``n_pages`` more pages; False (nothing changes)
        when the pool cannot cover them."""
        if n_pages < 0:
            raise ValueError("n_pages must be >= 0")
        if n_pages > self.free_pages:
            return False
        self._held[rid] = self._held.get(rid, 0) + n_pages
        return True

    def ensure(self, rid: str, n_pages_total: int) -> bool:
        """Grow ``rid``'s holding to ``n_pages_total`` (no-op if already
        there); False when the pool cannot cover the growth."""
        need = n_pages_total - self.held(rid)
        return True if need <= 0 else self.alloc(rid, need)

    def free(self, rid: str) -> int:
        """Release all of ``rid``'s pages; returns how many."""
        return self._held.pop(rid, 0)

    # -- prefix store accounting ------------------------------------------
    def pin_prefix(self, key: tuple, n_pages: int) -> bool:
        """Account a NEW prefix-store entry (one pooled copy of a shared
        system prompt's KV). False when it cannot fit."""
        if key in self._prefix:
            self.touch_prefix(key)
            return True
        if n_pages > self.free_pages:
            return False
        self._stamp += 1
        self._prefix[key] = {"pages": n_pages, "stamp": self._stamp}
        return True

    def touch_prefix(self, key: tuple) -> None:
        """LRU touch on admission reuse."""
        self._stamp += 1
        self._prefix[key]["stamp"] = self._stamp

    def prefix_pages(self, key: tuple) -> int:
        return self._prefix[key]["pages"] if key in self._prefix else 0

    def has_prefix(self, key: tuple) -> bool:
        return key in self._prefix

    def drop_prefix(self, key: tuple) -> int:
        """Un-account one prefix entry by key (a failed build that never
        reached the store); returns its pages (0 if absent)."""
        e = self._prefix.pop(key, None)
        return e["pages"] if e else 0

    def evict_prefix_lru(self) -> tuple | None:
        """Drop the least-recently-used prefix entry, returning its key
        (None when the store is empty). Any entry is droppable — admitted
        requests hold private copies, the store only saves future prefill
        compute — so LRU just picks the least useful."""
        if not self._prefix:
            return None
        key = min(self._prefix, key=lambda k: self._prefix[k]["stamp"])
        del self._prefix[key]
        return key

    def prefix_keys(self) -> Iterable[tuple]:
        return tuple(self._prefix)

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "total_pages": self.total_pages,
            "free_pages": self.free_pages,
            "held": dict(self._held),
            "prefix_entries": len(self._prefix),
            "prefix_pages": sum(e["pages"] for e in self._prefix.values()),
        }
