"""Continuous-batching serving engine over the paged KV cache.

Resilience-first by construction: overload, stragglers, mid-request
preemption, and cache exhaustion are the *steady state* of a loaded
server, so every one of them is a first-class, chaos-testable path here —
not an exception handler bolted on later.

Shape of the runtime (Orca-style iteration-level scheduling over a
vLLM-style paged budget, adapted to the model-native packed cache):

- ONE compiled decode step over ``cfg.slots`` fixed batch slots (the
  shared :func:`dtc_tpu.generate.decode_step`, driven with a per-slot
  ``(B,)`` cache-index vector). Requests enter and leave slots at
  iteration boundaries via a jitted cache-surgery ``insert`` whose slot
  argument is *traced* — admission and eviction NEVER recompile the step
  (audited: analysis baseline ``serve_decode``, cold==1 steady==0).
- Admission = per-request prefill on a side (batch-1) cache, padded to
  ``prefill_bucket`` so prefill compilations are bounded, then one
  device-side copy into the slot row. A shared system prompt
  (``Request.shared_prefix_len``) is prefilled once into the prefix store
  and reused by every admission that matches it — the prefix-sharing win
  is prefill compute (see paged_cache.py's honesty note on the dense
  layout).
- The paged allocator accounts every resident token in ``page_size``
  blocks against one pool; exhaustion triggers *eviction-and-re-prefill*
  (victim re-queues with its generated tokens and resumes bit-exactly —
  greedy decode over prompt+generated reproduces the continuation), the
  same recovery path mid-request preemption and detected cache-block
  corruption take.
- Robustness layer: bounded queue with typed rejection (QueueFullError),
  shed-under-overload (lowest priority / longest queued past the
  watermark, typed ShedError), per-request deadlines with mid-decode
  cancellation (DeadlineExceededError), transient-fault retry from the
  pre-step cache (``resilience.retry.retry_call`` + the logits finite
  check), page-checksum verification on a cadence, and a serving-mode
  hung-step watchdog. Chaos (``resilience.chaos`` serve hooks) injects
  faults at iteration boundaries ON these production paths.
- SLO accounting through ``obs``: queue-wait / TTFT / ms-per-token
  histograms, shed/evict/expire/reject/retry counters, and one
  ``serve_request`` event per terminal request — no silent drops.
- Speculative decoding (``ServeConfig.spec``, ISSUE 19): a resident
  shallow DRAFT rung (the target's bottom ``draft_layers``, extracted at
  construction — ``dtc_tpu/spec/draft.py``) proposes ``spec_k - 1``
  tokens per iteration and ONE k-query verify launch accepts a prefix of
  them, so an iteration emits 1..spec_k tokens per slot instead of
  exactly one. Greedy acceptance keeps the output token-identical to
  plain decode by construction. The draft's KV rides the SAME page pool
  (a proportional ``draft_layers / n_layers`` surcharge in
  ``_pages_needed``); rounds are atomic in-jit, so eviction / failover /
  corruption recovery land at iteration boundaries exactly as before —
  re-admission re-prefills BOTH caches and resumes token-identically.
  Honesty plumbing: rejected-draft wall time is a typed badput class
  (``spec_rejected_draft``, never productive_decode), the SLO monitor is
  fed ACCEPTED-tokens/s (a collapsing accept rate degrades admissions
  like a latency breach), and every ServeResult carries
  ``n_spec_proposed/accepted`` so accept_rate is per-request observable.
- Multi-tenant LoRA adapters (``dtc_tpu/adapters/``, model config
  ``adapter.rank > 0``): one resident ``(max_adapters, ...)`` stacked
  factor buffer over ONE base model — slot 0 pinned to the all-zero base
  adapter — with per-slot adapter indices gathered inside the jitted
  step, so admitting a new tenant (or ``load_adapter`` writing factors at
  a traced stack slot) never recompiles. Requests name their tenant
  (``Request.adapter``); the store pins it (refcount) from submit to
  terminal; per-tenant TTFT/ms-per-token histograms and ``adapter_*``
  events ride the same registry.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from dtc_tpu.adapters import (
    BASE_SLOT,
    AdapterStore,
    gather_slot_lora,
    init_lora_stack,
    lora_enabled,
    validate_lora_tree,
)
from dtc_tpu.generate import decode_step, init_cache
from dtc_tpu.obs.goodput import SPEC_REJECTED_DRAFT, OnlineGoodput
from dtc_tpu.obs.registry import MetricsRegistry
from dtc_tpu.obs.slo import SloMonitor
from dtc_tpu.obs.trace import FlightRecorder, Tracer
from dtc_tpu.resilience.chaos import ChaosInjector
from dtc_tpu.resilience.events import RecoveryBus
from dtc_tpu.resilience.retry import retry_call
from dtc_tpu.resilience.watchdog import StepWatchdog
from dtc_tpu.serve.paged_cache import PageAllocator, kv_token_bytes, pages_for
from dtc_tpu.spec import check_spec_backend, extract_draft, serve_round
from dtc_tpu.serve.request import (
    TERMINAL_STATES,
    DeadlineExceededError,
    EngineClosedError,
    QueueFullError,
    Request,
    RequestFailedError,
    RequestState,
    RequestTooLargeError,
    ServeResult,
    ShedError,
    TransientStepError,
    UnknownAdapterError,
)

PyTree = Any


def init_slot_cache(model, slots: int) -> PyTree:
    """Decode cache for ``slots`` independent slots: the standard cache
    with the scalar write frontier replaced by a ``(slots,)`` per-slot
    vector — the model branches on the index's static rank, so this one
    swap turns whole-batch decode into continuous-batching decode."""
    cache = dict(init_cache(model, slots))
    cache["index"] = jnp.zeros((slots,), jnp.int32)
    return cache


def _pad_to_bucket(tokens: list[int], bucket: int, limit: int) -> list[int]:
    """Right-pad to the next bucket multiple, clamped to ``limit`` (the
    remaining cache room — padding past it would make the prefill's
    dynamic_update_slice clamp its start and smear pad garbage over valid
    positions)."""
    n = len(tokens)
    padded = min(((n + bucket - 1) // bucket) * bucket, limit)
    return tokens + [0] * (padded - n)


class _Slot:
    """Host-side per-slot record: who occupies it, the write frontier
    (tokens RESIDENT in the cache row), and fingerprints of completed
    pages for the integrity verifier."""

    __slots__ = ("rid", "frontier", "page_fp")

    def __init__(self) -> None:
        self.rid: str | None = None
        self.frontier = 0
        self.page_fp: dict[int, float] = {}


class ServingEngine:
    """See module docstring. Construct once per (model, params, config);
    ``submit()`` requests, then drive ``step()`` (or ``run()``) —
    iteration boundaries are where admission, eviction, deadlines,
    shedding, verification, and chaos all land."""

    def __init__(
        self,
        model,
        params: PyTree,
        cfg,
        *,
        telemetry=None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.model = model
        self.params = params
        self.cfg = cfg
        self.mcfg = model.cfg
        if getattr(self.mcfg, "debug_checks", False):
            # The model would emit checkify.check guards that must be
            # functionalized before jit (see generate.py's debug path);
            # the engine jits decode_step directly, and the per-slot
            # overflow guard is the engine's own page/frontier accounting
            # here — fail clearly instead of erroring mid-trace.
            raise ValueError(
                "ServingEngine does not support model debug_checks=True "
                "(unfunctionalized checkify under jit); serve a config "
                "with debug_checks=False and use generate() for dev-mode "
                "assertions"
            )
        self.clock = clock
        self.sleep = sleep
        self.telemetry = telemetry
        self.reg: MetricsRegistry = (
            telemetry.registry if telemetry is not None else MetricsRegistry()
        )
        # ONE timebase for the whole serving record: event ts stamps,
        # span t0s, and the SLO timings on ServeResult all read the
        # scheduler clock (injected fake clocks stay coherent in tests).
        # Emission adds a constant epoch offset so the scheduler's
        # monotonic seconds land on the wall clock the TRAINER's shards
        # use — cross-host / mixed train+serve timeline merges sort by
        # raw timestamp, and a monotonic-since-boot base would place
        # every serve event decades before every train event. A constant
        # shift cancels in every duration/difference, so span-derived
        # TTFT/queue-wait still equal the ServeResult values exactly.
        self._epoch0 = time.time() - self.clock()
        emit_clock = lambda: self.clock() + self._epoch0  # noqa: E731
        self.reg.set_clock(emit_clock)
        if telemetry is not None:
            self.tracer = telemetry.tracer
            self.tracer.clock = emit_clock
            self.recorder = telemetry.recorder
        else:
            # Engine used bare (tests, bench): spans still emit to the
            # registry and the flight recorder still rings in memory.
            self.tracer = Tracer(self.reg, clock=emit_clock, tid="sched")
            self.recorder = self.reg.add_sink(FlightRecorder(256))
        # Online SLO monitor — evaluated at iteration boundaries; a
        # breaching latency objective activates graceful degradation.
        slo_cfg = getattr(cfg, "slo", None)
        self.slo = SloMonitor.from_config(slo_cfg, self.reg, runtime="serve")
        self._slo_check_every = getattr(slo_cfg, "check_every", 8) or 8
        # Online goodput gauge (ISSUE 16): share the telemetry facade's
        # instance (its registry IS this registry), or a private one for
        # bare engines (tests, bench). Fed below from the iteration
        # timestamps the scheduler already takes — never a device sync.
        self.goodput: OnlineGoodput | None = (
            getattr(telemetry, "goodput", None)
            if telemetry is not None else OnlineGoodput(self.reg)
        )
        self._gp_work = 0.0  # attributed seconds, current iteration
        self.bus = RecoveryBus()
        self.chaos = (
            ChaosInjector(cfg.chaos, self.bus) if cfg.chaos.enabled else None
        )
        self.watchdog = (
            StepWatchdog(cfg.watchdog) if cfg.watchdog.enabled else None
        )
        # Page checksums cost a device reduction + blocking transfer per
        # collection; only pay it when someone will read them (the
        # verifier cadence, or injected page corruption the verifier must
        # catch — other chaos kinds never touch the checksums).
        self._track_pages = cfg.verify_pages_every > 0 or (
            cfg.chaos.enabled and cfg.chaos.serve_corrupt_page_at_step > 0
        )

        if cfg.pool_hbm_bytes > 0:
            # Byte-budget sizing: the pool is however many pages of KV
            # payload fit the budget at the model's kv_cache_dtype —
            # int8 holds 2× the pages of bf16 (4× of fp32) in the same
            # bytes, i.e. quantization buys resident tenants/prefixes,
            # not just bandwidth (see paged_cache.kv_token_bytes for the
            # scale-sidecar honesty note).
            pool = max(
                1,
                cfg.pool_hbm_bytes
                // (cfg.page_size * kv_token_bytes(self.mcfg)),
            )
        else:
            pool = cfg.total_pages or cfg.slots * pages_for(
                self.mcfg.max_seq_len, cfg.page_size
            )
        self.alloc = PageAllocator(pool, cfg.page_size)

        # Multi-tenant adapters (dtc_tpu/adapters/): with an adapter-
        # enabled model, ONE resident (max_adapters, ...) stacked-factor
        # buffer serves every tenant — slot 0 is the all-zero base
        # adapter, per-request indices gather per-SLOT factors inside the
        # jitted step, and load_adapter() writes a tenant's factors at a
        # TRACED stack slot. Values change, shapes never do: tenant churn
        # cannot recompile (audited: serve_decode baseline).
        self.lora_on = lora_enabled(self.mcfg)
        if self.lora_on:
            self.adapter_store = AdapterStore(cfg.max_adapters)
            self.lora_stack = init_lora_stack(model, cfg.max_adapters)
            self.slot_adapter = np.zeros((cfg.slots,), np.int32)
        else:
            self.adapter_store = None
            self.lora_stack = None
            self.slot_adapter = None

        # Speculative decoding (ISSUE 19): extract the resident draft
        # rung ONCE at construction (a zero-copy layer slice of the
        # target params) and give it its own per-slot cache next to the
        # target's. Spec is adapter-free by design: the draft shares the
        # target's embed/head by reference and verify runs the BASE
        # model, so a per-tenant adapter would fork draft and target
        # distributions silently — fail typed at construction instead.
        spec_cfg = getattr(cfg, "spec", None)
        self.spec_on = spec_cfg is not None and spec_cfg.enabled
        if self.spec_on and self.lora_on:
            raise ValueError(
                "speculative decoding (serve.spec) does not compose with "
                "multi-tenant adapters (model adapter.rank > 0): the draft "
                "rung proposes under base weights while each tenant's "
                "verify would run adapted weights — acceptance would "
                "collapse and the draft KV surcharge would be priced "
                "wrong; serve an adapter-free config"
            )
        if self.spec_on:
            check_spec_backend(self.mcfg)  # token-identity needs one path
            self.draft_model, self.draft_params = extract_draft(
                model, params, spec_cfg.draft_layers
            )
            self.draft_cache = init_slot_cache(self.draft_model, cfg.slots)
        else:
            self.draft_model = self.draft_params = self.draft_cache = None
        # Accepted-token throughput window for the SLO floor: emitted
        # tokens and round count since the last SLO check (host ints).
        self._spec_emitted_since = 0
        self._spec_rounds_since = 0
        self._spec_rate_t0 = self.clock()

        self.cache = init_slot_cache(model, cfg.slots)
        self.slots = [_Slot() for _ in range(cfg.slots)]
        self.last_tok = np.zeros((cfg.slots,), np.int32)

        self.closed = False  # shutdown()/drain: submit() refuses typed
        self._in_shutdown = False  # one flight dump for the whole drain
        self.queue: list[Request] = []
        self.requests: dict[str, Request] = {}
        self.results: dict[str, ServeResult] = {}
        self._eff_max_new: dict[str, int] = {}
        self._deadline: dict[str, float] = {}
        self._prefix_store: dict[tuple, tuple[PyTree, int]] = {}
        self._retry_scope: list[str] = []  # rids charged for in-flight retries
        self._it = 0
        self._worked = False  # did this iteration run the model
        self._fps_memo: Any = None  # checksum table for the CURRENT cache

        self._build_fns()
        if self.spec_on:
            self._build_spec_fns()
        self._settle_cache_sharding()

    def _settle_cache_sharding(self) -> None:
        """Kill the PR 9 gotcha at construction: an engine fed
        GSPMD-sharded base params (a trainer-produced base) used to pay
        one EXTRA ``insert_fn`` compile on the first decode — the step's
        output cache settles its GSPMD-normalized sharding only then, so
        an insert compiled against the construction-time (uncommitted)
        cache stopped matching and silently recompiled inside the first
        compile-sensitive window (the two-admission warmup in
        adapter_smoke worked around it).

        Fix: when (and only when) the params carry NamedShardings, run
        ONE throwaway decode step here and adopt its output cache — the
        step's cold compile moves to construction (it was inevitable)
        and every later ``insert_fn``/``step_fn`` call sees the settled
        layout. Unsharded params (every CPU test, the audit's lowered
        entries) skip this entirely: no extra compile, baselines
        unchanged. The warm step writes garbage k/v at position 0 of
        every slot and advances the per-slot index once — both idle-slot
        states the scheduler already treats as meaningless (admission
        surgery overwrites the full row and pins the frontier)."""
        sharded = any(
            isinstance(getattr(leaf, "sharding", None), jax.sharding.NamedSharding)
            for leaf in jax.tree.leaves(self.params)
        )
        if not sharded:
            return
        toks = jnp.zeros((self.cfg.slots,), jnp.int32)
        if self.lora_on:
            warmed, _, _ = self._step_fn(
                self.params, self.lora_stack,
                jnp.asarray(self.slot_adapter), self.cache, toks,
            )
        else:
            warmed, _, _ = self._step_fn(self.params, self.cache, toks)
        self.cache = warmed
        self._fps_memo = None

    # ------------------------------------------------------------------
    # jitted device functions (each compiles ONCE; every per-request
    # quantity — slot, frontier, valid length — is a traced argument)
    # ------------------------------------------------------------------
    #: (model, page_size) -> the jitted fn set. Flax modules hash by
    #: structure, so N in-process replicas serving the SAME model (the
    #: fleet router's configuration) share ONE set of executables instead
    #: of compiling step/prefill/insert once per replica — the honest
    #: reading of "in-process replicas share host compute". The fns close
    #: over nothing engine-specific (params/cache/config all arrive as
    #: arguments), so sharing cannot couple replica state.
    _FN_CACHE: dict = {}

    def _build_fns(self) -> None:
        cache_key = (self.model, self.cfg.page_size)
        cached = ServingEngine._FN_CACHE.get(cache_key)
        if cached is not None:
            (self._step_fn, self._prefill_fn, self._insert_fn,
             self._fingerprint_fn, self._corrupt_fn, adapter_insert) = cached
            if adapter_insert is not None:
                self._adapter_insert_fn = adapter_insert
            return
        model = self.model
        lora_on = self.lora_on

        # ONE decode/prefill core shared by both compiled flavors — the
        # post-processing (greedy argmax matching generate()'s fast path,
        # the per-slot finite flag that detects poisoned logits, the
        # n_valid row selection) must never diverge between the lora and
        # adapter-free programs; only the signature (and the per-slot
        # factor gather) differs per branch below.
        def step_core(params, cache, toks, lora):
            """One continuous-batching decode iteration over ALL slots
            (idle slots compute garbage that is masked/overwritten before
            any read — fixed shapes are what keep this recompile-free)."""
            cache, logits = decode_step(model, params, cache, toks[:, None], lora)
            last = logits[:, -1]
            nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
            finite = jnp.all(jnp.isfinite(last.astype(jnp.float32)), axis=-1)
            return cache, nxt, finite

        def prefill_core(params, cache, prompt, n_valid, lora):
            """Batch-1 prefill over a bucket-padded prompt chunk starting
            at the cache's current scalar frontier. Samples the next token
            from the last VALID row (pad rows' outputs are discarded; pad
            K/V lands beyond the frontier the insert below pins, so it is
            masked until real decode overwrites it)."""
            cache, logits = decode_step(model, params, cache, prompt, lora)
            row = logits[0, n_valid - 1]
            tok = jnp.argmax(row, axis=-1).astype(jnp.int32)
            finite = jnp.all(jnp.isfinite(row.astype(jnp.float32)))
            return cache, tok, finite

        if lora_on:
            # Adapter mode: the step/prefill signatures grow the resident
            # factor stack + per-slot adapter indices, gathered INSIDE the
            # one compiled step — tenant admission is a value change,
            # never a shape change (the recompile-free invariant the
            # serve_decode audit baseline pins across adapter load +
            # mixed-tenant admission).
            @jax.jit
            def step_fn(params, stack, aids, cache, toks):
                return step_core(
                    params, cache, toks, gather_slot_lora(stack, aids)
                )

            @jax.jit
            def prefill_fn(params, stack, aid, cache, prompt, n_valid):
                return prefill_core(
                    params, cache, prompt, n_valid,
                    gather_slot_lora(stack, aid),  # aid: (1,) index
                )

            @jax.jit
            def adapter_insert_fn(stack, factors, slot):
                """Hot adapter load: write one tenant's factors into stack
                row ``slot``. ``slot`` is traced — loading into any slot
                reuses this one executable (the stack-side twin of the
                cache-surgery ``insert_fn`` below)."""
                def leaf(s, f):
                    return jax.lax.dynamic_update_slice(
                        s, f[None].astype(s.dtype), (slot,) + (0,) * f.ndim
                    )

                return jax.tree.map(leaf, stack, factors)

            self._adapter_insert_fn = adapter_insert_fn
        else:
            @jax.jit
            def step_fn(params, cache, toks):
                return step_core(params, cache, toks, None)

            @jax.jit
            def prefill_fn(params, cache, prompt, n_valid):
                return prefill_core(params, cache, prompt, n_valid, None)

        @jax.jit
        def insert_fn(batch_cache, row_cache, slot, n_tokens):
            """Admission surgery: copy a prefilled batch-1 cache into slot
            row ``slot`` and pin that slot's frontier to ``n_tokens`` (the
            VALID length — not the padded length the prefill advanced its
            scalar index by). ``slot`` is traced: admitting into any slot
            reuses this one executable."""
            n = jnp.asarray(n_tokens, jnp.int32)

            def leaf(b, r):
                if b.ndim == 1:  # the (slots,) frontier vector
                    return jax.lax.dynamic_update_slice(b, n[None], (slot,))
                start = (0, slot) + (0,) * (b.ndim - 2)
                return jax.lax.dynamic_update_slice(b, r, start)

            return jax.tree.map(leaf, batch_cache, row_cache)

        psize = self.cfg.page_size

        @jax.jit
        def fingerprint_fn(cache):
            """Integrity checksums of EVERY completed-page candidate in
            one launch: a (slots, n_pages) fp32 table, one device call
            and ONE transfer per use — never a host round-trip per page
            (the hot-loop host-sync pattern analysis/hostsync.py lints
            against in the trainer). Position-weighted SIGNED sums, not
            sum(|x|): a plain magnitude sum is blind to sign-bit flips
            and to value permutations within a page — realistic memory
            faults the verifier exists to catch. Deterministic for
            identical bytes (fixed weights, fixed reduction order), so
            the verifier recomputes bit-equal unless the page changed."""
            total = None
            for leaf in jax.tree.leaves(cache):
                if leaf.ndim < 4:
                    continue
                l, b_, s_, hd_ = leaf.shape
                n_pages = s_ // psize
                blk = leaf[:, :, : n_pages * psize, :].reshape(
                    l, b_, n_pages, psize, hd_
                ).astype(jnp.float32)
                w_l = 1.0 + 0.127 * jnp.arange(l, dtype=jnp.float32)
                w_p = 1.0 + 0.3183 * jnp.arange(psize, dtype=jnp.float32)
                w_f = 1.0 + 0.0721 * jnp.arange(hd_, dtype=jnp.float32)
                w = (
                    w_l[:, None, None, None, None]
                    * w_p[None, None, None, :, None]
                    * w_f[None, None, None, None, :]
                )
                fp = jnp.sum(blk * w, axis=(0, 3, 4))
                total = fp if total is None else total + fp
            return total

        @functools.partial(jax.jit, static_argnames=("size",))
        def corrupt_fn(cache, slot, start, size):
            """Chaos-only: overwrite one page of the first KV leaf with a
            constant — finite (so the logits check cannot catch it; only
            the checksum verifier can), device-side, on the real cache."""
            leaves, treedef = jax.tree.flatten(cache)
            done = False
            out = []
            for leaf in leaves:
                if not done and leaf.ndim >= 4:
                    blk = jnp.full(
                        (leaf.shape[0], 1, size, leaf.shape[3]), 123.25,
                        leaf.dtype,
                    )
                    leaf = jax.lax.dynamic_update_slice(
                        leaf, blk, (0, slot, start, 0)
                    )
                    done = True
                out.append(leaf)
            return jax.tree.unflatten(treedef, out)

        self._step_fn = step_fn
        self._prefill_fn = prefill_fn
        self._insert_fn = insert_fn
        self._fingerprint_fn = fingerprint_fn
        self._corrupt_fn = corrupt_fn
        ServingEngine._FN_CACHE[cache_key] = (
            step_fn, prefill_fn, insert_fn, fingerprint_fn, corrupt_fn,
            getattr(self, "_adapter_insert_fn", None),
        )

    def _build_spec_fns(self) -> None:
        """The draft-side jitted fn for spec mode: a batch-1 prefill over
        the SAME padded prompt shapes the target prefill uses (so the
        two caches' frontiers agree at admission). Cached per
        (model, page_size, draft_layers) for the same replica-sharing
        reason as ``_FN_CACHE``; the round itself is the module-level
        :func:`dtc_tpu.spec.serve_round` (shared process-wide via jit's
        own cache — flax modules hash by structure). No finite check /
        retry on the draft: a poisoned draft can only lower acceptance
        (the verify re-derives every emitted token from TARGET logits),
        never corrupt output — the target verify's finite flag is the
        retry trigger. Insert/rollback reuse the generic tree-map
        ``insert_fn`` and the in-round index decrement respectively, so
        the draft cache adds no new surgery paths."""
        key = (
            self.model, self.cfg.page_size, "spec_prefill",
            self.cfg.spec.draft_layers,
        )
        fn = ServingEngine._FN_CACHE.get(key)
        if fn is None:
            draft_model = self.draft_model

            @jax.jit
            def draft_prefill_fn(params, cache, prompt):
                cache, _ = decode_step(draft_model, params, cache, prompt)
                return cache

            ServingEngine._FN_CACHE[key] = fn = draft_prefill_fn
        self._draft_prefill_fn = fn

    # ------------------------------------------------------------------
    # submission (admission control)
    # ------------------------------------------------------------------
    def _pages_needed(self, n_tokens: int) -> int:
        """Page-pool footprint for ``n_tokens`` resident TARGET tokens —
        plus the draft rung's proportional KV surcharge under speculation
        (ISSUE 19): the draft cache holds the same positions at
        ``draft_layers`` of ``n_layers`` depth and rides the SAME pool,
        so every admission/decode reservation prices it or the pool
        over-commits exactly when speculation is on."""
        pages = pages_for(n_tokens, self.cfg.page_size)
        if self.spec_on:
            dl, nl = self.cfg.spec.draft_layers, self.mcfg.n_layers
            pages += (pages * dl + nl - 1) // nl
        return pages

    def submit(self, req: Request, *, resume: ServeResult | None = None) -> str:
        """Enqueue one request. Typed backpressure — raises
        :class:`QueueFullError` past ``queue_depth`` and
        :class:`RequestTooLargeError` for requests that could never run;
        neither is ever dropped silently. A ``rid`` may only be reused
        after its previous submission reached a terminal state (the new
        result then replaces the old one) — resubmitting an in-flight rid
        is a caller bug that would silently merge two requests into one
        record, so it raises ``ValueError`` like the Request validators.

        ``resume`` is the cross-replica failover path (the router's PR 6
        re-prefill lifted fleet-wide): a prior partial :class:`ServeResult`
        whose ``tokens`` are prompt-continuation generated elsewhere. The
        new record starts with those tokens, so admission re-prefills
        prompt+generated and greedy decode continues token-for-token
        identically. Timing accounting is the load-bearing part:
        ``submitted_t`` / ``first_token_t`` carry over (TTFT stays
        anchored at the ORIGINAL submit — fleet histograms must include
        failover cost, not hide it), ``requeued_t`` restarts the
        ``req.queued`` span at THIS hop, and ``n_hops`` increments."""
        if self.closed:
            self.reg.counter("serve_rejected").inc()
            self.reg.emit("serve_reject", rid=req.rid, reason="closed")
            raise EngineClosedError(
                f"request {req.rid}: engine is shut down / draining"
            )
        if resume is not None and len(resume.tokens) >= req.max_new_tokens:
            raise ValueError(
                f"request {req.rid}: resume carries {len(resume.tokens)} "
                f"tokens >= max_new_tokens {req.max_new_tokens} — the prior "
                "hop should have completed it (caller bug)"
            )
        if req.rid in self.requests:  # present == not yet terminal
            raise ValueError(
                f"request {req.rid}: rid already in flight "
                f"(state {self.results[req.rid].state.value})"
            )
        now = self.clock()
        total = len(req.prompt) + req.max_new_tokens
        # Speculation headroom (ISSUE 19): the verify window physically
        # writes spec_k positions from the frontier before rolling back,
        # so the last round still needs spec_k - 1 slots past the final
        # token — a request admitted without them would clamp its verify
        # writes mid-flight. Priced at submit, typed, never mid-decode.
        spec_pad = self.cfg.spec.spec_k - 1 if self.spec_on else 0
        if total + spec_pad > self.mcfg.max_seq_len:
            self.reg.counter("serve_rejected").inc()
            self.reg.emit("serve_reject", rid=req.rid, reason="too_large")
            raise RequestTooLargeError(
                f"request {req.rid}: prompt ({len(req.prompt)}) + "
                f"max_new_tokens ({req.max_new_tokens})"
                + (f" + spec_k-1 verify headroom ({spec_pad})" if spec_pad
                   else "")
                + f" exceeds max_seq_len ({self.mcfg.max_seq_len})"
            )
        if self._pages_needed(total + spec_pad) > self.alloc.total_pages:
            self.reg.counter("serve_rejected").inc()
            self.reg.emit("serve_reject", rid=req.rid, reason="too_large")
            raise RequestTooLargeError(
                f"request {req.rid}: footprint "
                f"{self._pages_needed(total + spec_pad)} pages"
                + (" (incl. draft KV surcharge)" if self.spec_on else "")
                + f" exceeds the pool ({self.alloc.total_pages})"
            )
        if req.adapter is not None and (
            not self.lora_on or req.adapter not in self.adapter_store
        ):
            self.reg.counter("serve_rejected").inc()
            self.reg.emit(
                "serve_reject", rid=req.rid, reason="unknown_adapter",
                adapter=req.adapter,
            )
            raise UnknownAdapterError(
                f"request {req.rid}: adapter {req.adapter!r} is not resident"
                + ("" if self.lora_on else
                   " (model has no adapter support: adapter.rank == 0)")
            )
        if len(self.queue) >= self.cfg.queue_depth:
            self.reg.counter("serve_rejected").inc()
            self.reg.emit("serve_reject", rid=req.rid, reason="queue_full")
            raise QueueFullError(
                f"request {req.rid}: queue at depth {self.cfg.queue_depth}"
            )
        if req.adapter is not None:
            # Pinned from submit to terminal: an in-flight tenant's
            # factors can never be LRU-evicted out from under it (the
            # eviction→re-prefill recovery path depends on this).
            self.adapter_store.acquire(req.adapter)
        self.requests[req.rid] = req
        res = ServeResult(
            rid=req.rid, state=RequestState.QUEUED, tokens=[],
            submitted_t=now, adapter=req.adapter,
        )
        if resume is not None:
            res.tokens = list(resume.tokens)
            if resume.submitted_t is not None:
                res.submitted_t = resume.submitted_t
            res.first_token_t = resume.first_token_t
            res.n_evictions = resume.n_evictions
            res.n_retries = resume.n_retries
            res.n_hops = resume.n_hops + 1
            res.degraded = resume.degraded
            # Acceptance telemetry carries over: per-request accept_rate
            # must cover the whole request, not just the last hop.
            res.n_spec_proposed = resume.n_spec_proposed
            res.n_spec_accepted = resume.n_spec_accepted
            res.requeued_t = now  # this hop's req.queued span starts here
        self.results[req.rid] = res
        ttl = self.cfg.deadline_s if req.deadline_s is None else req.deadline_s
        # Deadlines anchor at the ORIGINAL submit (== now for a fresh
        # request): a failover hop must not grant a request a fresh TTL.
        self._deadline[req.rid] = (
            res.submitted_t + ttl if ttl and ttl > 0 else float("inf")
        )
        self.queue.append(req)
        self.reg.counter("serve_submitted").inc()
        return req.rid

    # -- load/occupancy introspection (the router's placement inputs) ----
    @property
    def queue_room(self) -> int:
        """Admissions ``submit()`` would still accept before typed
        QueueFullError backpressure — the fleet router's per-replica
        admission-coordination signal (it routes around a full replica
        instead of overriding its bound)."""
        return max(0, self.cfg.queue_depth - len(self.queue))

    @property
    def active_count(self) -> int:
        """Slots currently decoding."""
        return sum(1 for s in self.slots if s.rid is not None)

    @property
    def load(self) -> int:
        """Queued + in-flight requests (the least-loaded placement key)."""
        return len(self.queue) + self.active_count

    @property
    def over_shed_watermark(self) -> bool:
        """Queue occupancy past the shed watermark — the replica is about
        to shed; the router prefers peers with headroom."""
        wm = self.cfg.shed_watermark
        return wm > 0 and len(self.queue) > int(wm * self.cfg.queue_depth)

    def drain_results(self) -> dict[str, ServeResult]:
        """Remove and return every TERMINAL result — the long-running
        caller's memory-reclamation API (``results`` otherwise holds
        each terminal record, tokens included, until drained)."""
        done = {
            rid: r for rid, r in self.results.items()
            if r.state in TERMINAL_STATES
        }
        for rid in done:
            del self.results[rid]
        return done

    # ------------------------------------------------------------------
    # multi-tenant adapters
    # ------------------------------------------------------------------
    def load_adapter(self, name: str, factors: PyTree) -> int:
        """Make tenant ``name``'s LoRA factors resident; returns its stack
        slot. ``factors`` is the per-adapter "lora" tree (the finetune
        export — :func:`dtc_tpu.adapters.load_adapter_file` with the
        engine's stack as ``like``, or a ``TrainResult.state.params``).

        Loading is a device-side write at a TRACED slot index into the
        fixed-shape resident stack, so it NEVER recompiles the decode
        step, even mid-flight with other tenants decoding (audited:
        serve_decode baseline). A full store evicts the least-recently-
        used idle tenant (``adapter_evict`` event); when every tenant has
        in-flight requests the load fails typed
        (:class:`AdapterStoreFullError`). Re-loading a resident name
        overwrites its factors in place (a hot adapter update) and drops
        any prefix KV built under the old factors; it raises ValueError
        while that tenant has in-flight requests (their decode would fork
        from the KV already computed)."""
        if not self.lora_on:
            raise ValueError(
                "load_adapter on a lora-free engine (model adapter.rank == "
                "0); serve an adapter-enabled model config"
            )
        validate_lora_tree(self.lora_stack, factors)
        slot, evicted = self.adapter_store.register(name)
        if evicted is not None:
            self.reg.counter("adapter_evictions").inc()
            self.reg.emit(
                "adapter_evict", name=evicted, slot=slot, iteration=self._it,
                reason="store_lru",
            )
            # The evicted tenant is fully retired: its prefix KV is
            # unreachable-by-correctness (a later SAME-NAME load may carry
            # different factors) and its per-tenant histograms must not
            # accrete forever under tenant churn.
            self._drop_adapter_prefixes(evicted)
            self.reg.drop_histogram(f"serve_ttft_s.{evicted}")
            self.reg.drop_histogram(f"serve_ms_per_token.{evicted}")
        # A (re)load changes the factors behind the name, so any prefix KV
        # built under the OLD factors is stale — reusing it would decode
        # the suffix under new factors against old-prefix KV bytes. Drop
        # the name's entries; the next admission rebuilds them.
        self._drop_adapter_prefixes(name)
        self.lora_stack = self._adapter_insert_fn(
            self.lora_stack, factors, jnp.int32(slot)
        )
        self.reg.counter("adapter_loads").inc()
        self.reg.emit(
            "adapter_load", name=name, slot=slot, iteration=self._it,
            params=int(sum(np.prod(np.shape(f)) for f in jax.tree.leaves(factors))),
        )
        return slot

    # ------------------------------------------------------------------
    # the scheduler iteration
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One iteration: faults/expiry/shed/admit at the boundary, then
        one decode step over the in-flight batch. Returns True while any
        request is queued or in flight."""
        self._it += 1
        self._worked = False  # set by _do_admit/_decode (model ran)
        self._gp_work = 0.0
        t0 = self.clock()
        if self.chaos is not None:
            stall = self.chaos.serve_stall(self._it)
            if stall > 0:
                self.sleep(stall)  # inside the timed iteration, on purpose
        self._expire()
        self._shed()
        self._admit()
        # Condition-dependent chaos shots are consulted ONLY when the
        # engine can act (a completed page / an active request exists) —
        # otherwise the fire-once shot would be consumed, and a chaos
        # event emitted, for an injection that never physically happened.
        if (
            self.chaos is not None
            and self._corruption_candidates()
            and self.chaos.serve_corrupt_page(self._it)
        ):
            self._inject_corruption()
        if (
            self.cfg.verify_pages_every > 0
            and self._it % self.cfg.verify_pages_every == 0
        ):
            self._verify_pages()
        if (
            self.chaos is not None
            and any(s.rid is not None for s in self.slots)
            and self.chaos.serve_preempt(self._it)
        ):
            self._preempt_newest()
        self._ensure_pages()
        self._decode()
        # Only WORKING iterations (a prefill or decode ran) feed the
        # watchdog: idle polling spins are microsecond-scale, and letting
        # them into the trailing median would flag every healthy decode
        # iteration of an interleaved submit()/step() caller as hung.
        # Bus drain BEFORE the watchdog verdict: chaos/recovery records
        # posted during this iteration land in the stream (and their
        # flight dumps fire) first, so a stall-then-flag iteration's LAST
        # dump carries the most diagnostic reason (hung_step).
        self._drain_bus()
        now_it = self.clock()
        if self.goodput is not None:
            # The iteration's unattributed remainder (scheduler
            # bookkeeping, chaos stalls, pure polling spins) is idle —
            # or degraded while a latency objective is breaching.
            idle = max((now_it - t0) - self._gp_work, 0.0)
            self.goodput.note(
                "degraded"
                if self.slo is not None and self.slo.degrade_active
                else "shed_or_idle",
                idle,
            )
            if self._it % self._slo_check_every == 0:
                pct = self.goodput.update(iteration=self._it)
                if self.slo is not None:
                    self.slo.observe("goodput_pct", pct)
        if self.watchdog is not None and self._worked:
            flag = self.watchdog.observe(self._it, now_it - t0)
            if flag is not None:
                self.reg.counter("serve_hung_steps").inc()
                self.reg.emit("hung_step", runtime="serve", **flag)
                self.dump_flight("hung_step", iteration=self._it)
        if self.slo is not None and self._it % self._slo_check_every == 0:
            if self.spec_on and self._spec_rounds_since > 0:
                # Feed the SLO floor ACCEPTED-tokens/s over the window
                # since the last check (only when rounds actually ran —
                # an idle engine's zero-rate must not fake a breach).
                # This is the "price accepted tokens, not proposals"
                # contract: a draft whose acceptance collapses breaches
                # the floor and degrades admissions (degrade_active)
                # even while launches-per-second looks healthy.
                rate = self._spec_emitted_since / max(
                    now_it - self._spec_rate_t0, 1e-9
                )
                self.reg.gauge("serve_accepted_tokens_per_s").set(rate)
                self.slo.observe("serve_accepted_tokens_per_s", rate)
                self._spec_emitted_since = 0
                self._spec_rounds_since = 0
                self._spec_rate_t0 = now_it
            self.slo.evaluate(iteration=self._it)
        return bool(self.queue) or any(s.rid is not None for s in self.slots)

    def run(self, *, max_steps: int = 100_000) -> dict[str, ServeResult]:
        """Drive ``step()`` until idle (every submitted request terminal)
        or ``max_steps`` iterations THIS CALL (a per-call budget, not the
        engine-lifetime counter — interleaved ``submit()``/``run()``
        callers get the full budget every time). Batch-mode entry point;
        interactive callers interleave ``submit()`` with their own
        ``step()`` loop."""
        for _ in range(max_steps):
            if not self.step():
                break
        return self.results

    def shutdown(
        self, *, mode: str = "drain", max_steps: int = 512,
        reason: str = "shutdown",
    ) -> dict[str, ServeResult]:
        """Graceful stop — the serving side of the trainer's SIGTERM
        contract (PR 2/7): stop admitting (``submit()`` raises a typed
        :class:`EngineClosedError` from here on), then

        - ``mode="drain"``: keep stepping until every queued/in-flight
          request is terminal or ``max_steps`` runs out; anything still
          unfinished at the budget is typed-evicted (FAILED +
          EngineClosedError — partial tokens preserved on the result);
        - ``mode="evict"``: typed-evict immediately (the hard-deadline
          SIGTERM path — e.g. a preemption notice too short to drain).

        Either way the recovery bus is drained (pending chaos/recovery
        records land in the stream), the flight recorder dumps ONCE with
        the shutdown reason — previously serving only dumped on crash
        paths — and sinks are flushed. Idempotent; returns ``results``.
        """
        if mode not in ("drain", "evict"):
            raise ValueError(f"unknown shutdown mode {mode!r}")
        if self.closed:
            return self.results
        self.closed = True
        self._in_shutdown = True  # per-request FAILED dumps collapse into
        try:                      # the single shutdown dump below
            if mode == "drain":
                for _ in range(max_steps):
                    if not self.step():
                        break
            for req in list(self.queue):
                self.queue.remove(req)
                self._finish(
                    req.rid, RequestState.FAILED,
                    EngineClosedError(
                        f"request {req.rid}: engine shut down while queued "
                        f"({reason})"
                    ),
                )
            for slot in self.slots:
                if slot.rid is None:
                    continue
                rid = slot.rid
                self._release_slot(rid)
                self._finish(
                    rid, RequestState.FAILED,
                    EngineClosedError(
                        f"request {rid}: engine shut down mid-decode "
                        f"({reason}; partial tokens preserved)"
                    ),
                )
        finally:
            self._in_shutdown = False
        self._drain_bus()
        self.reg.emit(
            "serve_shutdown", reason=reason, mode=mode, iteration=self._it,
        )
        self.dump_flight(f"shutdown: {reason}", iteration=self._it)
        self.reg.flush()
        return self.results

    # ------------------------------------------------------------------
    # boundary phases
    # ------------------------------------------------------------------
    def _expire(self) -> None:
        now = self.clock()
        for req in list(self.queue):
            if now > self._deadline[req.rid]:
                self.queue.remove(req)
                self._finish(
                    req.rid, RequestState.EXPIRED,
                    DeadlineExceededError(
                        f"request {req.rid} expired after "
                        f"{now - self.results[req.rid].submitted_t:.3f}s in queue"
                    ),
                )
        for slot in self.slots:
            if slot.rid is not None and now > self._deadline[slot.rid]:
                rid = slot.rid
                self._release_slot(rid)
                self._finish(
                    rid, RequestState.EXPIRED,
                    DeadlineExceededError(
                        f"request {rid} expired mid-decode (cancelled)"
                    ),
                )

    def _shed(self) -> None:
        wm = self.cfg.shed_watermark
        if wm <= 0 or not self.queue:
            return
        target = int(wm * self.cfg.queue_depth)
        while len(self.queue) > target:
            if self.cfg.shed_policy == "longest_queued":
                victim = min(
                    self.queue, key=lambda r: self.results[r.rid].submitted_t
                )
            else:  # priority: lowest first, longest-queued within
                victim = min(
                    self.queue,
                    key=lambda r: (r.priority, self.results[r.rid].submitted_t),
                )
            self.queue.remove(victim)
            self._finish(
                victim.rid, RequestState.SHED,
                ShedError(
                    f"request {victim.rid} shed under overload (queue "
                    f"{len(self.queue) + 1} > watermark {target} of "
                    f"{self.cfg.queue_depth})"
                ),
            )

    def _admit(self) -> None:
        while self.queue:
            free = [i for i, s in enumerate(self.slots) if s.rid is None]
            if not free:
                return
            # Highest priority first, FIFO within a priority.
            cand = max(
                self.queue,
                key=lambda r: (r.priority, -self.results[r.rid].submitted_t),
            )
            seq = list(cand.prompt) + self.results[cand.rid].tokens
            # Reserve through the FIRST decode write: +1 token plain,
            # +spec_k under speculation (the verify window), with the
            # draft surcharge folded in by _pages_needed.
            first_write = self.cfg.spec.spec_k if self.spec_on else 1
            need = self._pages_needed(len(seq) + first_write)
            if not self._make_room(need, cand.priority):
                return  # pool-bound: wait (deadlines/shedding keep it honest)
            # Reserve BEFORE the prefix store can pin pages out from under
            # this admission — the store competes for whatever remains.
            self.alloc.alloc(cand.rid, need)
            self.queue.remove(cand)
            self._do_admit(cand, free[0], seq)

    def _make_room(self, need: int, priority: int) -> bool:
        """Free pages for an admission: drop LRU prefix-store entries
        first, then evict strictly-lower-priority active requests (never
        equals — admission must not thrash same-priority work)."""
        while not self.alloc.can_fit(need):
            key = self.alloc.evict_prefix_lru()
            if key is None:
                break
            self._prefix_store.pop(key, None)
            self.reg.counter("serve_prefix_evictions").inc()
        while not self.alloc.can_fit(need):
            victims = [
                s.rid for s in self.slots
                if s.rid is not None and self.requests[s.rid].priority < priority
            ]
            if not victims:
                return False
            victim = min(
                victims,
                key=lambda r: (
                    self.requests[r].priority,
                    -(self.results[r].admitted_t or 0.0),
                ),
            )
            self._evict(victim, reason="admission_pressure")
        return True

    @staticmethod
    def prefix_key(req: Request) -> tuple | None:
        """The shared-prefix store key this request would hit (None when
        it declares no usable prefix). ONE definition — the engine's
        store lookups and the router's prefix-affinity placement must
        agree on it or affinity silently routes to misses. Keys are
        scoped PER ADAPTER: the same token prefix under two tenants
        yields different KV bytes (the adapter reshapes the k/v
        projections), so each (adapter, tokens) pair is its own entry."""
        plen = min(req.shared_prefix_len, len(req.prompt) - 1)
        if plen <= 0:
            return None
        return (req.adapter,) + tuple(int(t) for t in req.prompt[:plen])

    def has_prefix(self, req: Request) -> bool:
        """Whether this engine's prefix store already holds the request's
        shared prefix (the router's cache-affinity signal)."""
        key = self.prefix_key(req)
        return key is not None and key in self._prefix_store

    def _prefix_base(self, req: Request) -> tuple[PyTree, int]:
        """(base cache, base length) for this request's prefill: the
        shared-prefix store entry when one matches (prefilled once,
        reused by every admission), else a fresh batch-1 cache."""
        key = self.prefix_key(req)
        if key is None:
            return init_cache(self.model, 1), 0
        plen = len(key) - 1  # key = (adapter, *prefix tokens)
        if key in self._prefix_store:
            self.alloc.touch_prefix(key)
            self.reg.counter("serve_prefix_hits").inc()
            return self._prefix_store[key]
        n_pages = pages_for(plen, self.cfg.page_size)
        fits = self.alloc.pin_prefix(key, n_pages)
        while not fits:
            lru = self.alloc.evict_prefix_lru()
            if lru is None:
                break
            self._prefix_store.pop(lru, None)
            fits = self.alloc.pin_prefix(key, n_pages)
        if not fits:
            return init_cache(self.model, 1), 0  # no room: skip sharing
        padded = _pad_to_bucket(
            [int(t) for t in req.prompt[:plen]], self.cfg.prefill_bucket,
            self.mcfg.max_seq_len,
        )
        try:
            cache, _tok, _fin = self._checked_prefill(
                init_cache(self.model, 1), padded, plen,
                adapter_slot=self._adapter_slot(req),
            )
        except TransientStepError:
            # The entry was never stored: un-account its pinned pages or
            # they leak from the pool with no store key to evict.
            self.alloc.drop_prefix(key)
            raise
        # Pin the stored frontier to the VALID prefix length — the prefill
        # advanced it by the padded length, and a suffix prefill resuming
        # from the padded position would shift every later position (the
        # pad garbage beyond plen is overwritten/masked, but the index
        # must not count it).
        cache = dict(cache)
        cache["index"] = jnp.asarray(plen, jnp.int32)
        self._prefix_store[key] = (cache, plen)
        self.reg.counter("serve_prefix_builds").inc()
        return self._prefix_store[key]

    def _drop_adapter_prefixes(self, name: str) -> None:
        """Invalidate every shared-prefix store entry built under adapter
        ``name`` (prefix keys are ``(adapter, *tokens)``), returning their
        pages to the pool."""
        for key in [k for k in self._prefix_store if k and k[0] == name]:
            self._prefix_store.pop(key, None)
            self.alloc.drop_prefix(key)

    def _adapter_slot(self, req: Request) -> int:
        """The request's stack slot (BASE_SLOT for un-adapted requests or
        a lora-free engine). Submit-time validation + the store refcount
        guarantee residency from submit to terminal, so a miss here is an
        engine bug, not a race."""
        if not self.lora_on or req.adapter is None:
            return BASE_SLOT
        slot = self.adapter_store.slot_of(req.adapter)
        if slot is None:  # pragma: no cover — refcount pins residency
            raise UnknownAdapterError(
                f"request {req.rid}: adapter {req.adapter!r} vanished from "
                "the store while in flight"
            )
        return slot

    def _checked_prefill(self, base: PyTree, padded: list[int], n_valid: int,
                         adapter_slot: int = BASE_SLOT):
        """Prefill + finite check under the transient-fault retry (the
        production path poisoned logits and injected device faults take)."""
        prompt = jnp.asarray(np.asarray(padded, np.int32)[None])

        def attempt():
            if self.lora_on:
                cache, tok, fin = self._prefill_fn(
                    self.params, self.lora_stack,
                    jnp.asarray([adapter_slot], jnp.int32), base, prompt,
                    jnp.int32(n_valid),
                )
            else:
                cache, tok, fin = self._prefill_fn(
                    self.params, base, prompt, jnp.int32(n_valid)
                )
            if not bool(np.asarray(fin)):
                raise TransientStepError("prefill produced non-finite logits")
            self.reg.counter("serve_prefills").inc()
            return cache, tok, fin

        r = self.cfg.retry
        try:
            return retry_call(
                attempt, transient=(TransientStepError,),
                max_attempts=r.max_attempts, backoff_s=r.backoff_s,
                backoff_max_s=r.backoff_max_s, jitter=r.jitter,
                max_elapsed_s=r.max_elapsed_s, on_event=self._on_retry_event,
                sleep=self.sleep, clock=self.clock,
            )
        finally:
            self._retry_scope = []

    def _do_admit(self, req: Request, slot_i: int, seq: list[int]) -> None:
        self._worked = True  # a prefill runs whatever the outcome
        t_adm = self.clock()
        res = self.results[req.rid]
        res.state = RequestState.PREFILL
        if req.rid not in self._eff_max_new:
            eff = req.max_new_tokens
            over_queue = (
                self.cfg.degrade_watermark > 0
                and (len(self.queue) + 1) / self.cfg.queue_depth
                > self.cfg.degrade_watermark
            )
            # A breaching latency SLO degrades new admissions exactly like
            # crossing the queue watermark — the scheduler reacting to the
            # online monitor instead of a post-hoc bench row. A resumed
            # (failover) request that was ALREADY degraded stays capped:
            # a hop must never un-shrink a promise made to shed load.
            slo_hot = self.slo is not None and self.slo.degrade_active
            if self.cfg.degrade_max_new_tokens > 0 and (
                over_queue or slo_hot or res.degraded
            ):
                eff = min(eff, self.cfg.degrade_max_new_tokens)
                if eff < req.max_new_tokens:
                    res.degraded = True
                    self.reg.counter("serve_degraded").inc()
            self._eff_max_new[req.rid] = eff

        try:
            # The prefix-store build is INSIDE the guarded region: a
            # retry-exhausted prefix prefill must end this request typed
            # (FAILED) with its pages returned, not escape the scheduler.
            self._retry_scope = [req.rid]
            base, base_len = self._prefix_base(req)
            suffix = seq[base_len:]
            padded = _pad_to_bucket(
                suffix, self.cfg.prefill_bucket, self.mcfg.max_seq_len - base_len
            )
            self._retry_scope = [req.rid]
            cache1, tok, _fin = self._checked_prefill(
                base, padded, len(suffix), adapter_slot=self._adapter_slot(req)
            )
        except TransientStepError as e:
            self._release_slot(req.rid)  # return the reserved pages
            err = RequestFailedError(
                f"request {req.rid}: prefill retries exhausted"
            )
            err.__cause__ = e
            self._finish(req.rid, RequestState.FAILED, err)
            return
        self.cache = self._insert_fn(
            self.cache, cache1, jnp.int32(slot_i), jnp.int32(len(seq))
        )
        self._fps_memo = None
        if self.spec_on:
            # Prefill the draft rung over the FULL sequence (no prefix
            # store on the draft — its prefill is draft_layers/n_layers
            # of the target's, and sharing target-built prefix KV is
            # shape-impossible) and land its frontier at len(seq), the
            # same place the target insert pinned. Re-admission after
            # eviction/failover passes through here too, so a recovered
            # request resumes with BOTH caches rebuilt — no mid-rollback
            # state can survive a recovery (rounds are atomic in-jit).
            dpad = _pad_to_bucket(
                seq, self.cfg.prefill_bucket, self.mcfg.max_seq_len
            )
            dcache1 = self._draft_prefill_fn(
                self.draft_params, init_cache(self.draft_model, 1),
                jnp.asarray(np.asarray(dpad, np.int32)[None]),
            )
            self.draft_cache = self._insert_fn(
                self.draft_cache, dcache1, jnp.int32(slot_i),
                jnp.int32(len(seq)),
            )
        slot = self.slots[slot_i]
        slot.rid = req.rid
        slot.frontier = len(seq)
        slot.page_fp = {}
        if self.lora_on:
            # The slot now decodes under this request's adapter: one host
            # int per slot, shipped to the step as the (slots,) gather
            # index vector (same lifecycle as last_tok).
            self.slot_adapter[slot_i] = self._adapter_slot(req)
        if self._track_pages and len(seq) >= self.cfg.page_size:
            fps = self._page_fps()
            for p in range(len(seq) // self.cfg.page_size):
                slot.page_fp[p] = float(fps[slot_i, p])
        now = self.clock()
        res.admitted_t = now
        res.state = RequestState.DECODE
        tok = int(np.asarray(tok))
        res.tokens.append(tok)
        if res.first_token_t is None:
            res.first_token_t = now
            self.reg.histogram("serve_ttft_s").observe(res.ttft_s or 0.0)
            self.reg.histogram("serve_queue_wait_s").observe(
                res.queue_wait_s or 0.0
            )
            if self.lora_on:
                # Per-tenant TTFT: one histogram per adapter name ("base"
                # for un-adapted requests) next to the aggregate — the
                # SLO surface a noisy-neighbor tenant shows up on.
                self.reg.histogram(
                    f"serve_ttft_s.{req.adapter or 'base'}"
                ).observe(res.ttft_s or 0.0)
            if self.slo is not None:
                self.slo.observe("serve_ttft_s", res.ttft_s)
                self.slo.observe("serve_queue_wait_s", res.queue_wait_s)
        # Request waterfall spans: queued (submit — or last eviction — to
        # this admission) then prefill, on the request's own track. All
        # edges are timestamps already taken above: zero extra clock work
        # beyond t_adm. Explicit None checks: an injected clock may
        # legitimately read 0.0 at submit.
        q0 = res.requeued_t
        if q0 is None:
            q0 = res.submitted_t if res.submitted_t is not None else t_adm
        self.tracer.emit_span(
            "req.queued", self._ts(q0), self._ts(t_adm),
            cat="serve", tid=req.rid, rid=req.rid, iteration=self._it,
        )
        res.requeued_t = None
        self.tracer.emit_span(
            "req.prefill", self._ts(t_adm), self._ts(now), cat="serve",
            tid=req.rid, rid=req.rid,
            resident=len(seq), prefix_len=base_len, slot=slot_i,
        )
        if self.goodput is not None:
            # A re-prefill after an eviction or a failover hop is the
            # incident's recompute, not fresh productive prefill.
            self.goodput.note(
                "failover_replay"
                if (res.n_evictions or res.n_hops) else "prefill",
                now - t_adm,
            )
            self._gp_work += now - t_adm
        self.last_tok[slot_i] = tok
        self.reg.counter("serve_admissions").inc()
        self.reg.emit(
            "serve_admit", rid=req.rid, slot=slot_i, resident=len(seq),
            prefix_len=base_len, iteration=self._it, adapter=req.adapter,
        )
        self._maybe_complete(slot_i)

    def _ensure_pages(self) -> None:
        """Before decoding, every active slot needs pages covering its
        NEXT write (frontier + 1 plain; frontier + spec_k under
        speculation — the verify writes the whole window before rolling
        back, and the draft surcharge rides along via _pages_needed).
        Exhaustion evicts the lowest-priority, most-recently-admitted
        request — possibly the grower itself."""
        step_write = self.cfg.spec.spec_k if self.spec_on else 1
        for i, slot in enumerate(self.slots):
            if slot.rid is None:
                continue
            need = self._pages_needed(slot.frontier + step_write)
            while not self.alloc.ensure(slot.rid, need):
                key = self.alloc.evict_prefix_lru()
                if key is not None:
                    self._prefix_store.pop(key, None)
                    self.reg.counter("serve_prefix_evictions").inc()
                    continue
                active = [s.rid for s in self.slots if s.rid is not None]
                victim = min(
                    active,
                    key=lambda r: (
                        self.requests[r].priority,
                        -(self.results[r].admitted_t or 0.0),
                    ),
                )
                self._evict(victim, reason="cache_pressure")
                if victim == slot.rid:
                    break

    def _decode(self) -> None:
        if self.spec_on:
            return self._decode_spec()
        active = [
            (i, s.rid) for i, s in enumerate(self.slots) if s.rid is not None
        ]
        if not active:
            return
        self._worked = True
        t_dec = self.clock()
        prev_cache = self.cache  # kept alive so a retry re-runs bit-exactly
        toks = jnp.asarray(self.last_tok)
        last_fin = np.ones((self.cfg.slots,), bool)

        aids = (
            jnp.asarray(self.slot_adapter) if self.lora_on else None
        )

        def attempt():
            nonlocal last_fin
            if self.lora_on:
                cache, nxt, fin = self._step_fn(
                    self.params, self.lora_stack, aids, prev_cache, toks
                )
            else:
                cache, nxt, fin = self._step_fn(self.params, prev_cache, toks)
            nxt = np.asarray(nxt)
            fin = np.asarray(fin).copy()
            if self.chaos is not None and self.chaos.serve_poison_logits(
                self._it
            ):
                fin[:] = False  # the observed device buffer reads back NaN
            last_fin = fin
            if not all(bool(fin[i]) for i, _ in active):
                raise TransientStepError(
                    f"non-finite logits in decode step (iteration {self._it})"
                )
            return cache, nxt

        r = self.cfg.retry
        self._retry_scope = [rid for _, rid in active]
        try:
            cache, nxt = retry_call(
                attempt, transient=(TransientStepError,),
                max_attempts=r.max_attempts, backoff_s=r.backoff_s,
                backoff_max_s=r.backoff_max_s, jitter=r.jitter,
                max_elapsed_s=r.max_elapsed_s, on_event=self._on_retry_event,
                sleep=self.sleep, clock=self.clock,
            )
        except TransientStepError as e:
            # Localize the blast radius: only slots whose logits actually
            # read non-finite on the LAST attempt fail; co-scheduled
            # healthy requests keep their slots and retry next iteration
            # (the step's outputs were discarded, so nothing advanced —
            # their pre-step cache is intact).
            for i, rid in active:
                if bool(last_fin[i]):
                    continue
                self._release_slot(rid)
                err = RequestFailedError(
                    f"request {rid}: decode step retries exhausted"
                )
                err.__cause__ = e
                self._finish(rid, RequestState.FAILED, err)
            return
        finally:
            self._retry_scope = []
        self.cache = cache
        self._fps_memo = None
        now = self.clock()
        # Scheduler-side decode-iteration span (one per iteration over
        # the whole in-flight batch — the Orca iteration waterfall).
        self.tracer.emit_span(
            "decode_step", self._ts(t_dec), self._ts(now), cat="serve",
            tid="sched", iteration=self._it, batch=len(active),
        )
        if self.goodput is not None:
            self.goodput.note("productive_decode", now - t_dec)
            self._gp_work += now - t_dec
        completed_pages = []  # (slot_i, page) finished this step
        for i, rid in active:
            slot = self.slots[i]
            res = self.results[rid]
            tok = int(nxt[i])
            res.tokens.append(tok)
            self.last_tok[i] = tok
            slot.frontier += 1  # the step's input token is now resident
            if self._track_pages and slot.frontier % self.cfg.page_size == 0:
                completed_pages.append((i, slot.frontier // self.cfg.page_size - 1))
        if completed_pages:
            fps = self._page_fps()
            for i, p in completed_pages:
                self.slots[i].page_fp[p] = float(fps[i, p])
        for i, _rid in active:
            self._maybe_complete(i, now=now)
        self.reg.counter("serve_decode_steps").inc()
        self.reg.histogram("serve_batch_occupancy").observe(len(active))

    def _decode_spec(self) -> None:
        """One speculative iteration over the in-flight batch: ONE round
        (draft propose + single k-verify launch + greedy accept +
        rollback — :func:`dtc_tpu.spec.serve_round`) emits 1..spec_k
        tokens per active slot. Same retry / poison-localization /
        page-fingerprint contract as :meth:`_decode`; the extras are the
        honesty plumbing — emitted-vs-window goodput split, per-request
        proposal/acceptance counts, and the accepted-tokens/s SLO feed."""
        active = [
            (i, s.rid) for i, s in enumerate(self.slots) if s.rid is not None
        ]
        if not active:
            return
        self._worked = True
        t_dec = self.clock()
        spec_k = self.cfg.spec.spec_k
        # Retry re-runs bit-exactly from the PRE-round caches (greedy, no
        # rng) — both references held until the round is accepted.
        prev_cache, prev_draft = self.cache, self.draft_cache
        toks = jnp.asarray(self.last_tok)[:, None]
        remaining = np.zeros((self.cfg.slots,), np.int32)
        for i, rid in active:
            remaining[i] = max(
                self._eff_max_new[rid] - len(self.results[rid].tokens), 0
            )
        rem = jnp.asarray(remaining)  # 0 freezes idle slots' frontiers
        last_fin = np.ones((self.cfg.slots,), bool)

        def attempt():
            nonlocal last_fin
            tcache, dcache, _tok_next, emit, n_emit, fin = serve_round(
                self.model, self.draft_model, spec_k, self.params,
                self.draft_params, prev_cache, prev_draft, toks, rem,
            )
            emit = np.asarray(emit)
            n_emit = np.asarray(n_emit)
            fin = np.asarray(fin).copy()
            if self.chaos is not None and self.chaos.serve_poison_logits(
                self._it
            ):
                fin[:] = False  # the observed device buffer reads back NaN
            last_fin = fin
            if not all(bool(fin[i]) for i, _ in active):
                raise TransientStepError(
                    f"non-finite logits in spec verify (iteration {self._it})"
                )
            return tcache, dcache, emit, n_emit

        r = self.cfg.retry
        self._retry_scope = [rid for _, rid in active]
        try:
            tcache, dcache, emit, n_emit = retry_call(
                attempt, transient=(TransientStepError,),
                max_attempts=r.max_attempts, backoff_s=r.backoff_s,
                backoff_max_s=r.backoff_max_s, jitter=r.jitter,
                max_elapsed_s=r.max_elapsed_s, on_event=self._on_retry_event,
                sleep=self.sleep, clock=self.clock,
            )
        except TransientStepError as e:
            # Same blast-radius localization as _decode: only slots whose
            # verify logits read non-finite on the LAST attempt fail; the
            # round's outputs were discarded, so healthy co-scheduled
            # requests retry next iteration from intact pre-round caches
            # (no frontier moved — rounds are atomic).
            for i, rid in active:
                if bool(last_fin[i]):
                    continue
                self._release_slot(rid)
                err = RequestFailedError(
                    f"request {rid}: spec verify retries exhausted"
                )
                err.__cause__ = e
                self._finish(rid, RequestState.FAILED, err)
            return
        finally:
            self._retry_scope = []
        self.cache, self.draft_cache = tcache, dcache
        self._fps_memo = None
        now = self.clock()
        n_active = len(active)
        emitted = int(sum(int(n_emit[i]) for i, _ in active))
        # Goodput honesty (the ISSUE 19 accounting contract): the round's
        # wall time is split by the fraction of the verify window that
        # EMITTED — the rest is the draft-proposal/verify work the target
        # rejected, billed to the typed spec_rejected_draft badput class
        # (never productive_decode) in both the online gauge and the
        # offline span-ledger (a paired decode_step + spec_reject span).
        dur = now - t_dec
        frac = emitted / float(max(n_active * spec_k, 1))
        t_split = t_dec + dur * frac
        self.tracer.emit_span(
            "decode_step", self._ts(t_dec), self._ts(t_split), cat="serve",
            tid="sched", iteration=self._it, batch=n_active,
            spec_k=spec_k, emitted=emitted,
        )
        if dur * (1.0 - frac) > 0.0:
            self.tracer.emit_span(
                "spec_reject", self._ts(t_split), self._ts(now), cat="serve",
                tid="sched", iteration=self._it,
                rejected=n_active * spec_k - emitted,
            )
        if self.goodput is not None:
            self.goodput.note("productive_decode", dur * frac)
            self.goodput.note(SPEC_REJECTED_DRAFT, dur * (1.0 - frac))
            self._gp_work += dur
        completed_pages = []  # (slot_i, page) finished this round
        for i, rid in active:
            slot = self.slots[i]
            res = self.results[rid]
            ne = int(n_emit[i])
            new_toks = [int(t) for t in emit[i, :ne]]
            res.n_spec_proposed += spec_k - 1
            res.n_spec_accepted += max(ne - 1, 0)
            req = self.requests[rid]
            if req.eos_id is not None and req.eos_id in new_toks:
                # Plain decode would have stopped AT the eos — truncate
                # the emission there so the result is token-identical
                # (the slot completes below; its frontier/cache state
                # past the eos is idle-slot garbage from then on).
                new_toks = new_toks[: new_toks.index(req.eos_id) + 1]
            res.tokens.extend(new_toks)
            if new_toks:
                self.last_tok[i] = new_toks[-1]
            old_pages = slot.frontier // self.cfg.page_size
            slot.frontier += ne
            if self._track_pages:
                completed_pages.extend(
                    (i, p) for p in range(
                        old_pages, slot.frontier // self.cfg.page_size
                    )
                )
            self.reg.histogram("serve_accepted_per_launch").observe(ne)
        if completed_pages:
            fps = self._page_fps()
            for i, p in completed_pages:
                self.slots[i].page_fp[p] = float(fps[i, p])
        for i, _rid in active:
            self._maybe_complete(i, now=now)
        self._spec_emitted_since += emitted
        self._spec_rounds_since += 1
        self.reg.counter("serve_decode_steps").inc()
        self.reg.counter("serve_spec_rounds").inc()
        self.reg.counter("serve_spec_proposed").inc(n_active * (spec_k - 1))
        self.reg.counter("serve_spec_accepted").inc(emitted - n_active)
        self.reg.counter("serve_spec_rejected").inc(
            n_active * (spec_k - 1) - (emitted - n_active)
        )
        self.reg.histogram("serve_batch_occupancy").observe(n_active)

    # ------------------------------------------------------------------
    # recovery paths
    # ------------------------------------------------------------------
    def _evict(self, rid: str, *, reason: str) -> None:
        """Evict one active request: free pages + slot, requeue at the
        head with its generated tokens intact. Re-admission re-prefills
        prompt+generated and resumes — greedy decode makes the
        continuation token-for-token identical (asserted in tests)."""
        self._release_slot(rid)
        res = self.results[rid]
        res.state = RequestState.EVICTED  # observable until re-admission
        res.n_evictions += 1
        # The next req.queued span starts HERE, not at submit — the
        # waterfall shows the evict→requeue→re-prefill chain as segments.
        res.requeued_t = self.clock()
        self.queue.insert(0, self.requests[rid])
        self.reg.counter("serve_evictions").inc()
        self.reg.emit(
            "serve_evict", rid=rid, reason=reason, iteration=self._it,
            generated=len(res.tokens),
        )

    def _preempt_newest(self) -> None:
        active = [s.rid for s in self.slots if s.rid is not None]
        if not active:
            return
        victim = max(active, key=lambda r: self.results[r].admitted_t or 0.0)
        self.reg.counter("serve_preemptions").inc()
        self._evict(victim, reason="preempted")

    def _corruption_candidates(self) -> list:
        """Slots with a completed (fingerprinted) page — what chaos
        corruption and the verifier can act on."""
        return [
            (i, s) for i, s in enumerate(self.slots)
            if s.rid is not None and s.page_fp
        ]

    def _inject_corruption(self) -> None:
        """Chaos: damage a completed page of the oldest active request on
        the real device cache (the verifier must catch it)."""
        cands = self._corruption_candidates()
        if not cands:
            return
        i, slot = min(
            cands, key=lambda t: self.results[t[1].rid].admitted_t or 0.0
        )
        page = min(slot.page_fp)
        self.cache = self._corrupt_fn(
            self.cache, jnp.int32(i), jnp.int32(page * self.cfg.page_size),
            size=self.cfg.page_size,
        )
        self._fps_memo = None

    def _verify_pages(self) -> None:
        """Recompute completed-page checksums for every active slot; a
        mismatch is cache-block corruption — typed event + evict for
        bit-exact re-prefill (run every iteration to guarantee no token
        computed from a damaged page is ever emitted)."""
        if not any(s.rid is not None and s.page_fp for s in self.slots):
            return
        fps = self._page_fps()
        for i, slot in enumerate(self.slots):
            if slot.rid is None:
                continue
            for p, fp in slot.page_fp.items():
                if float(fps[i, p]) != fp:
                    self.reg.counter("serve_corruptions").inc()
                    self.reg.emit(
                        "serve_corruption", rid=slot.rid, slot=i, page=p,
                        iteration=self._it,
                    )
                    rid = slot.rid
                    self._evict(rid, reason="corruption")
                    self.dump_flight(
                        "serve_corruption", rid=rid, iteration=self._it
                    )
                    break

    # ------------------------------------------------------------------
    # bookkeeping
    # ------------------------------------------------------------------
    def _page_fps(self) -> np.ndarray:
        """The (slots, n_pages) checksum table — one call, one transfer,
        memoized per cache version (every site that replaces self.cache
        resets ``_fps_memo``), so a decode that completes a page and the
        next iteration's verifier pass share ONE reduction."""
        if self._fps_memo is None:
            self._fps_memo = np.asarray(self._fingerprint_fn(self.cache))
        return self._fps_memo

    def _maybe_complete(self, slot_i: int, now: float | None = None) -> None:
        slot = self.slots[slot_i]
        rid = slot.rid
        if rid is None:
            return
        req = self.requests[rid]
        res = self.results[rid]
        done = len(res.tokens) >= self._eff_max_new[rid] or (
            req.eos_id is not None and res.tokens and res.tokens[-1] == req.eos_id
        )
        if done:
            self._release_slot(rid)
            self._finish(rid, RequestState.DONE, None, now=now)

    def _release_slot(self, rid: str) -> None:
        for i, slot in enumerate(self.slots):
            if slot.rid == rid:
                slot.rid = None
                slot.frontier = 0
                slot.page_fp = {}
                if self.lora_on:
                    self.slot_adapter[i] = BASE_SLOT
        self.alloc.free(rid)

    def _finish(
        self, rid: str, state: RequestState, error, now: float | None = None
    ) -> None:
        res = self.results[rid]
        res.state = state
        res.error = error
        res.finished_t = self.clock() if now is None else now
        # Terminal: drop all per-request host state except the result
        # itself (kept until the caller reads/drains it) — a long-running
        # server must not grow with total requests served.
        self._deadline.pop(rid, None)
        self._eff_max_new.pop(rid, None)
        req = self.requests.pop(rid, None)
        if (
            self.lora_on and req is not None and req.adapter is not None
        ):
            self.adapter_store.release(req.adapter)  # unpin at terminal
        self.reg.counter(f"serve_{state.value}").inc()
        if state is RequestState.DONE and res.ms_per_token is not None:
            self.reg.histogram("serve_ms_per_token").observe(res.ms_per_token)
            if self.lora_on:
                self.reg.histogram(
                    f"serve_ms_per_token.{res.adapter or 'base'}"
                ).observe(res.ms_per_token)
            if self.slo is not None:
                self.slo.observe("serve_ms_per_token", res.ms_per_token)
        if res.accept_rate is not None:
            # Per-request acceptance (ISSUE 19) — every terminal outcome,
            # not just DONE: a shed/expired request's acceptance is still
            # real telemetry about the draft's fit to the workload.
            self.reg.histogram("serve_accept_rate").observe(res.accept_rate)
        if self.slo is not None:
            self.slo.observe_outcome(
                "serve_outcome_shed", state is RequestState.SHED
            )
        # Close the request's span chain: the decode span (first token →
        # terminal, spanning any eviction gaps — the evict instants mark
        # those) and a terminal instant naming the outcome.
        if res.first_token_t is not None:
            self.tracer.emit_span(
                "req.decode", self._ts(res.first_token_t),
                self._ts(res.finished_t), cat="serve",
                tid=rid, rid=rid, n_tokens=len(res.tokens),
            )
        self.tracer.instant(
            f"req.{state.value}", cat="serve", tid=rid,
            t=self._ts(res.finished_t),
            rid=rid, error=type(error).__name__ if error else None,
        )
        self.reg.emit("serve_request", iteration=self._it, **res.summary())
        if state is RequestState.FAILED and not self._in_shutdown:
            self.dump_flight(f"request_failed: {rid}", rid=rid)

    def _on_retry_event(self, etype: str, **fields: Any) -> None:
        self.reg.counter("serve_retries").inc()
        for rid in self._retry_scope:
            self.results[rid].n_retries += 1
        self.bus.post(etype, **fields)

    def _ts(self, t: float) -> float:
        """Scheduler-clock timestamp -> the emission (epoch) timebase —
        the constant shift that makes serve spans sortable against
        trainer shards (see __init__); durations are unaffected."""
        return t + self._epoch0

    def dump_flight(self, reason: str, **meta: Any) -> str | None:
        """Dump the flight-recorder ring (telemetry owns the file path;
        bare engines keep the ring in memory for the caller/tests)."""
        if self.telemetry is not None:
            return self.telemetry.dump_flight(reason, **meta)
        return None

    def _drain_bus(self) -> None:
        for etype, fields in self.bus.drain():
            if etype == "chaos":
                self.reg.counter("chaos_injections").inc()
                # Every injected fault leaves a timeline: the post-mortem
                # the flight recorder exists for, exercised by chaos.
                self.dump_flight(
                    f"chaos: {fields.get('kind', '?')}", iteration=self._it
                )
            elif etype == "recovery":
                self.reg.counter("recoveries").inc()
            fields.setdefault("iteration", self._it)
            self.reg.emit(etype, **fields)
