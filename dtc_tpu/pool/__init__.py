"""Resource pool: one chaos-verified manager over train + serve (ISSUE 17).

See :mod:`dtc_tpu.pool.manager` for the PoolManager and the typed
transition state machine, README "Resource pool / autoscaling" for
semantics, and ``configs/pool_config.yaml`` for knobs.
"""

from dtc_tpu.pool.manager import (
    POOL_ROUTER_PROC,
    POOL_TRAIN_PROC,
    PoolManager,
    PoolTransition,
)

__all__ = [
    "POOL_ROUTER_PROC",
    "POOL_TRAIN_PROC",
    "PoolManager",
    "PoolTransition",
]
