"""PoolManager: one fixed virtual-device pool arbitrated between the
serving fleet and the elastic trainer (ISSUE 17).

The ROADMAP north-star is a production system where the same chip pool
serves diurnal traffic AND keeps training — capacity must move between
the tenants without dropping a request or losing a step. Every enabler
landed earlier: PR 12's FleetRouter/EngineReplica seam (spawn/retire +
failover), PR 14/15's ring-mirrored snapshots + shrink-and-continue
(now generalized to :func:`~dtc_tpu.resilience.elastic.resize_mesh` —
GROW is shrink in reverse), PR 16's goodput ledger to price every
transition. This module is the arbiter on top.

**Leases.** Each of the pool's ``n_hosts`` virtual hosts is leased to
exactly one tenant at a time: a serving host runs one engine replica, a
training host contributes its devices to the train mesh. The pool owns
the lease table; the tenants own their machinery.

**Transitions** are a typed state machine — every lease move walks

    requested -> draining -> reassigned -> resized -> steady

(one state per pool tick, so every stage is observable and chaos can
land inside any of them; a GROW interrupted by a load spike before its
mesh is rebuilt takes the one extra edge ``-> aborted`` and rolls back
cleanly). For a GROW (serve -> train): ``draining`` retires the victim
replicas (stop routing new work, in-flight finishes — or fails over if
chaos kills the replica mid-drain), ``reassigned`` admits the freed
hosts to the trainer's monitor roster, ``resized`` rebuilds the larger
mesh and restores the newest complete snapshot onto it with fresh
NamedShardings (per-device batch rescales, GLOBAL batch preserved, the
row stream re-seeks by tokens consumed), ``steady`` lands after the
first post-resize step — which pays the mesh change's exactly-one
recompile. For a SHRINK (train -> serve): ``draining`` ensures a
complete snapshot covers the current step, ``reassigned`` retires the
surrendered hosts from the monitor (deliberate surrender, not death),
``resized`` rebuilds the smaller mesh (a host chaos-killed
mid-surrender is safe: its snapshot primaries died with it, the ring
mirror sources the restore) and spawns replicas on the freed hosts —
zero compiles, the engine fn cache shares the jitted executables.

**Zero silent drops.** ``submit()`` parks requests the fleet cannot
admit (including the zero-replica full-grow phase) in a pool-level
pending queue and re-submits as capacity returns; close() reconciles
every parked leftover to a typed FAILED terminal. Every rid therefore
ends in a typed terminal somewhere — router, engine, or pool backstop.

**Honesty.** Pool "hosts" time-slice one CPU process: wall-clocks are
shape-only (a transition's measured seconds reflect this emulation, not
DCN). What IS real: detection and recovery read only surviving state,
GROW restores are bit-checked against a fresh restart from the same
snapshot, and every recompile is counted, asserted, and billed.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import numpy as np

from dtc_tpu.obs.registry import JsonlSink, MetricsRegistry
from dtc_tpu.resilience.chaos import ChaosInjector
from dtc_tpu.resilience.elastic import HostMonitor, VirtualHosts, resize_mesh
from dtc_tpu.resilience.errors import ElasticAbort
from dtc_tpu.resilience.events import RecoveryBus
from dtc_tpu.resilience.snapshot import SnapshotStore
from dtc_tpu.serve.replica import ReplicaState
from dtc_tpu.serve.request import (
    FleetSaturatedError,
    QueueFullError,
    Request,
    RequestFailedError,
    RequestState,
    ServeResult,
)
from dtc_tpu.serve.router import FleetRouter
from dtc_tpu.utils.arrivals import seeded_prompts

PyTree = Any

#: Obs shard (process index) for the router's own registry under the
#: pool — well above any replica id spawn/retire will ever mint.
POOL_ROUTER_PROC = 64
#: Obs shard for the train tenant's registry.
POOL_TRAIN_PROC = 65

#: The typed transition machine: every edge a lease move may take.
#: Advancement is one edge per pool tick; anything else is a bug, not a
#: new state — _advance raises on an illegal edge.
_TRANSITION_EDGES: dict[str, frozenset[str]] = {
    "requested": frozenset({"draining", "aborted"}),
    "draining": frozenset({"reassigned", "aborted"}),
    "reassigned": frozenset({"resized", "aborted"}),
    "resized": frozenset({"steady"}),
    "steady": frozenset(),
    "aborted": frozenset(),
}


@dataclasses.dataclass
class PoolTransition:
    """One lease move through the typed state machine."""

    kind: str                    # "grow" | "shrink"
    hosts: list[int]             # hosts changing tenant
    tick: int                    # pool tick the transition was requested
    state: str = "requested"
    replicas: list[int] = dataclasses.field(default_factory=list)
    t_requested: float = 0.0
    t_detect: float | None = None    # mesh-rebuild start (the stall window)
    t_restored: float | None = None  # restore + step-fn rebuild complete
    to_step: int | None = None       # snapshot step the resize restored
    used_mirror: bool = False
    dead_hosts: list[int] = dataclasses.field(default_factory=list)
    abort_reason: str | None = None

    @property
    def terminal(self) -> bool:
        return self.state in ("steady", "aborted")


class _TrainTenant:
    """The pool's training tenant: a step-driven mini-loop over the
    trainer's own primitives (init_state / create_train_step /
    split_put / synthetic_row_batches / SnapshotStore), emitting the
    exact event schema the goodput ledger and trace tooling consume —
    run_start, per-step ``step`` events, startup ``compile``, steady
    ``recompile``, ``elastic_resize`` + ``aux_compile`` on resize."""

    def __init__(
        self,
        model,
        model_cfg,
        cfg,                      # PoolConfig
        hosts: VirtualHosts,
        lease: set[int],
        reg: MetricsRegistry,
        *,
        seed: int = 0,
    ):
        import jax

        from dtc_tpu.config.schema import OptimConfig, TrainConfig
        from dtc_tpu.obs.stepclock import CompileWatcher
        from dtc_tpu.parallel.mesh import build_mesh
        from dtc_tpu.parallel.sharding import DEFAULT_RULES, batch_spec
        from dtc_tpu.train.train_step import create_train_step
        from dtc_tpu.train.trainer import init_state

        self.model = model
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.hosts = hosts
        self.lease = set(lease)
        self.reg = reg
        self.seed = seed
        self.rules = DEFAULT_RULES
        self.spec = batch_spec(DEFAULT_RULES)
        self.seq = model_cfg.max_seq_len + 1

        # Monitor over the TRAIN lease only: construct over the full
        # alive roster, then retire the serving hosts — they are another
        # tenant's problem, not missing heartbeats.
        self.monitor = HostMonitor(
            hosts, miss_limit=cfg.heartbeat_miss_limit
        )
        for h in sorted(hosts.alive - self.lease):
            self.monitor.retire(h)

        # All compile seconds from here on are the train tenant's (the
        # serving fleet warms up BEFORE this tenant is constructed).
        self.compiles = CompileWatcher().activate()

        self._train_cfg = TrainConfig(
            seed=seed, parallel="dp", batch=cfg.global_batch,
            steps=cfg.train_steps, log_every=1_000_000, output_dir="",
        )
        self._opt_cfg = OptimConfig(lr=1e-2, weight_decay=0.0, grad_clip=1.0)

        devices = [d for h in sorted(self.lease)
                   for d in hosts.devices_of(h)]
        self.mesh = build_mesh(
            (1, len(devices) // cfg.model_axis, cfg.model_axis),
            devices=devices,
        )
        self.state = init_state(
            model, model_cfg, self._train_cfg, self._opt_cfg, self.mesh,
        )
        self.step_fn = create_train_step(
            self.mesh, model=model, state=self.state,
        )
        self.snapshots = SnapshotStore(
            hosts, keep=cfg.snapshot_keep,
            on_event=lambda etype, **f: self.reg.emit(etype, **f),
        )
        self.key = jax.random.PRNGKey(seed)
        self.cur_step = 0
        self.losses: list[float] = []
        self.recompiles = 0
        self._steady = False
        self.data = self._make_data(start_row=0)

        init_s, init_n = self.compiles.drain()
        self.reg.emit(
            "run_start", step=0, batch=cfg.global_batch,
            seq_len=model_cfg.max_seq_len, devices=len(devices),
            hosts=sorted(self.lease), pool=True,
        )
        if init_s > 0:
            self.reg.emit(
                "compile", step=0, compile_time_s=round(init_s, 6),
                count=init_n,
            )

    # ------------------------------------------------------------------
    def _make_data(self, start_row: int):
        from dtc_tpu.data.synthetic import synthetic_row_batches

        return synthetic_row_batches(
            self.cfg.global_batch, self.seq, self.model_cfg.vocab_size,
            seed=self.seed * 1000, start_row=start_row,
        )

    @property
    def finished(self) -> bool:
        return self.cur_step >= self.cfg.train_steps

    @property
    def per_device_batch(self) -> float:
        n = len(self.lease) * self.hosts.per_host
        return self.cfg.global_batch / max(n // self.cfg.model_axis, 1)

    def step_once(self) -> float:
        """One training step on the current mesh: data -> step -> beat
        the monitor -> snapshot cadence -> step event. GLOBAL batch is
        constant across resizes; the mesh's data axis decides the
        per-device share."""
        import jax

        from dtc_tpu.data.prefetch import split_put
        from dtc_tpu.train.train_step import Batch

        self.cur_step += 1
        t0 = time.perf_counter()
        batch = next(self.data)
        x, y = split_put(batch, self.mesh, self.spec)
        with self.mesh:
            self.state, loss = self.step_fn(
                self.state, Batch(x=x, y=y),
                jax.random.fold_in(self.key, self.cur_step),
            )
            loss = float(jax.block_until_ready(loss))
        dur = time.perf_counter() - t0
        comp_s, comp_n = self.compiles.drain()
        fields: dict[str, Any] = {
            "step": self.cur_step, "step_time_s": round(dur, 6),
            "loss": round(loss, 6),
        }
        if comp_s > 0:
            if self._steady:
                self.recompiles += 1
                self.reg.counter("recompiles").inc()
                self.reg.emit(
                    "recompile", step=self.cur_step,
                    compile_s=round(comp_s, 6), count=comp_n,
                )
                fields["compile_s"] = round(comp_s, 6)
            else:
                self.reg.emit(
                    "compile", step=0, compile_time_s=round(comp_s, 6),
                    count=comp_n,
                )
        self._steady = True
        self.reg.emit("step", **fields)
        self.losses.append(loss)
        self.monitor.tick(self.cur_step)
        for ev in self.monitor.poll(self.cur_step):
            self.reg.emit(ev.pop("kind"), **ev)
        if self.cur_step % self.cfg.snapshot_every == 0:
            t_snap0 = self.reg._clock()
            self.snapshots.begin(self.cur_step, self.state)
            # ``begin`` jit-compiles one tiny device copy per distinct
            # leaf shape (first begin, and again after every resize's
            # fresh shardings). Drain those NOW into their own
            # ``aux_compile`` so they never masquerade as a step
            # recompile — "exactly one recompile per mesh change" is an
            # assertion, and it must count ONLY the step executable.
            snap_s, snap_n = self.compiles.drain()
            if snap_s > 0:
                self.reg.emit(
                    "aux_compile", step=self.cur_step, what="snapshot_copy",
                    compile_s=round(snap_s, 6), count=snap_n,
                )
            t_snap1 = self.reg._clock()
            # The synchronous half of the async snapshot (device copies
            # dispatched on the hot loop before the commit thread takes
            # over) is snapshot wall, not a mystery gap between steps.
            # The compile portion is already billed by the aux_compile
            # above (its interval ends ~t_snap1), so the dispatch span
            # stops where that interval starts — no double-count.
            disp = t_snap1 - t_snap0 - snap_s
            if disp > 0.002:
                self.reg.emit(
                    "span", name="snapshot_dispatch", cat="pool", ph="X",
                    tid="pool", t0=round(t_snap0, 6), dur_s=round(disp, 6),
                    step=self.cur_step,
                )
        return loss

    def resize(self, new_lease: set[int], *, reason: str) -> dict[str, Any]:
        """Rebuild the mesh over ``new_lease`` (GROW or SHRINK) and
        restore the newest complete snapshot onto it — shrink-and-
        continue, both directions. Exactly one recompile follows at the
        first post-resize step (the step executable's input shardings
        changed); everything here is device_put + rebuild, attributed
        via ``aux_compile`` if XLA compiles anything at all."""
        from dtc_tpu.train.train_step import (
            canonicalize_state_placement,
            create_train_step,
        )

        t_detect = self.reg._clock()
        self.snapshots.drain()
        snap = self.snapshots.latest()
        if snap is None:
            raise ElasticAbort(
                "pool resize: no complete snapshot to restore from"
            )
        new_mesh = resize_mesh(self.mesh, self.hosts, target_hosts=new_lease)
        state, used_mirror = self.snapshots.restore(
            snap, self.hosts.alive, new_mesh,
        )
        self.mesh = new_mesh
        self.state = canonicalize_state_placement(state, new_mesh)
        self.step_fn = create_train_step(
            new_mesh, model=self.model, state=self.state,
        )
        # Re-seek the row stream by tokens consumed: the flat row stream
        # is batch-shape-independent, and the global batch is constant,
        # so rows consumed at the restored step = step x global_batch.
        replayed = self.cur_step - snap.step
        self.cur_step = snap.step
        del self.losses[snap.step:]
        self.data = self._make_data(start_row=snap.step * self.cfg.global_batch)
        self.lease = set(new_lease)
        comp_s, comp_n = self.compiles.drain()
        t_restored = self.reg._clock()
        n_dev = len(new_lease) * self.hosts.per_host
        self.reg.emit(
            "elastic_resize", step=snap.step, to_step=snap.step,
            tier="memory", used_mirror=used_mirror, reason=reason,
            devices=n_dev, hosts=sorted(new_lease),
            per_device_batch=self.per_device_batch,
            replayed_steps=replayed,
            t_detect=round(t_detect, 6), t_restored=round(t_restored, 6),
        )
        if comp_s > 0:
            self.reg.emit(
                "aux_compile", step=snap.step, what="elastic_resize",
                compile_s=round(comp_s, 6), count=comp_n,
            )
        return {
            "to_step": snap.step, "used_mirror": used_mirror,
            "t_detect": t_detect, "t_restored": t_restored,
        }

    def close(self) -> None:
        self.snapshots.close()
        self.compiles.deactivate()


class PoolManager:
    """See module docstring. Construct once per (model, params, pool
    config); drive ``tick()`` (or ``run()``) — one tick is one unit of
    time-sliced pool work: chaos consults, parked-request retries, one
    fleet iteration, one transition edge, one training step, then the
    arbitration decision."""

    def __init__(
        self,
        model,
        params: PyTree,
        model_cfg,
        cfg,                     # PoolConfig
        *,
        obs_dir: str = "",
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.cfg = cfg
        self.model_cfg = model_cfg
        self.hosts = VirtualHosts(cfg.n_hosts)
        if self.hosts.per_host * cfg.min_train_hosts < cfg.model_axis:
            raise ElasticAbort(
                f"model_axis={cfg.model_axis} cannot fit the minimum "
                f"train lease ({cfg.min_train_hosts} hosts x "
                f"{self.hosts.per_host} devices)"
            )

        all_hosts = list(range(cfg.n_hosts))
        # Trainer leases the HIGH host ids; serving the low ones. LIFO
        # surrender (most recently acquired first) keeps the baseline
        # lease stable across a grow/shrink cycle.
        self.train_lease: set[int] = set(all_hosts[-cfg.train_hosts:])
        self._acquired: list[int] = []   # grow-acquired hosts, LIFO
        serve0 = [h for h in all_hosts if h not in self.train_lease]

        rcfg = dataclasses.replace(cfg.router, n_replicas=len(serve0))
        self.router = FleetRouter(
            model, params, rcfg, obs_dir=obs_dir,
            router_proc=POOL_ROUTER_PROC, clock=clock, sleep=sleep,
        )
        self.serve_lease: dict[int, int] = {
            h: rep.replica_id for h, rep in zip(serve0, self.router.replicas)
        }
        # Fleet jit happens HERE, before the train tenant activates its
        # compile watcher — serving warmup must not masquerade as train
        # compile time.
        self.router.warmup([1, 2, 3])

        self.reg = MetricsRegistry(process_index=POOL_TRAIN_PROC)
        if obs_dir:
            self.reg.add_sink(
                JsonlSink(f"{obs_dir}/events.r{POOL_TRAIN_PROC}.jsonl")
            )
        self.trainer = _TrainTenant(
            model, model_cfg, cfg, self.hosts, self.train_lease, self.reg,
            seed=seed,
        )

        self.bus = RecoveryBus()
        self.chaos = (
            ChaosInjector(cfg.chaos, self.bus) if cfg.chaos.enabled else None
        )
        self.transition: PoolTransition | None = None
        self.transitions: list[PoolTransition] = []
        self._parked: list[Request] = []
        self._parked_results: dict[str, ServeResult] = {}
        self._grow_abort = False
        self._idle_ticks = 0
        self._spike_seq = 0
        self._tick = 0

    # ------------------------------------------------------------------
    # request plane (zero silent drops)
    # ------------------------------------------------------------------
    def submit(self, req: Request) -> str:
        """Route into the fleet; a request the fleet cannot admit right
        now (saturated, or zero replicas mid-grow) PARKS in the pool's
        pending queue — typed backpressure the pool itself retries, so
        a transition never sheds a request silently."""
        try:
            return self.router.submit(req)
        except (FleetSaturatedError, QueueFullError) as e:
            self._parked.append(req)
            self.reg.emit(
                "pool_request_parked", rid=req.rid, tick=self._tick,
                error=type(e).__name__, parked=len(self._parked),
            )
            return req.rid

    def _unpark(self) -> None:
        while self._parked:
            req = self._parked[0]
            try:
                self.router.submit(req)
            except (FleetSaturatedError, QueueFullError):
                return
            self._parked.pop(0)
            self.reg.emit(
                "pool_request_unparked", rid=req.rid, tick=self._tick,
                parked=len(self._parked),
            )

    def results(self) -> dict[str, ServeResult]:
        """Fleet terminals + the pool backstop's typed terminals."""
        out = dict(self.router.results)
        out.update(self._parked_results)
        return out

    def _emit_timeshare(self, t0: float, t1: float) -> None:
        if t1 - t0 > 0.002:
            self.reg.emit(
                "span", name="pool.timeshare", cat="pool", ph="X",
                tid="pool", t0=round(t0, 6), dur_s=round(t1 - t0, 6),
            )

    # ------------------------------------------------------------------
    # the tick loop
    # ------------------------------------------------------------------
    def tick(self) -> bool:
        """One pool iteration. Returns True while anything is in
        flight: training budget unfinished, requests live anywhere, or
        a transition mid-walk."""
        self._tick += 1
        t_serve0 = self.reg._clock()
        self._consult_chaos()
        self._unpark()
        if self.router.live_replicas:
            self.router.step()
        t_serve1 = self.reg._clock()
        # The train tenant's CPU slice yielded to the co-tenant serving
        # fleet this tick (one process time-slices every pool "host").
        # Typed yields on the train shard's timeline — the goodput
        # ledger classes them shed_or_idle(cause=timeshare) instead of
        # leaving unattributed holes between steps. On a real pod the
        # tenants own disjoint machines and these spans have zero width.
        self._emit_timeshare(t_serve0, t_serve1)
        pre_resized = (
            self.transition is not None
            and self.transition.state == "resized"
        )
        self._advance_transition()
        t_adv = self.reg._clock()
        tr = self.transition
        if (tr is not None and tr.state == "resized" and not pre_resized
                and tr.t_detect is not None and tr.t_restored is not None):
            # The transition walk just resized: its [t_detect, t_restored]
            # window is already typed elastic_resize(cause=restore) by the
            # incident — the timeshare pieces are only the fleet work
            # around it (retire/spawn/lease bookkeeping).
            self._emit_timeshare(t_serve1, tr.t_detect)
            self._emit_timeshare(tr.t_restored, t_adv)
        else:
            self._emit_timeshare(t_serve1, t_adv)
        tr = self.transition
        can_step = not self.trainer.finished and (
            tr is None or tr.state in ("requested", "draining", "resized")
        )
        if can_step:
            self.trainer.step_once()
            if tr is not None and tr.state == "resized":
                # The first post-resize step just ran (and paid the mesh
                # change's one recompile) — the transition is steady.
                self._advance(tr, "steady")
                self.transition = None
        elif tr is not None and tr.state == "resized" and self.trainer.finished:
            # Resize landed ON the budget boundary: no further step will
            # ever run (so no recompile is owed) — steady immediately.
            self._advance(tr, "steady")
            self.transition = None
        if self.transition is None:
            self._arbitrate()
        self._drain_bus()
        return (
            not self.trainer.finished
            or bool(self.router.records)
            or bool(self._parked)
            or self.transition is not None
        )

    def run(self, *, max_ticks: int = 10_000) -> None:
        for _ in range(max_ticks):
            if not self.tick():
                return

    # ------------------------------------------------------------------
    # chaos (deferred-fire: consulted only while the named transition
    # is actually in flight, so the shot lands on a production path)
    # ------------------------------------------------------------------
    def _consult_chaos(self) -> None:
        tr = self.transition
        if self.chaos is None or tr is None or tr.terminal:
            return
        if tr.kind == "grow":
            burst = self.chaos.pool_spike_mid_grow(self._tick)
            if burst:
                self._inject_spike(burst)
                if tr.state in ("requested", "draining", "reassigned"):
                    # Mesh not rebuilt yet: abort cleanly. Past that
                    # point the grow completes and the spike pressure
                    # drives an immediate shrink through arbitration.
                    self._grow_abort = True
            if tr.state == "draining" and self.chaos.pool_kill_draining_replica(
                self._tick
            ):
                for rid in tr.replicas:
                    rep = self.router.replicas[rid]
                    if rep.state is ReplicaState.DRAINING and rep.load > 0:
                        self.router.kill_replica(
                            rid, reason="chaos pool_kill_draining_replica",
                        )
                        break
        if tr.kind == "shrink" and tr.state in ("requested", "draining"):
            victim = self.chaos.pool_kill_mid_shrink(self._tick)
            if victim is not None:
                self._kill_host(victim, why="pool_kill_mid_shrink")

    def _inject_spike(self, burst: int) -> None:
        rng = np.random.RandomState(7_000 + self._tick)
        prompts = seeded_prompts(
            rng, burst, 8, self.model_cfg.vocab_size,
        )
        mnt = min(8, self.cfg.router.serve.max_new_tokens)
        self.reg.emit("pool_spike", requests=burst, tick=self._tick)
        for p in prompts:
            self._spike_seq += 1
            self.submit(Request(
                rid=f"spike{self._spike_seq}", prompt=p, max_new_tokens=mnt,
            ))

    def _kill_host(self, victim: int, *, why: str) -> None:
        """A host dies: its devices leave the alive set and its snapshot
        RAM (primary AND held mirrors) vanishes — recovery must source
        the ring mirror on a SURVIVOR, never the corpse. A serve-leased
        victim takes its replica down with it (the router fails over its
        in-flight requests) and surrenders the lease for good: a dead
        host must never be leased back to either tenant."""
        self.hosts.kill(victim)
        self.trainer.snapshots.drop_primary(victim)
        rid = self.serve_lease.pop(victim, None)
        if rid is not None:
            self.router.kill_replica(rid, reason=f"chaos {why}")
        tr = self.transition
        if tr is not None and not tr.terminal:
            # Any host that dies while a transition is in flight lands on
            # that transition's bill — the kill need not hit a host being
            # surrendered to count against the surrender's safety story.
            tr.dead_hosts.append(victim)
        self.reg.emit(
            "pool_host_killed", host=victim, why=why, tick=self._tick,
            replica=rid,
        )

    # ------------------------------------------------------------------
    # the typed state machine
    # ------------------------------------------------------------------
    def _advance(self, tr: PoolTransition, state: str, **fields: Any) -> None:
        if state not in _TRANSITION_EDGES[tr.state]:
            raise RuntimeError(
                f"illegal pool transition edge {tr.state} -> {state} "
                f"({tr.kind} {tr.hosts})"
            )
        prev, tr.state = tr.state, state
        self.reg.emit(
            "pool_transition", kind=tr.kind, hosts=list(tr.hosts),
            prev=prev, state=state, tick=self._tick,
            requested_tick=tr.tick, **fields,
        )

    def _request(self, kind: str, hosts: list[int], replicas: list[int]) -> None:
        tr = PoolTransition(
            kind=kind, hosts=list(hosts), tick=self._tick,
            replicas=list(replicas), t_requested=self.reg._clock(),
        )
        self.transition = tr
        self.transitions.append(tr)
        self._grow_abort = False
        self.reg.emit(
            "pool_transition", kind=kind, hosts=list(hosts), prev=None,
            state="requested", tick=self._tick, requested_tick=self._tick,
            replicas=list(replicas),
        )

    def _advance_transition(self) -> None:
        tr = self.transition
        if tr is None or tr.terminal:
            return
        if tr.kind == "grow":
            self._advance_grow(tr)
        else:
            self._advance_shrink(tr)
        if tr.terminal and tr.state == "aborted":
            self.transition = None

    # -- grow: serve -> train ------------------------------------------
    def _advance_grow(self, tr: PoolTransition) -> None:
        if self._grow_abort and tr.state in (
            "requested", "draining", "reassigned"
        ):
            self._abort_grow(tr, reason="load_spike")
            return
        if tr.state == "requested":
            for rid in tr.replicas:
                self.router.begin_retire(rid, reason="pool_grow")
            self._advance(tr, "draining")
        elif tr.state == "draining":
            done = True
            for rid in tr.replicas:
                rep = self.router.replicas[rid]
                if rep.state is ReplicaState.DEAD:
                    continue  # chaos-killed mid-drain: failover ran, host free
                if not self.router.finish_retire(rid, reason="pool_grow"):
                    done = False
            if done:
                self._advance(tr, "reassigned")
        elif tr.state == "reassigned":
            # Hosts leave the serve lease and join the monitor roster —
            # admit() refuses a host the monitor believes dead, which
            # aborts the grow instead of resurrecting a corpse.
            try:
                for h in tr.hosts:
                    if h not in self.hosts.alive:
                        raise ElasticAbort(
                            f"grow target host {h} is dead"
                        )
                    self.trainer.monitor.admit(h, step=self.trainer.cur_step)
            except ElasticAbort as e:
                self._abort_grow(tr, reason=str(e))
                return
            for h in tr.hosts:
                self.serve_lease.pop(h, None)
            self.train_lease |= set(tr.hosts)
            self._acquired.extend(tr.hosts)
            info = self.trainer.resize(
                set(self.train_lease), reason="pool_grow",
            )
            tr.to_step = info["to_step"]
            tr.used_mirror = info["used_mirror"]
            tr.t_detect, tr.t_restored = info["t_detect"], info["t_restored"]
            self._advance(
                tr, "resized", to_step=tr.to_step,
                used_mirror=tr.used_mirror,
                devices=len(self.train_lease) * self.hosts.per_host,
            )

    def _abort_grow(self, tr: PoolTransition, *, reason: str) -> None:
        """Roll a not-yet-resized grow back: draining replicas resume
        accepting, fully-retired ones are respawned (zero compiles via
        the fn cache), any monitor admissions are retired again. The
        trainer's mesh was never touched; parked requests drain on the
        restored capacity."""
        for h, rid in zip(tr.hosts, tr.replicas):
            self.trainer.monitor.retire(h)
            rep = self.router.replicas[rid]
            if rep.state is ReplicaState.DRAINING:
                self.router.cancel_retire(rid, reason="pool_grow_abort")
                self.serve_lease[h] = rid
            elif rep.state is ReplicaState.DEAD and h in self.hosts.alive:
                new = self.router.spawn_replica()
                self.serve_lease[h] = new.replica_id
            self.train_lease.discard(h)
            if h in self._acquired:
                self._acquired.remove(h)
        tr.abort_reason = reason
        self._grow_abort = False
        self.reg.emit(
            "pool_grow_abort", hosts=list(tr.hosts), reason=reason,
            tick=self._tick,
        )
        self._advance(tr, "aborted", reason=reason)

    # -- shrink: train -> serve ----------------------------------------
    def _advance_shrink(self, tr: PoolTransition) -> None:
        if tr.state == "requested":
            # The surrender is safe BEFORE it starts: every queued
            # snapshot commit lands now, so a complete snapshot covers
            # the current step (ring-mirrored — a victim dying mid-
            # surrender cannot take the only copy with it).
            self.trainer.snapshots.drain()
            self._advance(tr, "draining")
        elif tr.state == "draining":
            for h in tr.hosts:
                # Deliberate surrender, not death: the host leaves the
                # roster cleanly and a later admit() of it is legal.
                self.trainer.monitor.retire(h)
                self.train_lease.discard(h)
                if h in self._acquired:
                    self._acquired.remove(h)
            self._advance(tr, "reassigned")
        elif tr.state == "reassigned":
            info = self.trainer.resize(
                set(self.train_lease), reason="pool_shrink",
            )
            tr.to_step = info["to_step"]
            tr.used_mirror = info["used_mirror"]
            tr.t_detect, tr.t_restored = info["t_detect"], info["t_restored"]
            spawned = []
            for h in tr.hosts:
                if h not in self.hosts.alive:
                    continue  # died mid-surrender: nothing to serve on
                rep = self.router.spawn_replica()
                self.serve_lease[h] = rep.replica_id
                spawned.append(rep.replica_id)
            tr.replicas = spawned
            self._advance(
                tr, "resized", to_step=tr.to_step,
                used_mirror=tr.used_mirror, spawned=spawned,
                dead_hosts=list(tr.dead_hosts),
                devices=len(self.train_lease) * self.hosts.per_host,
            )

    # ------------------------------------------------------------------
    # arbitration
    # ------------------------------------------------------------------
    def _arbitrate(self) -> None:
        accepting = [r for r in self.router.replicas if r.accepting]
        backlog = len(self._parked) + sum(r.load for r in accepting)
        spike = (
            (not accepting and bool(self._parked))
            or (bool(accepting)
                and backlog / len(accepting) >= self.cfg.spike_queue_depth)
        )
        if spike:
            self._idle_ticks = 0
            victims = self._shrink_victims()
            if victims:
                self._request("shrink", victims, [])
            return
        if backlog == 0 and not self.router.records:
            self._idle_ticks += 1
        else:
            self._idle_ticks = 0
        if (
            self._idle_ticks >= self.cfg.grow_after_idle_ticks
            and not self.trainer.finished
        ):
            hosts, reps = self._grow_candidates()
            if hosts:
                self._idle_ticks = 0
                self._request("grow", hosts, reps)

    def _shrink_victims(self) -> list[int]:
        """LIFO: grow-acquired hosts surrender first (back to the
        configured baseline); under sustained pressure the baseline
        itself shrinks one host at a time down to min_train_hosts."""
        if not self.trainer.finished and len(self.train_lease) <= \
                self.cfg.min_train_hosts:
            return []
        if self._acquired:
            return list(reversed(self._acquired))
        if len(self.train_lease) > self.cfg.min_train_hosts:
            return [max(self.train_lease)]
        return []

    def _grow_candidates(self) -> tuple[list[int], list[int]]:
        """Serve hosts whose replicas are idle, above the serve floor —
        the LARGEST prefix that still yields a valid mesh and batch
        split (a dead host can leave the full idle set indivisible;
        growing by fewer hosts beats not growing at all)."""
        idle = [
            (h, rid) for h, rid in sorted(self.serve_lease.items())
            if h in self.hosts.alive
            and self.router.replicas[rid].accepting
            and self.router.replicas[rid].load == 0
        ]
        n_take = len(self.serve_lease) - self.cfg.min_serve_hosts
        for k in range(min(len(idle), max(n_take, 0)), 0, -1):
            take = idle[:k]
            new_lease = self.train_lease | {h for h, _ in take}
            n_dev = len(new_lease) * self.hosts.per_host
            if n_dev % self.cfg.model_axis == 0 and \
                    self.cfg.global_batch % (n_dev // self.cfg.model_axis) == 0:
                return [h for h, _ in take], [rid for _, rid in take]
        return [], []

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------
    def _drain_bus(self) -> None:
        for etype, fields in self.bus.drain():
            fields.setdefault("tick", self._tick)
            self.reg.emit(etype, **fields)

    def summary(self) -> dict[str, Any]:
        fleet = self.router.fleet_summary()
        return {
            "ticks": self._tick,
            "train_steps": self.trainer.cur_step,
            "train_hosts": sorted(self.train_lease),
            "serve_hosts": sorted(self.serve_lease),
            "recompiles": self.trainer.recompiles,
            "transitions": [
                {
                    "kind": t.kind, "hosts": t.hosts, "state": t.state,
                    "to_step": t.to_step, "used_mirror": t.used_mirror,
                    "dead_hosts": t.dead_hosts,
                    "abort_reason": t.abort_reason,
                }
                for t in self.transitions
            ],
            "parked_unserved": len(self._parked),
            "fleet": fleet,
        }

    def close(self, *, drain: bool = True) -> dict[str, ServeResult]:
        """Drain the fleet, reconcile every still-parked request to a
        typed FAILED terminal (the zero-silent-drop backstop), release
        tenants, and return the full terminal map."""
        if drain and self.router.live_replicas:
            self.router.drain()
        for req in self._parked:
            res = ServeResult(
                rid=req.rid, state=RequestState.FAILED, tokens=[],
                error=RequestFailedError(
                    f"request {req.rid}: pool closed before any replica "
                    "could admit it"
                ),
                finished_t=self.router.clock(),
            )
            self._parked_results[req.rid] = res
            self.router.reg.emit(
                "serve_request", iteration=self._tick, **res.summary(),
            )
        self._parked.clear()
        self.reg.emit("pool_closed", tick=self._tick)
        self.reg.flush()
        self.reg.close()
        self.router.close()
        self.trainer.close()
        return self.results()
