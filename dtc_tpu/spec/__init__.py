"""Speculative decoding (ISSUE 19): draft-propose, megakernel k-verify,
exactness-gated acceptance.

- :mod:`dtc_tpu.spec.draft` — truncated-layer draft extraction: a
  shallow rung of the SAME GPT family initialized from the target
  checkpoint's bottom layers (the stacked ``(L, ...)`` block params make
  this a zero-copy slice).
- :mod:`dtc_tpu.spec.core` — the propose/verify/accept round:
  ``spec_generate`` (the generate()-shaped driver), greedy
  token-identity acceptance (emitted tokens == plain decode by
  construction), and Leviathan-style rejection sampling for
  ``temperature > 0`` (target-distribution exact).

The serving integration (resident draft cache, per-slot rounds, goodput
/ SLO honesty) lives in :mod:`dtc_tpu.serve.engine` behind
``ServeConfig.spec``.
"""

from dtc_tpu.spec.draft import draft_config, extract_draft  # noqa: F401
from dtc_tpu.spec.core import (  # noqa: F401
    check_spec_backend,
    serve_round,
    spec_generate,
)
