"""Draft-model extraction: a truncated-layer rung of the target GPT.

Speculative decoding needs a proposer that is (a) much cheaper than the
target and (b) distributionally close to it. The cheapest checkpoint-
free answer is the target's own bottom layers: the ``nn.scan`` stacked
block params already carry a leading ``(L,)`` layer axis, so a
``draft_layers``-deep rung is literally ``leaf[:draft_layers]`` per
block leaf — no re-init, no training, no weight copy beyond the slice
(embed/head/ln_f subtrees are SHARED by reference with the target; jax
arrays are immutable, so residency costs only the sliced blocks).

This is the "early-exit as draft" construction (cf. self-speculative /
layer-skip decoding): the rung reuses the target's lm_head over its
layer-``draft_layers`` residual stream. Its proposals are imperfect —
that is what verification is for — but on a trained checkpoint the
bottom layers carry most next-token signal, and EXACTNESS never depends
on draft quality: acceptance gates every emitted token against the
target (spec/core.py), so a bad draft costs acceptance rate, not
correctness.

HBM math (why the draft rides along for ~free): draft KV pages cost
``draft_layers / n_layers`` of the target's — on the flagship at int8
KV a 3-of-12-layer draft adds 25% KV bytes, repaid when the mean
accepted window exceeds 1.25 tokens per verify launch. The serving
engine bills this honestly: draft pages ride the SAME paged-pool
accounting as target pages (engine's spec page surcharge), never a
hidden side allocation.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax

PyTree = Any


def draft_config(cfg, draft_layers: int):
    """The draft rung's ModelConfig: ``cfg`` with ``n_layers`` truncated
    (and adapters off — speculation is an adapter-free mode; the engine
    enforces the same restriction). Everything else — widths, vocab,
    ``max_seq_len``, decode backend, KV dtype — is inherited, so the
    draft's cache rides the same kernels and the same pool arithmetic."""
    if not 1 <= draft_layers < cfg.n_layers:
        raise ValueError(
            f"draft_layers must be in [1, {cfg.n_layers - 1}] "
            f"(a strict truncation of the {cfg.n_layers}-layer target), "
            f"got {draft_layers}"
        )
    return dataclasses.replace(
        cfg,
        n_layers=draft_layers,
        adapter=dataclasses.replace(cfg.adapter, rank=0),
    )


def extract_draft(model, params: PyTree, draft_layers: int):
    """Build ``(draft_model, draft_params)`` from the target checkpoint.

    ``draft_params`` has the target's exact tree structure with the
    stacked ``(L, ...)`` block leaves sliced to ``[:draft_layers]``;
    the embed and head subtrees are the target's own (shared, not
    copied). The returned model is a plain :class:`~dtc_tpu.models.gpt.
    GPT` — every decode path (init_cache, decode_step, the fused
    megakernel, the engine's slot caches) serves it unchanged."""
    cfg = model.cfg
    if cfg.moe_experts > 0:
        raise ValueError(
            "speculative draft extraction does not support MoE targets "
            "(expert-stacked params have no bottom-layers truncation)"
        )
    from dtc_tpu.models.gpt import GPT

    dcfg = draft_config(cfg, draft_layers)
    dparams = dict(params)
    stage = dict(params["stage"])
    stage["blocks"] = jax.tree.map(
        lambda leaf: leaf[:draft_layers], params["stage"]["blocks"]
    )
    dparams["stage"] = stage
    return GPT(dcfg), dparams
