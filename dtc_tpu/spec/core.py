"""The speculative round: draft-propose, k-verify in one launch, accept.

One ROUND emits between 1 and ``spec_k`` tokens of the target model:

1. **Propose** — the draft rung (spec/draft.py) runs ``spec_k`` plain
   single-token decode steps under ``lax.scan`` starting from the last
   emitted token, yielding ``spec_k - 1`` proposals. (It takes one step
   more than it strictly needs so its cache frontier lands at
   ``start + spec_k`` — the same place the target's verify leaves ITS
   frontier — making rollback a uniform index decrement on both.)
2. **Verify** — ONE :func:`~dtc_tpu.generate.decode_step` call with the
   ``(B, spec_k)`` window ``[t_last, d_1 .. d_{k-1}]`` and
   ``spec_verify=True``: under ``decode_attention: fused_layers`` the
   megakernel takes all k query positions in a single launch (causal
   among the k in-register); the xla/fused fallback ladder computes the
   identical logits (the parity oracle).
3. **Accept** — greedy: proposal ``d_{j+1}`` is accepted iff it equals
   the target's argmax at position j AND every earlier proposal was
   accepted; the emitted tokens are the TARGET's argmax row, so the
   output is token-identical to plain greedy decode *by construction*
   (the draft can only change how many tokens each launch yields).
   Sampled (``temperature > 0``): Leviathan et al.'s rejection rule —
   accept ``d`` with probability ``min(1, q(d)/p(d))``, resample the
   first rejection from ``normalize(max(q - p, 0))``, bonus-sample from
   ``q`` when everything is accepted — which makes every emitted token
   an EXACT sample from the target distribution, independent of draft
   quality.
4. **Rollback** — the verify wrote all ``spec_k`` positions and moved
   the frontier to ``start + spec_k``; the round rebinds the cache
   index to ``start + n_emit``. Positions past a frontier are invisible
   (every decode read masks ``col < frontier``) and are rewritten by
   whichever later step advances over them, so rejection costs ONE
   integer per cache — no cache surgery, nothing for eviction/failover
   to observe mid-flight (serve-side rounds are atomic in-jit).

``spec_generate`` drives rounds to ``max_new_tokens`` with per-row
frontiers — rows accept independently, so the batch decouples exactly
like the serving engine's slots.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from dtc_tpu.generate import decode_step, init_cache

PyTree = Any


def _reindex(cache: PyTree, new_index) -> PyTree:
    """Rebind the GPT-level frontier — THE rollback primitive."""
    return {"index": new_index, "stage": cache["stage"]}


def check_spec_backend(cfg) -> None:
    """Exactness gate: greedy acceptance is token-identical to plain
    decode only when the single-token path and the k-verify path share
    ONE numeric implementation — ``fused_layers`` (the megakernel serves
    both) or ``xla`` (the oracle serves both). ``fused`` runs the
    per-layer Pallas kernel for single tokens but the xla oracle for the
    multi-token verify window: two different accumulation orders, whose
    bf16-compute logits disagree by enough to flip near-tie argmaxes —
    the identity guarantee would silently become "usually identical".
    Raised typed at spec_generate() / ServingEngine construction, never
    discovered as a token mismatch mid-flight."""
    if getattr(cfg, "decode_attention", None) == "fused":
        raise ValueError(
            "speculative decoding requires decode_attention='fused_layers' "
            "or 'xla' (one numeric path for both plain decode and the "
            "k-verify window); 'fused' pairs the per-layer kernel with the "
            "xla verify oracle and greedy acceptance loses its "
            "token-identity guarantee"
        )


def _propose_greedy(draft_model, draft_params, dcache, tok, spec_k):
    """``spec_k`` draft steps from ``tok`` (B, 1); returns the advanced
    draft cache (frontier +spec_k) and (B, spec_k - 1) proposals."""
    def body(carry, _):
        dc, t = carry
        dc, logits = decode_step(draft_model, draft_params, dc, t)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (dc, nxt[:, None]), nxt

    (dcache, _), drafts = jax.lax.scan(
        body, (dcache, tok), None, length=spec_k
    )
    return dcache, drafts[: spec_k - 1].T  # (B, k-1); last step cache-only


def _propose_sampled(
    draft_model, draft_params, dcache, tok, spec_k, temperature, rng
):
    """Sampled propose: like :func:`_propose_greedy` but each proposal is
    drawn from the draft distribution at ``temperature``, and the full
    per-step draft probabilities ride out for the rejection test."""
    def body(carry, _):
        dc, t, key = carry
        dc, logits = decode_step(draft_model, draft_params, dc, t)
        lg = logits[:, -1].astype(jnp.float32) / temperature
        probs = jax.nn.softmax(lg, axis=-1)
        key, sub = jax.random.split(key)
        nxt = jax.random.categorical(sub, lg, axis=-1).astype(jnp.int32)
        return (dc, nxt[:, None], key), (nxt, probs)

    (dcache, _, _), (drafts, probs) = jax.lax.scan(
        body, (dcache, tok, rng), None, length=spec_k
    )
    # drafts (k, B), probs (k, B, V); the k-th step only advances the cache.
    return (
        dcache,
        drafts[: spec_k - 1].T,                     # (B, k-1)
        probs[: spec_k - 1].transpose(1, 0, 2),     # (B, k-1, V)
    )


def _accept_sampled(proposals, p_probs, q_probs, rng):
    """Leviathan-style rejection: returns ``(n_acc, t_extra)`` — the
    accepted-proposal count per row and the resampled/bonus token that
    always follows the accepted prefix. Pure (seeded) — unit-tested
    against the analytic target distribution in tests/test_spec.py."""
    b, km1 = proposals.shape
    rows = jnp.arange(b)
    q_d = jnp.take_along_axis(
        q_probs[:, :km1], proposals[..., None], axis=2
    )[..., 0]                                        # (B, k-1) q(d_j)
    p_d = jnp.take_along_axis(p_probs, proposals[..., None], axis=2)[..., 0]
    key_u, key_r = jax.random.split(rng)
    u = jax.random.uniform(key_u, (b, km1))
    # u < q/p without the division (p_d == 0 can only pair with a
    # proposal of probability zero — accept iff q_d > 0, which the
    # product form gets right).
    acc = u * p_d < q_d
    n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
    # Residual distribution at the first rejection; bonus from q when
    # every proposal was accepted (n_acc == k-1).
    q_row = q_probs[rows, n_acc]                     # (B, V)
    p_row = jnp.where(
        (n_acc < km1)[:, None],
        p_probs[rows, jnp.minimum(n_acc, km1 - 1)],
        0.0,
    )
    resid = jnp.maximum(q_row - p_row, 0.0)
    norm = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(norm > 0, resid / norm, q_row)
    t_extra = jax.random.categorical(
        key_r, jnp.log(resid), axis=-1
    ).astype(jnp.int32)
    return n_acc, t_extra


def spec_round(
    model, draft_model, spec_k, params, draft_params,
    tcache, dcache, tok, remaining, *, temperature=0.0, rng=None,
):
    """ONE propose/verify/accept/rollback round over a (B,)-frontier
    batch. Pure and jit-safe (the serving engine jits it directly; jit
    with ``static_argnums=(0, 1, 2)``).

    ``tok`` (B, 1) is the last emitted token per row; ``remaining`` (B,)
    caps emission (0 freezes a row: its frontier does not move and its
    lanes compute masked garbage — the engine's idle slots, generate's
    finished rows). Returns ``(tcache, dcache, tok_next, emit, n_emit,
    fin)``: ``emit`` (B, spec_k) holds each row's emitted tokens in its
    first ``n_emit`` columns, ``fin`` flags rows whose verify logits
    were all finite (the engine's poison-localization hook)."""
    start_t, start_d = tcache["index"], dcache["index"]
    b = tok.shape[0]
    rows = jnp.arange(b)
    greedy = temperature == 0.0

    if greedy:
        dcache, proposals = _propose_greedy(
            draft_model, draft_params, dcache, tok, spec_k
        )
    else:
        rng, sub = jax.random.split(rng)
        dcache, proposals, p_probs = _propose_sampled(
            draft_model, draft_params, dcache, tok, spec_k, temperature, sub
        )

    verify_toks = jnp.concatenate([tok, proposals], axis=1)   # (B, k)
    tcache, logits = decode_step(
        model, params, tcache, verify_toks, spec_verify=True
    )
    fin = jnp.all(jnp.isfinite(logits.astype(jnp.float32)), axis=(1, 2))

    if greedy:
        target = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, k)
        match = verify_toks[:, 1:] == target[:, :-1]
        n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        emit = target  # accepted prefix == the target's own argmax row
    else:
        q_probs = jax.nn.softmax(
            logits.astype(jnp.float32) / temperature, axis=-1
        )
        n_acc, t_extra = _accept_sampled(proposals, p_probs, q_probs, rng)
        pos = jnp.arange(spec_k)[None]
        prop_pad = jnp.pad(proposals, ((0, 0), (0, 1)))
        emit = jnp.where(
            pos < n_acc[:, None],
            prop_pad,
            jnp.where(pos == n_acc[:, None], t_extra[:, None], 0),
        )

    n_emit = jnp.where(
        remaining > 0, jnp.clip(n_acc + 1, 1, remaining), 0
    ).astype(jnp.int32)
    tok_next = jnp.where(
        n_emit > 0, emit[rows, jnp.maximum(n_emit, 1) - 1], tok[:, 0]
    )[:, None]
    tcache = _reindex(tcache, start_t + n_emit)
    dcache = _reindex(dcache, start_d + n_emit)
    return tcache, dcache, tok_next, emit, n_emit, fin


#: Jitted round for the serving engine — ONE module-level wrapper so
#: every in-process replica serving the same (model, draft, spec_k)
#: shares the compiled executable (flax modules hash by structure; same
#: sharing story as ServingEngine._FN_CACHE). The engine calls it
#: greedy-only (ServeConfig validation pins acceptance="greedy").
serve_round = jax.jit(spec_round, static_argnums=(0, 1, 2))


@functools.partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("temperature",)
)
def _round_step(
    model, draft_model, spec_k, max_new, params, draft_params,
    tcache, dcache, tok, out, n_done, rng, *, temperature,
):
    """One jitted spec_generate iteration: round + ragged scatter of the
    emitted tokens into the (B, max_new) output buffer."""
    b = tok.shape[0]
    rows = jnp.arange(b)
    remaining = jnp.maximum(max_new - n_done, 0)
    if temperature > 0.0:
        rng, sub = jax.random.split(rng)
    else:
        sub = rng
    tcache, dcache, tok, emit, n_emit, _ = spec_round(
        model, draft_model, spec_k, params, draft_params,
        tcache, dcache, tok, remaining, temperature=temperature, rng=sub,
    )
    cols = n_done[:, None] + jnp.arange(spec_k)[None]
    valid = jnp.arange(spec_k)[None] < n_emit[:, None]
    cols = jnp.where(valid, cols, max_new)          # OOB -> dropped
    out = out.at[rows[:, None], cols].set(emit, mode="drop")
    n_done = n_done + n_emit
    n_acc = jnp.sum(jnp.maximum(n_emit - 1, 0))
    return tcache, dcache, tok, out, n_done, rng, n_acc


def spec_generate(
    model,
    params: PyTree,
    draft_model,
    draft_params: PyTree,
    prompt: jax.Array,
    max_new_tokens: int,
    rng: jax.Array | None = None,
    *,
    spec_k: int,
    temperature: float = 0.0,
    return_stats: bool = False,
) -> jax.Array:
    """Speculative :func:`~dtc_tpu.generate.generate`: same contract —
    ``(B, max_new_tokens)`` int32 continuations — served by draft-
    propose/k-verify rounds. ``temperature == 0`` is token-identical to
    plain greedy ``generate`` (asserted in tests/test_spec.py and
    scripts/spec_smoke.py); ``temperature > 0`` is distribution-exact
    via rejection sampling (``rng`` required). Top-k/top-p filters are
    not supported with speculation (the rejection identity needs the
    unfiltered target distribution).

    ``return_stats`` also returns ``{"proposed": int, "accepted": int,
    "rounds": int}`` — the acceptance telemetry every bench row and
    smoke gate reads (``accept_rate = accepted / proposed``)."""
    from dtc_tpu.ops.decode_fused import _SPEC_MAX_K

    b, t_prompt = prompt.shape
    cfg = model.cfg
    check_spec_backend(cfg)
    if not 2 <= spec_k <= _SPEC_MAX_K:
        raise ValueError(f"spec_k must be in [2, {_SPEC_MAX_K}], got {spec_k}")
    # The verify window physically writes spec_k positions from the
    # frontier before rolling back, so the LAST round (one token left,
    # frontier at t_prompt + max_new - 1) still needs spec_k slots.
    if t_prompt + max_new_tokens + spec_k - 1 > cfg.max_seq_len:
        raise ValueError(
            f"prompt ({t_prompt}) + max_new_tokens ({max_new_tokens}) + "
            f"spec_k-1 ({spec_k - 1}) verify headroom exceeds max_seq_len "
            f"({cfg.max_seq_len}) — the KV cache cannot grow past it"
        )
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused by greedy

    tcache = init_cache(model, b)
    dcache = init_cache(draft_model, b)
    with jax.named_scope("prefill"):
        tcache, logits = decode_step(model, params, tcache, prompt)
        dcache, _ = decode_step(draft_model, draft_params, dcache, prompt)
    rng, sub = jax.random.split(rng)
    if temperature == 0.0:
        first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    else:
        first = jax.random.categorical(
            sub, logits[:, -1].astype(jnp.float32) / temperature, axis=-1
        ).astype(jnp.int32)

    # Per-row frontiers from here on: rows accept independently.
    vec = jnp.full((b,), t_prompt, jnp.int32)
    tcache, dcache = _reindex(tcache, vec), _reindex(dcache, vec)
    out = jnp.zeros((b, max_new_tokens), jnp.int32)
    out = out.at[:, 0].set(first)
    n_done = jnp.ones((b,), jnp.int32)
    tok = first[:, None]

    proposed = accepted = rounds = 0
    while bool(jnp.any(n_done < max_new_tokens)):
        tcache, dcache, tok, out, n_done, rng, n_acc = _round_step(
            model, draft_model, spec_k, max_new_tokens, params, draft_params,
            tcache, dcache, tok, out, n_done, rng, temperature=temperature,
        )
        rounds += 1
        proposed += (spec_k - 1) * b
        accepted += int(n_acc)
    if return_stats:
        return out, {
            "proposed": proposed, "accepted": accepted, "rounds": rounds,
        }
    return out
