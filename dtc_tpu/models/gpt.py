"""GPT model — flax linen, strategy-agnostic, pipeline-splittable.

Capability parity with the reference model family
(`/root/reference/model/GPTModel.py`, `TransformerBlock.py`,
`CausalSelfAttention.py`, `MLP.py`): decoder-only pre-LN GPT-2-style
transformer with learned absolute position embeddings, separate q/k/v
projections, GELU MLP, dropout, and a pipeline-splittable embed/stage/head
decomposition with scan-over-layers parameter stacking — the structure both
TP sharding rules and PP stage-chunking key on
(`/root/reference/model/GPTModel.py:25-82`).

TPU-native differences:

- The split is *module-level* (GPTEmbed / GPTStage / GPTHead composed by
  GPT), not method-level: pipeline stages apply the sub-modules standalone
  with their own param subtrees — no ``method=`` plumbing — and the full
  param tree is already {"embed", "stage", "head"}, so the PP layout is a
  leaf reshape, not a re-init (the reference re-inits per stage with
  different keys, `/root/reference/train/train.py:143-161`).
- No ``parallel: str`` branches in model code. Activations carry *logical*
  axis names via ``nn.with_logical_constraint``; the active rule table +
  mesh shape decide physical sharding (cf. reference's per-strategy branches
  at `/root/reference/model/CausalSelfAttention.py:28-31,49-50`).
- Mixed precision: the storage/compute pair flows from config
  (``param_dtype``/``compute_dtype``; the default flagship pairing is fp32
  params + bf16 MXU-native matmuls, and ``OptimConfig.precision:
  bf16_mixed`` lifts BOTH to bf16 with fp32 master weights held by the
  optimizer — train/train_step.resolve_precision, ISSUE 14). The
  fp32-MANDATED islands are hard-coded by design and stay fp32 under every
  policy: LayerNorm (``ln``/``GPTHead``), MoE routing softmax
  (``MoEMLP``), and the CE loss (ops/fused_ce.py). Those scope names are a
  CONTRACT with the graph auditor: analysis/dtypelint.py allowlists
  exactly them (renaming one fails tests/test_numerics.py), and
  analysis/numerics.py asserts the islands' exp/rsqrt lower fp32 in every
  audited program.
- Attention is a pluggable op (dense / Pallas flash / ring); causality lives
  inside the op — no (1,1,T,T) mask tensor threaded through the model
  (cf. `/root/reference/model/GPTModel.py:50-51`).
- Optional per-block rematerialisation (``remat``) to trade FLOPs for HBM.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from dtc_tpu.adapters.lora import apply_lora
from dtc_tpu.config.schema import ModelConfig
from dtc_tpu.ops.attention import causal_attention


def _dtype(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}[name]


class OverlapDense(nn.Module):
    """``nn.Dense`` twin whose matmul rides the overlapped-collectives
    ring (ops/overlap_collectives.py, ISSUE 12).

    Same parameter tree, names, shapes, and init as ``nn.Dense`` — so the
    sharding rule table, checkpoints, and LoRA injection see an identical
    layer — but the product is computed by the fused
    all-gather-then-matmul whenever the active rules shard "embed_p"
    (FSDP): each ring step matmuls the parameter shard already on-chip
    while the next shard streams in, and the backward pass streams the
    weight-gradient reduce-scatter through the ring the same way.
    ``shard_axis`` names which KERNEL axis carries "embed_p" under
    FSDP_RULES: 0 for the contraction axis (q/k/v/fc1 — d_model in), 1
    for the output axis (out_proj/fc2 — d_model out); ``tp_logical`` is
    the logical axis of the OTHER kernel dimension ("qkv" / "mlp"), so on
    a DP×FSDP×TP mesh the op goes manual over the Megatron axis too and
    makes its row-parallel psums explicit. Every inapplicable call (no
    FSDP axis in scope, eager init, decode's narrow batches,
    non-divisible tails) falls back to the identical plain dot inside the
    op, so selecting ``collectives: overlapped`` is safe on any config.
    """

    features: int
    shard_axis: int
    tp_logical: str = "qkv"
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from flax.linen import dtypes

        from dtc_tpu.ops.overlap_collectives import overlap_dense_matmul
        from dtc_tpu.parallel.sharding import fsdp_axis_in_scope

        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features), self.param_dtype,
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,),
            self.param_dtype,
        )
        x, kernel, bias = dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype
        )
        tp_axis = dict(nn.get_logical_axis_rules()).get(self.tp_logical)
        y = overlap_dense_matmul(
            x, kernel, shard_axis=self.shard_axis,
            axis_name=fsdp_axis_in_scope(),
            tp_axis=tp_axis if isinstance(tp_axis, str) else None,
        )
        return y + bias


def _dense(cfg: ModelConfig, features: int, name: str, shard_axis: int,
           cdtype, pdtype, tp_logical: str = "qkv") -> nn.Module:
    """The dense-layer factory every matmul site shares: ``nn.Dense`` for
    ``collectives: xla`` (byte-identical to every pre-ISSUE-12 program),
    :class:`OverlapDense` for ``overlapped``."""
    if cfg.collectives == "overlapped":
        return OverlapDense(
            features, shard_axis=shard_axis, tp_logical=tp_logical,
            name=name, dtype=cdtype, param_dtype=pdtype,
        )
    return nn.Dense(features, name=name, dtype=cdtype, param_dtype=pdtype)


class CausalSelfAttention(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        train: bool,
        decode: bool = False,
        decode_index: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.cfg
        b, t, _ = x.shape
        cdtype = _dtype(cfg.compute_dtype)
        pdtype = _dtype(cfg.param_dtype)

        def dense(name, shard_axis=0):
            # LoRA injection point (dtc_tpu/adapters/): with an active
            # adapter config and a targeted name, the base Dense output
            # gains a low-rank delta from the SEPARATE "lora" collection;
            # at rank 0 apply_lora is an identity passthrough that creates
            # no variables — the rank-0 graph is bitwise the base graph.
            # ``shard_axis`` is the kernel axis FSDP shards (0 = the
            # d_model contraction for q/k/v, 1 = the d_model output for
            # out_proj) — consumed only by the overlapped-collectives
            # flavor (_dense, ISSUE 12).
            layer = _dense(cfg, cfg.d_model, name, shard_axis, cdtype, pdtype)
            return lambda h: apply_lora(
                self, layer, h, cfg=cfg, name=name, train=train
            )

        # named_scope component annotation (ISSUE 8): trace-time-only HLO
        # op_name provenance so XLA fusions roll up to model components in
        # the device-time attribution (obs/devprof.py). attn_qkv /
        # attn_kernel / attn_proj split the attention block into its
        # projection, kernel, and output legs — the same cut PERF.md's
        # hand-read rounds used.
        with jax.named_scope("attn_qkv"):
            q = dense("q_proj")(x).reshape(b, t, cfg.n_heads, cfg.head_dim)
            k = dense("k_proj")(x).reshape(b, t, cfg.n_heads, cfg.head_dim)
            v = dense("v_proj")(x).reshape(b, t, cfg.n_heads, cfg.head_dim)

        if decode:
            # Autoregressive KV-cache path (inference; single device or
            # GSPMD — no flash/ring). The cache holds max_seq_len k/v per
            # layer in the PACKED model-native (B, S, H·D) layout — the
            # raw byte order of the k/v projections, so the write below is
            # a lane-aligned in-place dynamic_update_slice with no
            # relayout, and the fused decode kernel reads it directly.
            # ``decode_index`` is the write frontier, owned by GPT (one
            # scalar per model, not one per layer — the scan body carries
            # it, it never updates inside the loop). CALLER CONTRACT:
            # total decoded length must stay <= max_seq_len — past it,
            # dynamic_update_slice CLAMPS the write start and logits go
            # silently wrong (the index is traced, so this cannot raise
            # here; GPT.__call__ emits the checkify guard under
            # cfg.debug_checks and dtc_tpu.generate.generate enforces the
            # bound at its static API surface).
            from dtc_tpu.ops.attention import decode_attention
            from dtc_tpu.ops import decode_attention as fused

            if decode_index is None:
                # ValueError, not assert: must fire under `python -O` too
                # (same rationale as parallel/pipeline.py's stage check).
                raise ValueError(
                    "decode=True requires the GPT-owned decode_index (apply "
                    "the full GPT model, not a bare stage, for decode)"
                )
            idx = decode_index
            hd = cfg.n_heads * cfg.head_dim
            quant = cfg.kv_quantized
            kv_dt = jnp.int8 if quant else _dtype(cfg.kv_store_dtype)
            ck = self.variable(
                "cache", "k", jnp.zeros, (b, cfg.max_seq_len, hd), kv_dt,
            )
            cv = self.variable(
                "cache", "v", jnp.zeros, (b, cfg.max_seq_len, hd), kv_dt,
            )
            if quant:
                # Per-(position, head) fp32 scales next to the int8
                # payload (ops/decode_attention.quantize_kv) — ~1/(2·D)
                # of the bf16 payload's bytes, accounted as metadata
                # overhead (utils/metrics.decode_step_bytes counts it in
                # the roofline; the paged-pool budget does not).
                cks = self.variable(
                    "cache", "k_scale", jnp.zeros,
                    (b, cfg.max_seq_len, cfg.n_heads), jnp.float32,
                )
                cvs = self.variable(
                    "cache", "v_scale", jnp.zeros,
                    (b, cfg.max_seq_len, cfg.n_heads), jnp.float32,
                )

            # Logical constraints shard the cache over heads under a TP
            # mesh (the packed lane axis IS the head axis × head_dim, so
            # sharding it over "model" is head sharding — and the scale
            # cache's last axis IS the head axis; seq stays unsharded and
            # the dynamic update partitions trivially); decode then runs
            # head-parallel up to out_proj's all-reduce, same as training.
            def cache_write(var, update):
                if idx.ndim == 1:
                    # Per-slot frontiers (the serving runtime's continuous
                    # batching: the cache index is (B,), one write
                    # position per slot). The batched dynamic_update_slice
                    # lowers to a scatter — each row writes at its own
                    # frontier.
                    new = jax.vmap(
                        lambda c, u, i: jax.lax.dynamic_update_slice(
                            c, u, (i, 0)
                        )
                    )(var.value, update, idx)
                else:
                    new = jax.lax.dynamic_update_slice(
                        var.value, update, (0, idx, 0)
                    )
                var.value = nn.with_logical_constraint(
                    new, ("batch", "seq", "heads")
                )

            if quant:
                kq, ksc = fused.quantize_kv(k.reshape(b, t, hd), cfg.n_heads)
                vq, vsc = fused.quantize_kv(v.reshape(b, t, hd), cfg.n_heads)
                cache_write(ck, kq)
                cache_write(cv, vq)
                cache_write(cks, ksc)
                cache_write(cvs, vsc)
            else:
                cache_write(ck, k.reshape(b, t, hd).astype(kv_dt))
                cache_write(cv, v.reshape(b, t, hd).astype(kv_dt))
            if (
                cfg.decode_attention in ("fused", "fused_layers")
                and t == 1
                and fused.supports(cfg.max_seq_len)
            ):
                # The serving fast path: one Pallas launch reads the whole
                # packed cache, masked to the frontier (int8 caches ride
                # their scales in; dequant is in-register). Multi-token
                # calls (prefill — once per sequence) and unsupported
                # cache lengths take the XLA oracle below. fused_layers
                # reaching HERE means a call the megakernel declined
                # (prefill, or an unsupported shape) — the per-layer
                # kernel is its fallback before the oracle.
                with jax.named_scope("attn_kernel"):
                    out = fused.fused_decode_attention(
                        q.reshape(b, 1, hd), ck.value, cv.value, idx,
                        h=cfg.n_heads, d=cfg.head_dim,
                        k_scale=cks.value if quant else None,
                        v_scale=cvs.value if quant else None,
                    ).reshape(b, 1, cfg.n_heads, cfg.head_dim)
            else:
                with jax.named_scope("attn_kernel"):
                    if quant:
                        k_full = fused.dequantize_kv(
                            ck.value, cks.value, cfg.n_heads, cdtype
                        )
                        v_full = fused.dequantize_kv(
                            cv.value, cvs.value, cfg.n_heads, cdtype
                        )
                    else:
                        k_full, v_full = ck.value, cv.value
                    out = decode_attention(
                        q,
                        k_full.reshape(b, cfg.max_seq_len, cfg.n_heads, cfg.head_dim),
                        v_full.reshape(b, cfg.max_seq_len, cfg.n_heads, cfg.head_dim),
                        idx,
                    )
        else:
            # Head axis is the TP-sharded axis: under TP each device holds
            # n_heads / model_parallelism heads and attention is
            # embarrassingly parallel until out_proj's row-parallel
            # all-reduce.
            q = nn.with_logical_constraint(q, ("batch", "seq", "heads", "head_dim"))
            k = nn.with_logical_constraint(k, ("batch", "seq", "heads", "head_dim"))
            v = nn.with_logical_constraint(v, ("batch", "seq", "heads", "head_dim"))

            with jax.named_scope("attn_kernel"):
                out = causal_attention(
                    q, k, v,
                    impl=cfg.attention,
                    block_q=cfg.attention_block_q,
                    block_kv=cfg.attention_block_kv,
                    block_q_bwd=cfg.attention_block_q_bwd,
                    block_kv_bwd=cfg.attention_block_kv_bwd,
                )
        with jax.named_scope("attn_proj"):
            out = out.reshape(b, t, cfg.d_model)
            out = dense("out_proj", shard_axis=1)(out)
            # Row-parallel output: constraining back to embed-replicated
            # makes XLA insert the TP all-reduce here.
            out = nn.with_logical_constraint(out, ("batch", "seq", "embed"))
        return out


class MLP(nn.Module):
    cfg: ModelConfig
    # Only consulted by the LoRA dropout path (adapters/lora.py); the base
    # MLP has no train-dependent ops, which is why the field can default.
    train: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        cdtype = _dtype(cfg.compute_dtype)
        pdtype = _dtype(cfg.param_dtype)
        with jax.named_scope("mlp"):
            # FSDP shards fc1's d_model CONTRACTION axis and fc2's d_model
            # OUTPUT axis — the shard_axis the overlapped-collectives
            # flavor of _dense keys its ring schedule on (ISSUE 12).
            fc1 = _dense(cfg, cfg.d_ff, "fc1", 0, cdtype, pdtype, "mlp")
            h = apply_lora(self, fc1, x, cfg=cfg, name="fc1", train=self.train)
            h = nn.gelu(h)
            h = nn.with_logical_constraint(h, ("batch", "seq", "mlp"))  # column-parallel
            fc2 = _dense(cfg, cfg.d_model, "fc2", 1, cdtype, pdtype, "mlp")
            h = apply_lora(self, fc2, h, cfg=cfg, name="fc2", train=self.train)
            h = nn.with_logical_constraint(h, ("batch", "seq", "embed"))  # row-parallel all-reduce
        return h


def moe_capacity(t: int, cfg: ModelConfig) -> int:
    """Slots per expert per batch row: ceil(t * top_k * capacity_factor / E).
    Shared with tests so the parity reference cannot drift from the model."""
    import math

    return max(
        1, math.ceil(t * cfg.moe_top_k * cfg.moe_capacity_factor / cfg.moe_experts)
    )


class MoEMLP(nn.Module):
    """Mixture-of-Experts FFN with expert parallelism (beyond the reference,
    which is dense-only — `/root/reference/model/MLP.py`).

    GShard/Switch-style top-k routing with STATIC capacity slots; this
    module owns the router and parameters, while the token<->slot
    permutation is a pluggable backend from ``ops/moe_dispatch.py``
    (``cfg.moe_dispatch``): ``einsum`` contracts one-hot ``(B,T,E,cap)``
    dispatch/combine tensors over T (gather-free, MXU-shaped, cost grows
    with E — PERF.md round 5), ``sort`` executes the same permutation as
    an int32 slot map + row gathers (MegaBlocks-style, O(B·T·k·d) data
    movement at any E). Routing — and therefore which tokens reach which
    expert, the capacity drop policy, and the aux loss — is computed once
    and shared, so the switch is a pure execution-strategy A/B.

    Expert tensors carry an "experts" logical axis mapped to the "model"
    mesh axis, so XLA's partitioner emits the expert-parallel collectives
    (tokens to their experts' devices and back) exactly as it emits TP
    collectives — EP is a rule-table entry, not a hand-written comm
    schedule, and holds for both backends (tests/test_collectives_hlo.py).
    Tokens over an expert's capacity are dropped (contribute zero; the
    residual stream carries them — standard Switch semantics). The
    load-balance aux loss (Switch eq. 4-6, coefficient pre-applied) is
    sowed into the "aux_loss" collection; the train step adds it to the
    CE loss.
    """

    cfg: ModelConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from dtc_tpu.ops import moe_dispatch as md

        cfg = self.cfg
        e, k = cfg.moe_experts, cfg.moe_top_k
        cdtype = _dtype(cfg.compute_dtype)
        b, t, d = x.shape
        cap = moe_capacity(t, cfg)

        wi = self.param(
            "wi", nn.initializers.lecun_normal(), (e, d, cfg.d_ff),
            _dtype(cfg.param_dtype),
        )
        bi = self.param("bi", nn.initializers.zeros_init(), (e, cfg.d_ff),
                        _dtype(cfg.param_dtype))
        wo = self.param(
            "wo", nn.initializers.lecun_normal(), (e, cfg.d_ff, d),
            _dtype(cfg.param_dtype),
        )
        bo = self.param("bo", nn.initializers.zeros_init(), (e, d),
                        _dtype(cfg.param_dtype))

        # Routing in fp32 (softmax numerics), per batch row — shared by
        # both dispatch backends, bitwise.
        logits = nn.Dense(
            e, name="router", use_bias=False,
            dtype=jnp.float32, param_dtype=jnp.float32,
        )(x.astype(jnp.float32))
        routing = md.top_k_routing(jax.nn.softmax(logits, axis=-1), k, cap)
        self.sow(
            "aux_loss", "load_balance",
            md.load_balance_loss(routing, k, cfg.moe_aux_coef),
        )

        if cfg.moe_dispatch == "sort":
            x_e = md.sort_dispatch(x, routing, cap)
        else:
            # Build the one-hot pair ONCE; dispatch and combine each
            # consume their half (the buildup is ~18% of the E=8 step).
            dispatch, combine = md.dispatch_combine_tensors(routing, cap)
            x_e = md.einsum_dispatch(x, dispatch)
        x_e = nn.with_logical_constraint(x_e, ("batch", "experts", None, "embed"))
        y_e = md.expert_ffn(
            x_e, wi.astype(cdtype), bi.astype(cdtype),
            wo.astype(cdtype), bo.astype(cdtype),
        )
        y_e = nn.with_logical_constraint(y_e, ("batch", "experts", None, "embed"))
        if cfg.moe_dispatch == "sort":
            y = md.sort_combine(y_e, routing, cap)
        else:
            y = md.einsum_combine(y_e, combine)
        return nn.with_logical_constraint(y, ("batch", "seq", "embed"))


class Block(nn.Module):
    """Pre-LN transformer block: x + Attn(LN(x)); x + MLP(LN(x)) — the MLP
    is the dense reference FFN or, with ``moe_experts > 0``, the
    expert-parallel :class:`MoEMLP`."""

    cfg: ModelConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        train: bool,
        decode: bool = False,
        decode_index: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.cfg

        def ln(name):
            # LayerNorm in fp32 for numerical stability.
            return nn.LayerNorm(name=name, dtype=jnp.float32, param_dtype=jnp.float32)

        h = ln("ln_1")(x).astype(_dtype(cfg.compute_dtype))
        x = x + nn.Dropout(cfg.dropout, deterministic=not train)(
            CausalSelfAttention(cfg, name="attn")(
                h, train=train, decode=decode, decode_index=decode_index
            )
        )
        h = ln("ln_2")(x).astype(_dtype(cfg.compute_dtype))
        if cfg.moe_experts > 0:
            moe_cls = MoEMLP
            if cfg.remat_mode == "mlp" and train and not decode:
                # Same selective-remat contract as the dense branch: the
                # (B, E, cap, d_ff) expert intermediates are the memory to
                # trade away.
                moe_cls = nn.remat(MoEMLP, prevent_cse=False)
            ff = moe_cls(cfg, name="moe")(h)
        else:
            mlp_cls = MLP
            if cfg.remat_mode == "mlp" and train and not decode:
                # Selective remat: only the MLP's d_ff-wide intermediates
                # are recomputed in backward; the attention path's
                # flash-kernel residuals (q/k/v/out/lse) stay saved, so the
                # backward scan skips the ~0.7 ms/layer attention recompute
                # the "block" mode pays (measured, PERF.md round 4).
                mlp_cls = nn.remat(MLP, prevent_cse=False)
            ff = mlp_cls(cfg, train=train, name="mlp")(h)
        x = x + nn.Dropout(cfg.dropout, deterministic=not train)(ff)
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


class _ScanBlock(nn.Module):
    """Carry adapter so Block can run under nn.scan. The carry is
    ``(h, decode_index)`` — the decode write frontier rides along
    UNCHANGED (None outside decode), so the scan body stays one fused
    block per layer with no per-layer index variable or counter update
    (the pre-hoist layout stacked an (L,) index in the cache collection
    and re-incremented it in every layer's program)."""

    cfg: ModelConfig
    train: bool
    decode: bool = False

    @nn.compact
    def __call__(self, carry, _):
        h, idx = carry
        h = Block(self.cfg)(
            h, train=self.train, decode=self.decode, decode_index=idx
        )
        return (h, idx), None


class GPTEmbed(nn.Module):
    """Token + learned-position embedding with dropout (pipeline stage 0 head-end).

    ``lookup="onehot"`` computes the token lookup as one_hot(x) @ table — a
    matmul instead of a gather. The pipeline step uses it because XLA's SPMD
    partitioner cannot partition a sharded gather inside a partially-manual
    (shard_map over "pipe") region, while a matmul partitions fine — and it
    rides the MXU. Both lookups share identical params.
    """

    cfg: ModelConfig
    lookup: str = "gather"

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        train: bool = True,
        pos_offset: int | jax.Array = 0,
        decode: bool = False,
    ) -> jax.Array:
        cfg = self.cfg
        pdtype = _dtype(cfg.param_dtype)
        _, t = x.shape
        # Decode position bookkeeping is GPT's: the single cache "index"
        # counter doubles as the position offset (cache slots and
        # positions advance in lockstep by construction), passed in via
        # ``pos_offset`` — no per-module counters to keep in sync.
        del decode
        wte = nn.Embed(cfg.padded_vocab_size, cfg.d_model, name="wte", param_dtype=pdtype)
        if self.lookup == "onehot":
            onehot = jax.nn.one_hot(x, cfg.padded_vocab_size, dtype=_dtype(cfg.compute_dtype))
            tok = onehot @ wte.embedding.astype(_dtype(cfg.compute_dtype))
        else:
            tok = wte(x)
        # Positions are a contiguous slice of the table, not a gather.
        # ``pos_offset`` (possibly traced, e.g. stage_id * chunk in the
        # pipeline's seq-chunked embed) says where the slice starts.
        wpe = nn.Embed(cfg.max_seq_len, cfg.d_model, name="wpe", param_dtype=pdtype)
        if isinstance(pos_offset, int) and pos_offset == 0:
            pos = wpe.embedding[:t][None, :, :]
        elif getattr(pos_offset, "ndim", 0) == 1:
            # Per-slot offsets (serving decode: each batch row at its own
            # position) — a (B, t) gather instead of one shared slice.
            rows = pos_offset[:, None] + jnp.arange(t, dtype=jnp.int32)[None]
            pos = jnp.take(wpe.embedding, rows, axis=0)
        else:
            pos = jax.lax.dynamic_slice_in_dim(wpe.embedding, pos_offset, t, axis=0)[None]
        h = (tok + pos).astype(_dtype(cfg.compute_dtype))
        h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        return nn.with_logical_constraint(h, ("batch", "seq", "embed"))


class GPTStage(nn.Module):
    """``n_layers`` stacked blocks — a pipeline stage's layer chunk.

    nn.scan stacks every block param with a leading "layers" axis — the
    layout the TP rule table keys on and the PP (stages, layers/stage, ...)
    reshape relies on (mirrors `/root/reference/model/GPTModel.py:55-67`).
    """

    cfg: ModelConfig
    n_layers: int

    @nn.compact
    def __call__(
        self,
        h: jax.Array,
        *,
        train: bool = True,
        decode: bool = False,
        decode_index: jax.Array | None = None,
    ) -> jax.Array:
        cls = _ScanBlock
        mode = self.cfg.remat_mode
        if mode in ("block", "block_save_flash") and not decode:
            kwargs: dict = {"prevent_cse": False}
            if mode == "block_save_flash":
                # Block remat, but the flash kernel's full residual set
                # (q/k/v/out/lse — tagged with checkpoint_name in the
                # custom-vjp fwd rule) is saved instead of recomputed: the
                # backward scan re-runs the cheap LN/MLP ops but neither
                # the attention kernel nor the qkv projections. ~65 MB/layer
                # of extra HBM at the flagship shape buys back ~4.3 ms/step
                # of recompute at b32 (device-busy 83.1 -> 78.8 ms, PERF.md
                # round 4).
                kwargs["policy"] = jax.checkpoint_policies.save_only_these_names(
                    "flash_out", "flash_lse", "flash_q", "flash_k", "flash_v"
                )
            cls = nn.remat(cls, **kwargs)
        scanned = nn.scan(
            cls,
            # "lora" rides the scan like every block variable: per-layer
            # adapter factors stack with the leading "layers" axis
            # (training (L, in, r); the serving engine's per-slot gather
            # feeds (L, B, in, r) and each layer sees its (B, in, r) row
            # factors). A lora-free model simply has no such collection.
            variable_axes={"params": 0, "cache": 0, "aux_loss": 0, "lora": 0},
            split_rngs={"params": True, "dropout": True},
            length=self.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(self.cfg, train, decode, name="blocks")
        (h, _), _ = scanned((h, decode_index), None)
        return h


class _DenseParams(nn.Module):
    """Parameter container with nn.Dense's exact tree, names, and init
    (kernel: lecun_normal, bias: zeros) — so GPTHead can hand the raw
    kernel/bias to the fused head+CE op while staying checkpoint- and
    sharding-rule-compatible with the nn.Dense layout it replaced."""

    features: int
    param_dtype: Any

    @nn.compact
    def __call__(self, in_features: int):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (in_features, self.features), self.param_dtype,
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,), self.param_dtype
        )
        return kernel, bias


class GPTHead(nn.Module):
    """Final LayerNorm + LM head (pipeline last-stage tail).

    With ``targets`` the head returns the mean next-token CE loss via
    :func:`dtc_tpu.ops.fused_ce.fused_head_ce` (whose backward folds the
    bias gradient into the dW matmul — one logits pass fewer than autodiff,
    PERF.md round 4); without, the padded-and-masked logits as before.
    Both paths share one logits computation (``head_logits``), so train and
    eval/generate numerics cannot drift apart.
    """

    cfg: ModelConfig

    @nn.compact
    def __call__(self, h: jax.Array, targets: jax.Array | None = None) -> jax.Array:
        from dtc_tpu.ops.fused_ce import fused_head_ce, head_logits

        cfg = self.cfg
        h = nn.LayerNorm(name="ln_f", dtype=jnp.float32, param_dtype=jnp.float32)(h)
        kernel, bias = _DenseParams(
            cfg.padded_vocab_size, _dtype(cfg.param_dtype), name="lm_head"
        )(cfg.d_model)
        hc = h.astype(_dtype(cfg.compute_dtype))
        if targets is not None:
            return fused_head_ce(hc, kernel, bias, targets, cfg.vocab_size)
        return head_logits(hc, kernel, bias, cfg.vocab_size)


class GPT(nn.Module):
    """Full decoder-only GPT. Param tree: {"embed": …, "stage": …, "head": …} —
    already the pipeline decomposition, so PP is a leaf reshape away."""

    cfg: ModelConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        train: bool = True,
        decode: bool = False,
        targets: jax.Array | None = None,
    ) -> jax.Array:
        """Forward pass. Returns logits, or — when ``targets`` is given —
        the mean next-token CE loss via the fused head+CE op (the train
        step's path; one logits pass cheaper in backward, PERF.md round 4).

        ``decode=True``: GPT owns the ONE decode position/write-frontier
        counter (``cache/index``) — updated here, outside the layer scan,
        and threaded down read-only so the scan body is pure per-layer
        compute (the per-layer stacked counters this replaced cost an
        update op per layer per token). CALLER CONTRACT: the cumulative
        decoded length across calls must stay <= ``cfg.max_seq_len``. The
        write index is a traced value, so it cannot be range-checked here;
        past the bound, ``dynamic_update_slice`` clamps the write start
        and logits go silently wrong. ``dtc_tpu.generate.generate``
        enforces this at its static API surface — callers applying the
        model directly must do the same (or discharge the
        ``cfg.debug_checks`` checkify guard below).
        """
        cfg = self.cfg
        idx = None
        pos_offset: int | jax.Array = 0
        if decode:
            # The index is () for generate's whole-batch decode, or (B,)
            # when the caller built a per-slot cache (the serving
            # runtime's continuous batching — dtc_tpu/serve/engine.py
            # init_slot_cache): every decode consumer below branches on
            # its STATIC rank, so both flavors share this one model.
            ci = self.variable("cache", "index", lambda: jnp.zeros((), jnp.int32))
            idx = ci.value
            if cfg.debug_checks:
                # The caller contract above, enforced dynamically: callers
                # bypassing generate() can discharge this via
                # checkify.checkify instead of debugging clamped writes.
                from jax.experimental import checkify

                checkify.check(
                    jnp.all(idx + x.shape[1] <= cfg.max_seq_len),
                    "decode cache overflow: write frontier {i} + {n} tokens "
                    "exceeds max_seq_len={m}; dynamic_update_slice would "
                    "clamp and corrupt the cache",
                    i=jnp.max(idx), n=jnp.int32(x.shape[1]),
                    m=jnp.int32(cfg.max_seq_len),
                )
            ci.value = idx + x.shape[1]
            pos_offset = idx
        h = GPTEmbed(cfg, name="embed")(
            x, train=train, decode=decode, pos_offset=pos_offset
        )
        h = GPTStage(cfg, cfg.n_layers, name="stage")(
            h, train=train, decode=decode, decode_index=idx
        )
        return GPTHead(cfg, name="head")(h, targets=targets)


def adapter_param_count(cfg: ModelConfig) -> int:
    """Exact LoRA adapter parameter count from config (0 when disabled).

    Counted SEPARATELY from :func:`param_count` on purpose: the base
    params are frozen and shared across every tenant, while each tenant
    pays only this subtree — the whole point of the multi-tenant design.
    Per targeted site: ``rank * (in + out)`` for the A/B pair, per layer.
    With ``moe_experts > 0`` the dense fc1/fc2 sites do not exist (the
    MoE expert tensors carry no adapters), so only attention targets
    count."""
    a = cfg.adapter
    if a.rank <= 0:
        return 0
    d, f, r = cfg.d_model, cfg.d_ff, a.rank
    dims = {
        "q_proj": (d, d), "k_proj": (d, d), "v_proj": (d, d),
        "out_proj": (d, d),
    }
    if cfg.moe_experts == 0:
        dims["fc1"] = (d, f)
        dims["fc2"] = (f, d)
    per_layer = sum(
        r * (i + o) for t, (i, o) in dims.items() if t in tuple(a.target_modules)
    )
    return cfg.n_layers * per_layer


def param_count(cfg: ModelConfig) -> int:
    """Exact BASE parameter count from config (no tracing needed).
    LoRA adapter params are deliberately excluded — they are per-tenant
    and counted by :func:`adapter_param_count`."""
    d, v, L, f, s = cfg.d_model, cfg.padded_vocab_size, cfg.n_layers, cfg.d_ff, cfg.max_seq_len
    embed = v * d + s * d
    if cfg.moe_experts > 0:
        e = cfg.moe_experts
        ffn = d * e + e * (d * f + f + f * d + d)  # router + E experts
    else:
        ffn = (d * f + f) + (f * d + d)            # fc1 + fc2
    per_block = (
        4 * (d * d + d)        # q,k,v,out projections
        + ffn
        + 4 * d                # ln_1, ln_2 scale+bias
    )
    head = 2 * d + (d * v + v)  # ln_f + lm_head
    return embed + L * per_block + head
