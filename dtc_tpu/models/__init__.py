from dtc_tpu.models.gpt import GPT, param_count

__all__ = ["GPT", "param_count"]
