from dtc_tpu.utils.metrics import gpt_step_flops, mfu, peak_flops_per_chip
from dtc_tpu.utils.logging import CSVLogger
from dtc_tpu.utils.percentile import nearest_rank

__all__ = [
    "gpt_step_flops", "mfu", "peak_flops_per_chip", "CSVLogger",
    "nearest_rank",
]
