"""Sharding-aware checkpoint / resume via Orbax.

The reference never persists anything but the CSV log (SURVEY.md §5
"Checkpoint / resume: absent"). Orbax restores arrays directly into their
NamedShardings, so resume works across mesh shapes as long as the logical
param tree matches.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

PyTree = Any


class CheckpointManager:
    def __init__(self, directory: str):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir, options=ocp.CheckpointManagerOptions(max_to_keep=3)
        )

    def save(self, step: int, state: PyTree) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state_like: PyTree, step: int | None = None) -> PyTree:
        """Restore into the sharding/structure of ``state_like``.

        Every jax.Array leaf gets an explicit NamedSharding on the current
        mesh. Leaves created eagerly outside jit (e.g. scalar AdamW step
        counts from ``tx.init``) carry a SingleDeviceSharding — restoring
        them as-is pins them to device 0 and the first donated train step
        after resume fails with an incompatible-devices error (round-1
        VERDICT "What's weak" #2). Those leaves are restored replicated
        (``P()``) on the mesh inferred from the sharded leaves instead.
        """
        import orbax.checkpoint as ocp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")

        mesh = None
        for leaf in jax.tree.leaves(state_like):
            if isinstance(leaf, jax.Array) and isinstance(leaf.sharding, NamedSharding):
                mesh = leaf.sharding.mesh
                break

        def as_restore_arg(x):
            if isinstance(x, jax.Array):
                sharding = x.sharding
                if not isinstance(sharding, NamedSharding) and mesh is not None:
                    sharding = NamedSharding(mesh, P())
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
            return x

        target = jax.tree.map(as_restore_arg, state_like)
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(target))
        return restored

    # ---- data-stream position sidecars -----------------------------------
    # The input stream's resume point (documents consumed + packer buffer,
    # dtc_tpu.data.packing.TokenPacker.position) rides next to the Orbax
    # step as a small JSON file: a resumed run SEEKS the stream instead of
    # re-tokenizing everything consumed so far (round-3 VERDICT weak #5).

    def save_stream(self, step: int, position: dict, process_index: int = 0) -> None:
        """Positions are PER-PROCESS: each pod host consumes a different
        count of its striped documents and holds a different buffer, so
        every process writes (and later reads) its own sidecar."""
        import glob
        import json

        with open(
            os.path.join(self._dir, f"stream_{step}_p{process_index}.json"), "w"
        ) as f:
            json.dump(position, f)
        # Mirror max_to_keep=3: prune this process's sidecars (Orbax's GC
        # won't touch them).
        paths = sorted(
            glob.glob(os.path.join(self._dir, f"stream_*_p{process_index}.json")),
            key=lambda p: int(os.path.basename(p).split("_")[1]),
        )
        for p in paths[:-3]:
            os.remove(p)

    def load_stream(self, step: int, process_index: int = 0) -> dict | None:
        import json

        path = os.path.join(self._dir, f"stream_{step}_p{process_index}.json")
        if not os.path.exists(path):
            return None  # pre-sidecar checkpoint: caller falls back to drain
        with open(path) as f:
            return json.load(f)

    def save_eval_set(self, batches: list, process_index: int = 0) -> None:
        """Persist the held-out eval batches (already-materialized numpy
        arrays) so a resume does not re-stream and re-tokenize the dataset
        head just to rebuild them."""
        np.savez(
            os.path.join(self._dir, f"eval_set_p{process_index}.npz"), *batches
        )

    def load_eval_set(self, process_index: int = 0) -> list | None:
        path = os.path.join(self._dir, f"eval_set_p{process_index}.npz")
        if not os.path.exists(path):
            return None
        with np.load(path) as z:
            return [z[k] for k in z.files]

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
