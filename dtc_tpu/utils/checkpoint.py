"""Sharding-aware, integrity-verified checkpoint / resume via Orbax.

The reference never persists anything but the CSV log (SURVEY.md §5
"Checkpoint / resume: absent"). Orbax restores arrays directly into their
NamedShardings, so resume works across mesh shapes as long as the logical
param tree matches.

Integrity (CheckFreq-style verified checkpoints): every ``save`` waits for
the async write to land, then records a checksum manifest
(``manifest_<step>.json``: per-file size + sha256) next to the step.
``latest_step``/``restore`` re-verify against the manifest and silently
fall back to the newest INTACT earlier step when the latest is corrupt or
partial — a preempted half-written checkpoint (or bit rot) costs a few
steps of progress instead of the whole run. All JSON/npz sidecars are
written atomically (tmp + ``os.replace``) so a preemption mid-write can
never leave a truncated file that poisons the *next* resume.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
from typing import Any, Callable

import jax
import numpy as np

PyTree = Any

MANIFEST_SKIP = {".tmp"}  # our own atomic-write temp suffix


def _subtree_get(tree: PyTree, path: tuple[str, ...]) -> PyTree:
    node = tree
    for key in path:
        node = node[key] if isinstance(node, dict) else getattr(node, key)
    return node


def _subtree_set(tree: PyTree, path: tuple[str, ...], value: PyTree) -> PyTree:
    """Functionally replace the node at ``path`` (dicts copied per level;
    flax structs / dataclasses updated via ``.replace``)."""
    if not path:
        return value
    head, rest = path[0], path[1:]
    if isinstance(tree, dict):
        out = dict(tree)
        out[head] = _subtree_set(tree[head], rest, value)
        return out
    return tree.replace(**{head: _subtree_set(getattr(tree, head), rest, value)})


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp + fsync + os.replace: readers see the old file or the new file,
    never a truncated one — even across a preemption mid-write."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _atomic_write_json(path: str, obj: Any) -> None:
    _atomic_write_bytes(path, json.dumps(obj).encode())


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


class CheckpointManager:
    """Orbax checkpoints + position sidecars + integrity manifests.

    ``on_event(etype, **fields)`` (typically a
    :class:`dtc_tpu.resilience.events.RecoveryBus` post) receives one
    ``recovery``/``ckpt_fallback`` record whenever verification rejects a
    step, so silent fallbacks still land in telemetry. ``verify=False``
    skips manifest writing/checking (and the save-side wait it requires).

    Known multi-host cost: on resume every process hashes the newest step
    during its own restore_latest (N redundant read passes over shared
    storage). Lead-verify + broadcast (the clobber-guard pattern in the
    trainer) would cut it to one pass, but needs cross-host agreement on
    the chosen step through the fallback path — deferred until multi-host
    restore paths are exercisable in tests; set ``verify=False``
    (``resilience.verify_checkpoints``) if resume-time hashing dominates.
    """

    def __init__(
        self,
        directory: str,
        *,
        verify: bool = True,
        on_event: Callable[..., None] | None = None,
        keep_n: int = 3,
    ):
        import orbax.checkpoint as ocp

        if keep_n < 1:
            raise ValueError(f"keep_n must be >= 1, got {keep_n}")
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.verify = verify
        self.keep_n = keep_n
        self._on_event = on_event
        # Steps that already failed verification: skip re-hashing them (and
        # re-warning) on every later latest_step/restore call — a corrupt
        # step stays corrupt unless re-saved, which clears its entry.
        # Passes are deliberately NOT cached: bit rot between two reads
        # must still be caught, so callers that only need existence should
        # gate on all_steps() and leave the one full verification to
        # restore_latest (as the trainer's resume path does).
        self._rejected: set[int] = set()
        # Retention is OURS (_gc), not Orbax's: max_to_keep would reap an
        # out-of-order re-save the moment it lands (replaying past a
        # rollback on a resumed run re-saves steps BELOW the stale latest
        # — Orbax deletes the fresh dir, _write_manifest then hashes an
        # empty directory, and the run's most recent recovery point
        # silently vanishes; reproduced on the dev_chaos resume drill).
        self._mgr = ocp.CheckpointManager(
            self._dir, options=ocp.CheckpointManagerOptions(max_to_keep=None)
        )

    # ---- paths -----------------------------------------------------------
    def step_dir(self, step: int) -> str:
        return os.path.join(self._dir, str(step))

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._dir, f"manifest_{step}.json")

    # ---- save / verify ---------------------------------------------------
    def save(self, step: int, state: PyTree,
             subtree: tuple[str, ...] | None = None) -> None:
        """Persist ``state`` (or, with ``subtree``, ONLY the node at that
        key path) as the step's checkpoint.

        ``subtree`` is the adapter-checkpoint contract (dtc_tpu/adapters/):
        an adapter-only checkpoint must neither write nor later require
        the frozen base params — extraction happens HERE, before Orbax
        sees the tree, so nothing else can leak in. Restore with the same
        ``subtree`` against any freshly-initialized enclosing state
        (tests/test_adapters.py pins this)."""
        import orbax.checkpoint as ocp

        if subtree is not None:
            state = _subtree_get(state, tuple(subtree))

        if step in self._mgr.all_steps():
            # Replaying past a rollback (or a resume that fell back below
            # the newest step) re-visits steps with stale — possibly
            # corrupt — checkpoints on disk. The fresh save supersedes;
            # the old manifest goes too, or a verify=False re-save would
            # leave a mismatched manifest that damns the good new bytes
            # the next time verification is on.
            self._mgr.delete(step)
            try:
                os.remove(self._manifest_path(step))
            except FileNotFoundError:
                pass
        self._rejected.discard(step)
        # force=True: Orbax's default save policy SILENTLY SKIPS any step
        # <= its latest — exactly what a replay past a rollback on a
        # resumed run produces (re-saving 30 below a stale 40). Combined
        # with the stale-delete above, the skip turned a re-save into a
        # pure deletion: the run's newest recovery point vanished and an
        # empty manifest blessed the ghost (caught by _write_manifest's
        # guard; reproduced on the dev_chaos resume drill). The save
        # cadence is the trainer's decision, never Orbax's.
        self._mgr.save(step, args=ocp.args.StandardSave(state), force=True)
        if not self.verify:
            # Pure-async mode: the save overlap is the whole point — no
            # wait, no manifest. GC still runs (it only ever touches steps
            # OTHER than this in-flight one); a not-yet-finalized step is
            # simply invisible to all_steps until the next save's pass.
            self._gc(current=step)
            return
        # Verified checkpointing trades the async-save overlap for
        # integrity: the manifest must hash the FINAL bytes, so wait for
        # the write to land before fingerprinting it. wait_until_finished
        # is Orbax's cross-process finalize barrier, after which the step
        # is globally complete. The manifest itself is ONE shared file in
        # a shared directory: lead-process-only, or N hosts race the same
        # tmp-and-replace (and pay N redundant sha256 passes).
        self._mgr.wait_until_finished()
        if jax.process_index() == 0:
            self._write_manifest(step)
            self._prune_aux("manifest_*.json", keyfield=1, keep_step=step)
        self._gc(current=step)

    def _gc(self, current: int) -> None:
        """Retention (ISSUE 15 satellite): keep the newest ``keep_n``
        steps, delete older superseded steps plus their manifests. Only
        runs from ``save`` AFTER the new step landed (and, with
        verification on, after its manifest hashed the final bytes) — so
        every collection is superseded by a just-verified newer step,
        never a blind delete. Replay-path deletion used to be the only
        pruning; long runs accumulated steps unboundedly.

        ``current`` — the step this pass just saved — is never deleted:
        after a rollback on a resumed run, the replay re-saves steps
        numerically BELOW stale steps from the abandoned timeline, and
        "newest keep_n" alone would reap the run's actual recovery point
        (keep_n=1 with a stale later step would leave ONLY the stale
        one). Stale-but-newer steps linger until the replay passes and
        re-saves them — bounded by the old timeline's length, and still
        valid restore targets on this deterministic replay anyway."""
        steps = self.all_steps()
        if len(steps) <= self.keep_n:
            return
        for old in steps[:-self.keep_n]:
            if old == current:
                continue
            self._mgr.delete(old)
            self._rejected.discard(old)
            try:
                os.remove(self._manifest_path(old))
            except FileNotFoundError:
                pass

    def _write_manifest(self, step: int) -> None:
        root = self.step_dir(step)
        files: dict[str, dict[str, Any]] = {}
        for dirpath, _, names in os.walk(root):
            for name in names:
                if any(name.endswith(s) for s in MANIFEST_SKIP):
                    continue
                p = os.path.join(dirpath, name)
                rel = os.path.relpath(p, root)
                files[rel] = {
                    "size": os.path.getsize(p),
                    "sha256": _sha256_file(p),
                }
        if not files:
            # A manifest hashing nothing would "verify" a checkpoint that
            # no longer exists (seen when Orbax retention reaped the step
            # dir between save and fingerprint). Fail loud: an empty
            # checkpoint is never a valid restore target.
            raise RuntimeError(
                f"checkpoint step {step}: no files under {root} at "
                "manifest time — step dir vanished before fingerprinting"
            )
        _atomic_write_json(
            self._manifest_path(step), {"step": step, "files": files}
        )

    def verify_step(self, step: int) -> bool:
        """True when the step's files match its manifest. A step with no
        manifest (pre-manifest checkpoint, or ``verify=False`` writer) is
        trusted — restore still has its own exception fallback."""
        if step in self._rejected:
            return False
        root = self.step_dir(step)
        if not os.path.isdir(root):
            return False
        mpath = self._manifest_path(step)
        if not os.path.exists(mpath):
            return True
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            return False
        for rel, meta in manifest.get("files", {}).items():
            p = os.path.join(root, rel)
            if not os.path.exists(p):
                return False
            if os.path.getsize(p) != meta["size"]:
                return False
            if _sha256_file(p) != meta["sha256"]:
                return False
        return True

    def _reject(self, step: int, why: str, sticky: bool = True) -> None:
        """``sticky`` caches the rejection (manifest mismatches are
        permanent until re-saved); restore-time exceptions are NOT cached —
        they may be transient (OOM, storage hiccup) or structural (model
        config changed), and excluding the step forever would be wrong."""
        if step in self._rejected:
            return  # already reported once
        if sticky:
            self._rejected.add(step)
        print(
            f"[dtc_tpu] WARNING: checkpoint step {step} {why}; "
            "falling back to an earlier step"
        )
        if self._on_event is not None:
            self._on_event("recovery", action="ckpt_fallback",
                           rejected_step=step, reason=why)

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def latest_step(self) -> int | None:
        """Newest INTACT step (manifest-verified); None when no checkpoint
        survives verification."""
        steps = self.all_steps()
        if not self.verify:
            return steps[-1] if steps else None
        for s in reversed(steps):
            if self.verify_step(s):
                return s
            self._reject(s, "failed integrity verification")
        return None

    # ---- restore ---------------------------------------------------------
    def restore(self, state_like: PyTree, step: int | None = None,
                subtree: tuple[str, ...] | None = None) -> PyTree:
        """Restore into the sharding/structure of ``state_like``.

        With ``step=None``, restores the newest step that BOTH passes
        manifest verification AND actually restores — an unverifiable
        legacy step that turns out corrupt is caught by Orbax's own raise
        and the next older intact step is tried.

        With ``subtree`` (a checkpoint written by ``save(..., subtree=…)``),
        only that node is read from disk and grafted back into
        ``state_like`` — the rest of the tree (e.g. a freshly-initialized
        frozen base) passes through untouched, never required on disk.

        Every jax.Array leaf gets an explicit NamedSharding on the current
        mesh. Leaves created eagerly outside jit (e.g. scalar AdamW step
        counts from ``tx.init``) carry a SingleDeviceSharding — restoring
        them as-is pins them to device 0 and the first donated train step
        after resume fails with an incompatible-devices error (round-1
        VERDICT "What's weak" #2). Those leaves are restored replicated
        (``P()``) on the mesh inferred from the sharded leaves instead.
        """
        if step is not None:
            if subtree is not None:
                piece = self._restore_step(
                    step, _subtree_get(state_like, tuple(subtree))
                )
                return _subtree_set(state_like, tuple(subtree), piece)
            return self._restore_step(step, state_like)
        state, _ = self.restore_latest(state_like, subtree=subtree)
        return state

    def restore_latest(self, state_like: PyTree,
                       subtree: tuple[str, ...] | None = None
                       ) -> tuple[PyTree, int]:
        """Restore the newest intact step; returns ``(state, step)`` so
        callers (resume, rollback) know which step they actually got.
        ``subtree``: see :meth:`restore`."""
        if subtree is not None:
            piece_like = _subtree_get(state_like, tuple(subtree))
            piece, step = self.restore_latest(piece_like)
            return _subtree_set(state_like, tuple(subtree), piece), step
        steps = self.all_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        last_err: Exception | None = None
        for s in reversed(steps):
            if self.verify and not self.verify_step(s):
                self._reject(s, "failed integrity verification")
                continue
            try:
                return self._restore_step(s, state_like), s
            except Exception as e:  # corrupt beyond what the manifest saw
                last_err = e
                self._reject(
                    s, f"failed to restore ({type(e).__name__})", sticky=False
                )
        raise FileNotFoundError(
            f"no intact checkpoint under {self._dir} "
            f"(all {len(steps)} candidate step(s) rejected; last error: "
            f"{type(last_err).__name__ if last_err else 'manifest mismatch'})"
        ) from last_err

    def _restore_step(self, step: int, state_like: PyTree) -> PyTree:
        import orbax.checkpoint as ocp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = None
        for leaf in jax.tree.leaves(state_like):
            if isinstance(leaf, jax.Array) and isinstance(leaf.sharding, NamedSharding):
                mesh = leaf.sharding.mesh
                break

        def as_restore_arg(x):
            if isinstance(x, jax.Array):
                sharding = x.sharding
                if not isinstance(sharding, NamedSharding) and mesh is not None:
                    sharding = NamedSharding(mesh, P())
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
            return x

        target = jax.tree.map(as_restore_arg, state_like)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(target))

    # ---- data-stream position sidecars -----------------------------------
    # The input stream's resume point (documents consumed + packer buffer,
    # dtc_tpu.data.packing.TokenPacker.position) rides next to the Orbax
    # step as a small JSON file: a resumed run SEEKS the stream instead of
    # re-tokenizing everything consumed so far (round-3 VERDICT weak #5).

    def save_stream(self, step: int, position: dict, process_index: int = 0) -> None:
        """Positions are PER-PROCESS: each pod host consumes a different
        count of its striped documents and holds a different buffer, so
        every process writes (and later reads) its own sidecar. Atomic:
        a preemption mid-write must not leave truncated JSON that poisons
        the next resume."""
        _atomic_write_json(
            os.path.join(self._dir, f"stream_{step}_p{process_index}.json"),
            position,
        )
        self._prune_aux(
            f"stream_*_p{process_index}.json", keyfield=1, keep_step=step
        )

    def _prune_aux(
        self, pattern: str, keyfield: int, keep_step: int | None = None
    ) -> None:
        """Mirror ``keep_n`` retention for our auxiliary files (Orbax's
        GC won't touch them). ``keep_step`` — the step this save pass
        just wrote — is exempt, same as ``_gc``'s current-step guard:
        a replay re-save numerically below >= keep_n stale steps would
        otherwise lose its just-written manifest/sidecar, silently
        stripping integrity verification (verify_step trusts a
        manifest-less step) from the run's actual recovery point."""
        paths = sorted(
            glob.glob(os.path.join(self._dir, pattern)),
            key=lambda p: int(
                os.path.basename(p).split("_")[keyfield].split(".")[0]
            ),
        )
        for p in paths[:-self.keep_n]:
            step = int(
                os.path.basename(p).split("_")[keyfield].split(".")[0]
            )
            if keep_step is not None and step == keep_step:
                continue
            os.remove(p)

    def load_stream(self, step: int, process_index: int = 0) -> dict | None:
        path = os.path.join(self._dir, f"stream_{step}_p{process_index}.json")
        if not os.path.exists(path):
            return None  # pre-sidecar checkpoint: caller falls back to drain
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            # A legacy (pre-atomic-write) torn sidecar must degrade to the
            # drain-loop fallback, not kill the resume.
            print(f"[dtc_tpu] WARNING: unreadable stream sidecar {path} ({e})")
            return None

    def save_eval_set(self, batches: list, process_index: int = 0) -> None:
        """Persist the held-out eval batches (already-materialized numpy
        arrays) so a resume does not re-stream and re-tokenize the dataset
        head just to rebuild them. Atomic (tmp + os.replace)."""
        path = os.path.join(self._dir, f"eval_set_p{process_index}.npz")
        tmp = path + ".tmp"
        # np.savez appends ".npz" to bare paths but honors open handles.
        with open(tmp, "wb") as f:
            np.savez(f, *batches)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def load_eval_set(self, process_index: int = 0) -> list | None:
        path = os.path.join(self._dir, f"eval_set_p{process_index}.npz")
        if not os.path.exists(path):
            return None
        try:
            with np.load(path) as z:
                return [z[k] for k in z.files]
        except (OSError, ValueError) as e:
            print(f"[dtc_tpu] WARNING: unreadable eval-set sidecar {path} ({e})")
            return None

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
