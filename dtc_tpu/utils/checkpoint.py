"""Sharding-aware checkpoint / resume via Orbax.

The reference never persists anything but the CSV log (SURVEY.md §5
"Checkpoint / resume: absent"). Orbax restores arrays directly into their
NamedShardings, so resume works across mesh shapes as long as the logical
param tree matches.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

PyTree = Any


class CheckpointManager:
    def __init__(self, directory: str):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir, options=ocp.CheckpointManagerOptions(max_to_keep=3)
        )

    def save(self, step: int, state: PyTree) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state_like: PyTree, step: int | None = None) -> PyTree:
        """Restore into the sharding/structure of ``state_like``."""
        import orbax.checkpoint as ocp

        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")

        def as_restore_arg(x):
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            return x

        target = jax.tree.map(as_restore_arg, state_like)
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(target))
        return restored

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
