"""Throughput and MFU accounting.

The reference reports only wall-clock step time (`/root/reference/train/train.py:87-90`).
The north star demands >=40% MFU on TPU, which requires actually computing
model FLOPs and knowing per-chip peak — both live here.
"""

from __future__ import annotations

import jax

from dtc_tpu.config.schema import ModelConfig
from dtc_tpu.models.gpt import param_count

#: Peak dense (bf16) FLOP/s per chip by device kind substring.
_PEAK_FLOPS = (
    ("v5 lite", 197e12),   # v5e (axon reports "TPU v5 lite")
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),        # Trillium
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_chip(device=None) -> float | None:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if device.platform != "tpu":
        return None
    for key, flops in _PEAK_FLOPS:
        if key in kind:
            return flops
    return None


def gpt_step_flops(cfg: ModelConfig, batch: int, seq_len: int) -> float:
    """Total training FLOPs for one step (fwd + bwd).

    Standard 6ND matmul accounting over non-embedding params plus the
    causal attention score/value term 12·L·B·T²·d_model / 2.
    """
    n = param_count(cfg)
    # wte/wpe gathers are not matmuls; lm_head IS a matmul and is counted.
    # Subtract on the padded-vocab basis param_count uses (round-1 ADVICE:
    # mixing bases counted the pad rows as matmul FLOPs).
    n_matmul = n - cfg.padded_vocab_size * cfg.d_model - cfg.max_seq_len * cfg.d_model
    tokens = batch * seq_len
    dense = 6.0 * n_matmul * tokens
    attn = 12.0 * cfg.n_layers * batch * (seq_len**2) * cfg.d_model / 2.0
    return dense + attn


def moe_step_flops(cfg: ModelConfig, batch: int, seq_len: int) -> float:
    """Training FLOPs/step for the MoE model (``moe_experts > 0``).

    Counts the matmul work the step actually schedules (6ND-style: fwd
    2x + bwd 4x per MAC), with the dense FFN term replaced by the MoE
    block's four structural matmuls: router, dispatch/combine einsums
    (contraction over T — real MXU work, see PERF.md round 5), and the
    E-expert FFN over the static capacity slots. Capacity slack means
    E*cap >= k*T slots run regardless of how many are filled — that
    overhead is the einsum-dispatch design's price and is counted, so the
    MFU here is hardware utilization, not "useful-token" utilization.
    """
    from dtc_tpu.models.gpt import moe_capacity

    assert cfg.moe_experts > 0
    e, cap, d, ff = cfg.moe_experts, moe_capacity(seq_len, cfg), cfg.d_model, cfg.d_ff
    tokens = batch * seq_len
    # Dense accounting minus the router/expert params — a token does NOT
    # visit every expert, so their FLOPs are counted structurally below,
    # not via 6N.
    n = param_count(cfg)
    n_matmul = n - cfg.padded_vocab_size * cfg.d_model - cfg.max_seq_len * cfg.d_model
    n_moe = cfg.n_layers * (d * e + e * 2 * d * ff)
    dense = 6.0 * (n_matmul - n_moe) * tokens
    attn = 12.0 * cfg.n_layers * batch * (seq_len**2) * d / 2.0
    per_layer_moe = (
        2.0 * batch * seq_len * d * e              # router
        + 2.0 * 2.0 * batch * seq_len * e * cap * d  # dispatch + combine
        + 2.0 * 2.0 * batch * e * cap * d * ff       # wi + wo
    )
    moe = 3.0 * cfg.n_layers * per_layer_moe       # fwd + 2x bwd
    return dense + attn + moe


def _dtype_bytes(dtype: str) -> int:
    return {"float32": 4, "bfloat16": 2, "float16": 2}.get(dtype, 4)


def comm_bytes_per_step(
    cfg: ModelConfig,
    batch: int,
    seq_len: int,
    mesh_shape: dict[str, int],
    parallel: str,
    pp_microbatches: int = 1,
) -> dict[str, float]:
    """Estimated per-device collective traffic for ONE training step, in
    bytes, from the active parallelism config — no profiler needed.

    Standard ring-collective accounting (each of the three terms is what
    the paper's DP/TP/PP comparison trades off):

    - ``dp_allreduce``: gradient all-reduce over the ``data`` axis —
      ``2·(d-1)/d · P`` bytes per device (reduce-scatter + all-gather),
      with gradients in ``param_dtype``. FSDP pays the same wire bytes
      re-phased (param all-gather fwd + bwd, grad reduce-scatter):
      ``3·(d-1)/d · P``.
    - ``tp_allreduce``: Megatron TP's two activation all-reduces in
      forward and two in backward per layer over the ``model`` axis, on
      ``(B, T, d_model)`` activations in ``compute_dtype``.
    - ``pp_p2p``: boundary-activation sends between adjacent stages —
      ``(stages-1)`` cuts crossed forward and backward by every
      microbatch.

    Returns per-collective estimates plus their ``total``; all terms are
    0.0 for axes of size 1, so the dict is safe to emit unconditionally.
    """
    d_axis = max(mesh_shape.get("data", 1), 1)
    m_axis = max(mesh_shape.get("model", 1), 1)
    p_axis = max(mesh_shape.get("pipe", 1), 1)
    pbytes = _dtype_bytes(cfg.param_dtype)
    abytes = _dtype_bytes(cfg.compute_dtype)
    n_params = param_count(cfg)

    dp = 0.0
    if d_axis > 1:
        factor = 3.0 if parallel == "fsdp" else 2.0
        # Per-device parameter share: TP/PP already split the tree.
        local_params = n_params / (m_axis * p_axis)
        dp = factor * (d_axis - 1) / d_axis * local_params * pbytes

    tp = 0.0
    if m_axis > 1:
        act = batch * seq_len * cfg.d_model * abytes / d_axis  # per-device B shard
        tp = 4.0 * cfg.n_layers * 2.0 * (m_axis - 1) / m_axis * act

    pp = 0.0
    if p_axis > 1:
        micro = batch / max(pp_microbatches, 1) / d_axis
        act = micro * seq_len * cfg.d_model * abytes
        pp = 2.0 * (p_axis - 1) * pp_microbatches * act

    return {
        "dp_allreduce": dp,
        "tp_allreduce": tp,
        "pp_p2p": pp,
        "total": dp + tp + pp,
    }


def mfu(cfg: ModelConfig, batch: int, seq_len: int, step_time_s: float, n_chips: int) -> float | None:
    peak = peak_flops_per_chip()
    if peak is None or step_time_s <= 0:
        return None
    flops = (
        moe_step_flops(cfg, batch, seq_len)
        if cfg.moe_experts > 0
        else gpt_step_flops(cfg, batch, seq_len)
    )
    return flops / (step_time_s * peak * n_chips)
