"""Throughput and MFU accounting.

The reference reports only wall-clock step time (`/root/reference/train/train.py:87-90`).
The north star demands >=40% MFU on TPU, which requires actually computing
model FLOPs and knowing per-chip peak — both live here.
"""

from __future__ import annotations

import jax

from dtc_tpu.config.schema import ModelConfig
from dtc_tpu.models.gpt import adapter_param_count, param_count

#: Peak dense (bf16) FLOP/s per chip by device kind substring.
_PEAK_FLOPS = (
    ("v5 lite", 197e12),   # v5e (axon reports "TPU v5 lite")
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),        # Trillium
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_chip(device=None) -> float | None:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if device.platform != "tpu":
        return None
    for key, flops in _PEAK_FLOPS:
        if key in kind:
            return flops
    return None


def gpt_step_flops(cfg: ModelConfig, batch: int, seq_len: int) -> float:
    """Total training FLOPs for one step (fwd + bwd).

    Standard 6ND matmul accounting over non-embedding params plus the
    causal attention score/value term 12·L·B·T²·d_model / 2.
    """
    n = param_count(cfg)
    # wte/wpe gathers are not matmuls; lm_head IS a matmul and is counted.
    # Subtract on the padded-vocab basis param_count uses (round-1 ADVICE:
    # mixing bases counted the pad rows as matmul FLOPs).
    n_matmul = n - cfg.padded_vocab_size * cfg.d_model - cfg.max_seq_len * cfg.d_model
    tokens = batch * seq_len
    dense = 6.0 * n_matmul * tokens
    attn = 12.0 * cfg.n_layers * batch * (seq_len**2) * cfg.d_model / 2.0
    return dense + attn


def moe_step_flops(cfg: ModelConfig, batch: int, seq_len: int) -> float:
    """Training FLOPs/step for the MoE model (``moe_experts > 0``).

    Counts the matmul work the step actually schedules (6ND-style: fwd
    2x + bwd 4x per MAC), with the dense FFN term replaced by the MoE
    block's four structural matmuls: router, dispatch/combine einsums
    (contraction over T — real MXU work, see PERF.md round 5), and the
    E-expert FFN over the static capacity slots. Capacity slack means
    E*cap >= k*T slots run regardless of how many are filled — that
    overhead is the einsum-dispatch design's price and is counted, so the
    MFU here is hardware utilization, not "useful-token" utilization.
    """
    from dtc_tpu.models.gpt import moe_capacity

    cap = moe_capacity(seq_len, cfg)
    dense, attn = _moe_non_expert_flops(cfg, batch, seq_len)
    d, e, ff = cfg.d_model, cfg.moe_experts, cfg.d_ff
    # Expert FFN on the same 2-FLOPs-per-param-per-token convention the
    # dense 6N term uses (biases included), over the e·cap static slots.
    per_layer_moe = (
        2.0 * batch * seq_len * d * e                    # router
        + 2.0 * 2.0 * batch * seq_len * e * cap * d      # dispatch + combine
        + 2.0 * batch * e * cap * (2 * d * ff + ff + d)  # expert FFN
    )
    moe = 3.0 * cfg.n_layers * per_layer_moe       # fwd + 2x bwd
    return dense + attn + moe


def _moe_non_expert_flops(cfg: ModelConfig, batch: int, seq_len: int) -> tuple[float, float]:
    """Shared prelude of both MoE FLOP bases: (dense-6N minus the MoE
    block, attention). Dense accounting excludes the router/expert params
    — a token does NOT visit every expert, so their FLOPs are counted
    structurally by each basis — and the subtracted block must be the
    FULL per-layer MoE param count from param_count: router + wi/bi/wo/bo
    INCLUDING the per-expert biases (round-5 ADVICE: omitting the
    e·(ff+d) bias params left them double-counted via the 6N term). One
    definition so a future accounting fix cannot skew the hardware-vs-
    useful comparison by landing in only one basis."""
    assert cfg.moe_experts > 0
    d, e, ff = cfg.d_model, cfg.moe_experts, cfg.d_ff
    n = param_count(cfg)
    n_matmul = n - cfg.padded_vocab_size * d - cfg.max_seq_len * d
    n_moe = cfg.n_layers * (d * e + e * (2 * d * ff + ff + d))
    dense = 6.0 * (n_matmul - n_moe) * batch * seq_len
    attn = 12.0 * cfg.n_layers * batch * (seq_len**2) * d / 2.0
    return dense, attn


def moe_step_flops_useful(cfg: ModelConfig, batch: int, seq_len: int) -> float:
    """Useful-FLOPs basis for the MoE step: only the k·T routed
    token-expert assignments count (no capacity slack — drops still
    count, matching Switch's nominal compute), dispatch/combine are
    uncounted bookkeeping.

    This basis is dispatch-implementation-independent, so it is the
    honest denominator-free A/B metric between ``moe_dispatch`` backends
    (``moe_step_flops`` counts the einsum backend's structural work —
    capacity slack and the (B,T,E,cap) contractions — which the sort
    backend does not schedule). PERF.md reports both.
    """
    dense, attn = _moe_non_expert_flops(cfg, batch, seq_len)
    d, e, ff, k = cfg.d_model, cfg.moe_experts, cfg.d_ff, cfg.moe_top_k
    per_layer_moe = (
        2.0 * batch * seq_len * d * e                          # router
        + 2.0 * batch * seq_len * k * (2 * d * ff + ff + d)    # k assignments/token
    )
    return dense + attn + 3.0 * cfg.n_layers * per_layer_moe


def _dtype_bytes(dtype: str) -> int:
    from dtc_tpu.config.schema import DTYPE_BYTES

    return DTYPE_BYTES.get(dtype, 4)


#: Sustained HBM bandwidth per v5e chip (GB/s) — the denominator of the
#: decode roofline. Peak is 819; we model the floor at peak (optimistic
#: floor = honest "pct of roofline" ceiling).
HBM_GBPS_V5E = 819.0


def decode_step_flops(cfg: ModelConfig, batch: int, cache_len: int) -> float:
    """Matmul FLOPs for ONE decode step (every sequence in the batch
    appends one token; no backward).

    2·N_matmul per token for the dense side (same N_matmul basis as
    :func:`gpt_step_flops`: embedding gathers excluded, lm_head counted)
    plus single-query attention: per layer one (1, cache_len)·head score
    row and one value contraction — 4·cache_len·d_model FLOPs/layer/token.
    Decode FLOPs are tiny (the flagship's ~0.13 GF/token is <0.001% of a
    v5e-second); the step is bandwidth-bound, which is why the roofline
    below is a byte model, not a FLOP model.

    With an active adapter (``cfg.adapter.rank > 0``) the per-token
    low-rank term rides along — 2 FLOPs per adapter param per token, the
    same convention as the dense 2·N term — so LoRA-serving roofline rows
    stay honest about the extra work every token pays.
    """
    n = param_count(cfg)
    n_matmul = n - cfg.padded_vocab_size * cfg.d_model - cfg.max_seq_len * cfg.d_model
    dense = 2.0 * n_matmul * batch
    attn = 4.0 * cfg.n_layers * batch * cache_len * cfg.d_model
    lora = 2.0 * adapter_param_count(cfg) * batch
    return dense + attn + lora


def decode_step_bytes(
    cfg: ModelConfig, batch: int, cache_len: int
) -> dict[str, float]:
    """Estimated HBM bytes moved by ONE decode step — the decode
    roofline's numerator, by component:

    - ``weights``: every matmul parameter read once per step in
      ``param_dtype`` (batch amortizes this — THE reason wider decode
      batches win; fp32 master weights make it 4 bytes/param: an
      inference deployment would halve it by serving bf16 copies).
    - ``kv_read``: both caches read up to the frontier per layer
      (``cache_len`` columns) — the bandwidth-OPTIMAL traffic a
      single-query step needs, which keeps this a true floor. Neither
      current path achieves it: the XLA oracle and the single-tile fused
      kernels read the full ``max_seq_len`` buffer, and the blocked
      kernel's beyond-frontier skip predicates the compute only (the
      pipeline still copies every block in), so measured pct-of-roofline
      carries that slack on top of launch overhead. The element size
      follows ``cfg.kv_cache_dtype``: int8 moves the 1-byte payload PLUS
      the per-(position, head) fp32 scales (counted honestly — they are
      real HBM traffic, ~1/(2·D) of the bf16 payload), so int8 cuts this
      term ~2× vs bf16 and ~4× vs fp32, not exactly.
    - ``kv_write``: the new token's k/v appended per layer (same
      dtype-and-scales accounting as ``kv_read``).
    - ``activations``: residual stream + qkv/attn-out + the d_ff-wide MLP
      intermediate crossing HBM once each per layer, plus the final
      logits row — an estimate (XLA fuses some of these into neighbors),
      kept structural so the floor is conservative (higher floor = honest
      pct-of-roofline).
    - ``lora`` (adapter-enabled models only): each batch row reads ITS
      OWN gathered factors per step — unlike the base weights, the
      per-tenant term scales with batch and cannot amortize across rows,
      which is the multi-tenant design's bandwidth price.

    Returns the components plus ``total``.
    """
    pbytes = _dtype_bytes(cfg.param_dtype)
    cbytes = _dtype_bytes(cfg.compute_dtype)
    d, ff, hd = cfg.d_model, cfg.d_ff, cfg.n_heads * cfg.head_dim
    n = param_count(cfg)
    n_matmul = n - cfg.padded_vocab_size * d - cfg.max_seq_len * d
    weights = float(n_matmul) * pbytes
    # Per cache position per layer: both payloads in the store dtype,
    # plus — int8 only — the two fp32 per-head scale vectors
    # (ops/decode_attention.quantize_kv).
    kv_pos = 2.0 * hd * _dtype_bytes(cfg.kv_store_dtype)
    if cfg.kv_quantized:
        kv_pos += 2.0 * cfg.n_heads * 4.0
    kv_read = cfg.n_layers * cache_len * kv_pos * batch
    kv_write = cfg.n_layers * kv_pos * batch
    # Per layer: residual in/out (2d), two LN reads (2d, fp32 but count
    # cbytes — fused), qkv out (3d), attention out + proj out (2d), MLP
    # intermediate write+read (2·d_ff), MLP out (d) ≈ 10·d + 2·d_ff per
    # token; plus the (padded) logits row the head writes.
    activations = (
        cfg.n_layers * (10.0 * d + 2.0 * ff) * cbytes * batch
        + cfg.padded_vocab_size * cbytes * batch
    )
    lora = float(adapter_param_count(cfg)) * pbytes * batch
    total = weights + kv_read + kv_write + activations + lora
    return {
        "weights": weights,
        "kv_read": kv_read,
        "kv_write": kv_write,
        "activations": activations,
        "lora": lora,
        "total": total,
    }


def decode_roofline_ms(
    cfg: ModelConfig,
    batch: int,
    cache_len: int,
    hbm_gbps: float = HBM_GBPS_V5E,
) -> float:
    """Memory-bandwidth floor for one decode step, in ms (Pope et al.
    2022's small-batch regime: weight + cache reads at HBM speed bound
    the step; compute is negligible at these shapes). ``cache_len``
    should be the mean frontier over the measured run (prompt +
    new_tokens/2) when scoring a bench row."""
    total = decode_step_bytes(cfg, batch, cache_len)["total"]
    return total / (hbm_gbps * 1e9) * 1e3


def spec_decode_step_flops(
    cfg: ModelConfig, draft_cfg: ModelConfig, batch: int, cache_len: int,
    spec_k: int,
) -> float:
    """Matmul FLOPs for ONE speculative round (ISSUE 19): the k-query
    verify launch plus the draft's ``spec_k`` propose steps (the round
    runs one draft step more than it strictly needs so both cache
    frontiers land together — counted, because it is scheduled).

    Verify: every one of the ``spec_k`` in-register query positions pays
    the full dense 2·N_matmul pass, and its attention row reads
    ``cache_len`` cache columns plus its in-window causal prefix —
    ``Σ_j (cache_len + j) = k·cache_len + k(k-1)/2`` columns total.
    """
    n = param_count(cfg)
    d = cfg.d_model
    n_matmul = n - cfg.padded_vocab_size * d - cfg.max_seq_len * d
    dense = 2.0 * n_matmul * batch * spec_k
    cols = spec_k * cache_len + spec_k * (spec_k - 1) / 2.0
    attn = 4.0 * cfg.n_layers * batch * cols * d
    draft = spec_k * decode_step_flops(draft_cfg, batch, cache_len)
    return dense + attn + draft


def spec_decode_step_bytes(
    cfg: ModelConfig, draft_cfg: ModelConfig, batch: int, cache_len: int,
    spec_k: int,
) -> dict[str, float]:
    """Estimated HBM bytes for ONE speculative round — what makes
    ``pct_of_roofline`` on spec bench rows honest about the draft's
    bandwidth price (ISSUE 19). Components:

    - ``weights`` / ``kv_read``: the TARGET's, read ONCE — this is the
      whole speculative bet: one verify launch amortizes the dominant
      stream over up to ``spec_k`` emitted tokens instead of one.
    - ``kv_write`` / ``activations``: the target's, ×``spec_k`` — every
      window position writes its k/v and runs the dense stack.
    - ``draft``: ``spec_k`` FULL single-token draft steps (the
      ``lax.scan`` re-reads the draft weights and its cache every step —
      no amortization; this is the price the accepted-token rate must
      repay, and at ``draft_layers/n_layers`` depth it is the term that
      decides whether speculation wins on bandwidth at all).

    Returns the components plus ``total``. Score spec rows against
    ``ms_per_accepted_token``, never raw launch time: a row that hides
    the draft term would report >100% roofline at accept_rate 0.
    """
    tb = decode_step_bytes(cfg, batch, cache_len)
    draft = spec_k * decode_step_bytes(draft_cfg, batch, cache_len)["total"]
    out = {
        "weights": tb["weights"],
        "kv_read": tb["kv_read"],
        "kv_write": tb["kv_write"] * spec_k,
        "activations": tb["activations"] * spec_k,
        "lora": tb["lora"],  # structurally 0: spec serving is adapter-free
        "draft": draft,
    }
    out["total"] = sum(out.values())
    return out


def tokens_accepted_per_launch(emitted: int, launches: int) -> float | None:
    """Mean tokens landed per verify launch (``n_acc + 1`` per row per
    round, so ∈ [1, spec_k] when speculation runs) — the launch-economy
    numerator every spec bench row reports. None when nothing launched."""
    if launches <= 0:
        return None
    return emitted / launches


def ms_per_accepted_token(wall_s: float, emitted: int) -> float | None:
    """Wall milliseconds per ACCEPTED (emitted) token — the spec-vs-plain
    A/B metric: plain decode's equivalent is its ms/token, and a draft
    only earns its keep when this comes in lower. Proposals never appear
    in the denominator (the honesty rule the goodput ledger enforces on
    the time side). None when nothing was emitted."""
    if emitted <= 0:
        return None
    return wall_s * 1e3 / emitted


def tp_sharded_param_count(cfg: ModelConfig) -> int:
    """Parameters Megatron TP actually shards over "model": the block
    matmul kernels, their COLUMN-parallel biases (qkv/fc1 — out_proj/fc2
    biases live on the replicated ``embed_p`` output axis), and the
    vocab-parallel lm_head. LayerNorms, row-parallel biases, and the
    wte/wpe embeddings are TP-replicated. Mirrors the DEFAULT_RULES /
    FSDP_RULES tables (tests pin it against ``param_specs``); the MoE
    expert tensors shard over "model" via the ``experts_p`` rows and are
    counted whole (router replicated)."""
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    v = cfg.padded_vocab_size
    if cfg.moe_experts > 0:
        e = cfg.moe_experts
        ffn = e * (d * f + f + f * d + d)      # wi/bi/wo/bo (experts_p)
    else:
        ffn = d * f + f + f * d                # fc1 kernel+bias, fc2 kernel
    per_block = 4 * d * d + 3 * d + ffn        # q/k/v/out kernels, qkv biases
    return L * per_block + d * v + v           # + lm_head kernel+bias


def comm_bytes_per_step(
    cfg: ModelConfig,
    batch: int,
    seq_len: int,
    mesh_shape: dict[str, int],
    parallel: str,
    pp_microbatches: int = 1,
) -> dict[str, float]:
    """Estimated per-device collective traffic for ONE training step, in
    bytes, from the active parallelism config — no profiler needed.

    Standard ring-collective accounting (each of the three terms is what
    the paper's DP/TP/PP comparison trades off):

    - ``dp_allreduce``: gradient all-reduce over the ``data`` axis —
      ``2·(d-1)/d · P`` bytes per device (reduce-scatter + all-gather),
      with gradients in ``param_dtype``. FSDP pays the same wire bytes
      re-phased (param all-gather fwd + bwd, grad reduce-scatter):
      ``3·(d-1)/d · P``.
    - ``tp_allreduce``: Megatron TP's two activation all-reduces in
      forward and two in backward per layer over the ``model`` axis, on
      ``(B, T, d_model)`` activations in ``compute_dtype``.
    - ``pp_p2p``: boundary-activation sends between adjacent stages —
      ``(stages-1)`` cuts crossed forward and backward by every
      microbatch.

    Combined DP×FSDP×TP meshes (``parallel == "fsdp"`` with ``model > 1``
    — configs/train_config_3d.yaml, ISSUE 12): the naive
    ``n_params / model`` per-device share over-divides, because TP only
    shards the matmul family (qkv/out/fc1/fc2 kernels + their
    column-parallel biases, lm_head) while LayerNorms, row-parallel
    biases, and the embeddings stay TP-replicated — and FSDP gathers /
    reduce-scatters each device's ACTUAL share. The 3d term therefore
    splits the tree: ``n_tp_sharded / model + n_tp_replicated``. Plain DP
    keeps the historical formula (committed audit baselines pin it).
    The estimate is transport-independent on purpose: the overlapped
    ring (ops/overlap_collectives.py) re-phases exactly these wire bytes
    under compute, it does not change them — which is what lets the
    census cross-check hold for both ``collectives:`` modes.

    Returns per-collective estimates plus their ``total``; all terms are
    0.0 for axes of size 1, so the dict is safe to emit unconditionally.
    """
    d_axis = max(mesh_shape.get("data", 1), 1)
    m_axis = max(mesh_shape.get("model", 1), 1)
    p_axis = max(mesh_shape.get("pipe", 1), 1)
    pbytes = _dtype_bytes(cfg.param_dtype)
    abytes = _dtype_bytes(cfg.compute_dtype)
    n_params = param_count(cfg)

    dp = 0.0
    if d_axis > 1:
        factor = 3.0 if parallel == "fsdp" else 2.0
        if parallel == "fsdp" and m_axis > 1:
            # DP×FSDP×TP: per-device share = TP-sharded params / model +
            # the TP-replicated remainder (each TP rank stores and
            # gathers its own full copy of those).
            n_tp = tp_sharded_param_count(cfg)
            local_params = (n_tp / m_axis + (n_params - n_tp)) / p_axis
        else:
            # Per-device parameter share: TP/PP already split the tree.
            local_params = n_params / (m_axis * p_axis)
        dp = factor * (d_axis - 1) / d_axis * local_params * pbytes

    tp = 0.0
    if m_axis > 1:
        act = batch * seq_len * cfg.d_model * abytes / d_axis  # per-device B shard
        tp = 4.0 * cfg.n_layers * 2.0 * (m_axis - 1) / m_axis * act

    pp = 0.0
    if p_axis > 1:
        micro = batch / max(pp_microbatches, 1) / d_axis
        act = micro * seq_len * cfg.d_model * abytes
        pp = 2.0 * (p_axis - 1) * pp_microbatches * act

    return {
        "dp_allreduce": dp,
        "tp_allreduce": tp,
        "pp_p2p": pp,
        "total": dp + tp + pp,
    }


def train_memory_bytes(
    cfg: ModelConfig,
    batch: int,
    seq_len: int,
    mesh_shape: dict[str, int],
    parallel: str,
    precision: str = "fp32",
) -> dict[str, float]:
    """Analytic per-device HBM budget for ONE training step, in bytes —
    the cross-check target of the graph auditor's static memory plan
    (``dtc_tpu/analysis/memory.py``) and the first metrics helper that
    accounts OPTIMIZER-STATE bytes at all (ROADMAP item 3: the all-fp32
    AdamW state is the dominant residency at scale; Rajbhandari et al.'s
    ZeRO accounting is the model here).

    Components, all per device (TP/FSDP split applied the same way
    :func:`comm_bytes_per_step` splits its dp term):

    - ``params``: the model's resident parameters in ``param_dtype``
      (bf16_mixed: 2 bytes — the policy stores bf16 params).
    - ``master``: fp32 master weights (bf16_mixed only; 0 under fp32 —
      the params ARE the masters). The honest accounting: bf16_mixed
      state is params 2 + master 4 + moments 8 = 14 B/param vs fp32's
      12 — the +2 master tax buys the halved param/grad bytes every
      fwd+bwd pass actually touches.
    - ``moments``: AdamW mu+nu, fp32 under both policies (2 x 4 bytes).
    - ``grads``: the transient gradient tree in ``param_dtype`` (bf16
      halves it — and it is also the DP/FSDP wire payload).
    - ``activations``: saved-for-backward estimate — per layer the
      residual/qkv/attn-out/proj/MLP intermediates (~10·d + 2·d_ff per
      token in ``compute_dtype``) plus, for dense attention, the fp32
      (B, H, T, T) probability tensor autodiff saves (flash recomputes
      it — the kernel's O(T) memory claim), plus the logits row. remat
      "block"/"mlp" drop the block/MLP share and keep residuals.
    - ``comm_buffers``: the collective landing buffers, taken as the
      wire-byte estimate (:func:`comm_bytes_per_step` total).
    - ``batch_io``: the token batch (x, y) in int32.

    Structural estimate, not a simulator: XLA fuses, rematerializes, and
    reuses buffers — the audit cross-check applies a wide warn-band and
    the committed baselines pin the measured numbers.
    """
    d_axis = max(mesh_shape.get("data", 1), 1)
    m_axis = max(mesh_shape.get("model", 1), 1)
    p_axis = max(mesh_shape.get("pipe", 1), 1)
    n = param_count(cfg)
    n_tp = tp_sharded_param_count(cfg)

    # Per-device parameter share: TP shards only the matmul family; FSDP
    # shards everything over "data"; PP splits layers.
    local = (n_tp / m_axis + (n - n_tp)) / p_axis
    if parallel == "fsdp" and d_axis > 1:
        local = local / d_axis

    pbytes = float(_dtype_bytes("bfloat16" if precision == "bf16_mixed"
                                else cfg.param_dtype))
    cbytes = float(_dtype_bytes(cfg.compute_dtype))
    params = local * pbytes
    master = local * 4.0 if precision == "bf16_mixed" else 0.0
    moments = local * 8.0
    grads = local * pbytes

    b_loc = batch / d_axis
    dm, ff = cfg.d_model, cfg.d_ff
    per_tok = (10.0 * dm + 2.0 * ff) * cbytes
    layer_acts = b_loc * seq_len * per_tok
    if cfg.attention == "dense":
        # Dense attention saves the fp32 (B, H, T, T) probs for backward.
        layer_acts += b_loc * cfg.n_heads * (seq_len ** 2) * 4.0
    n_layers = cfg.n_layers / p_axis
    if cfg.remat_mode in ("block", "block_save_flash"):
        # Block remat keeps one residual per layer + one block's working
        # set; model the residuals only (conservative floor).
        acts = n_layers * b_loc * seq_len * dm * cbytes + layer_acts
    elif cfg.remat_mode == "mlp":
        acts = n_layers * (layer_acts - b_loc * seq_len * 2.0 * ff * cbytes)
    else:
        acts = n_layers * layer_acts
    acts += b_loc * seq_len * cfg.padded_vocab_size * cbytes / m_axis  # logits
    comm = comm_bytes_per_step(
        cfg, batch, seq_len, mesh_shape, parallel
    )["total"]
    batch_io = 2.0 * b_loc * seq_len * 4.0
    total = params + master + moments + grads + acts + comm + batch_io
    return {
        "params": params,
        "master": master,
        "moments": moments,
        "grads": grads,
        "activations": acts,
        "comm_buffers": comm,
        "batch_io": batch_io,
        "total": total,
    }


def mfu(
    cfg: ModelConfig,
    batch: int,
    seq_len: int,
    step_time_s: float,
    n_chips: int,
    moe_basis: str = "hardware",
) -> float | None:
    """Model FLOPs utilization; None off-TPU or at zero step time.

    ``moe_basis`` selects the MoE FLOP accounting (dense models ignore
    it): "hardware" = :func:`moe_step_flops` (einsum-structural work,
    capacity slack + dispatch counted), "useful" =
    :func:`moe_step_flops_useful` (k·T routed tokens only — the
    dispatch-backend-independent A/B number the PERF.md MoE tables lead
    with).
    """
    peak = peak_flops_per_chip()
    if peak is None or step_time_s <= 0:
        return None
    if cfg.moe_experts > 0:
        fn = moe_step_flops_useful if moe_basis == "useful" else moe_step_flops
        flops = fn(cfg, batch, seq_len)
    else:
        flops = gpt_step_flops(cfg, batch, seq_len)
    return flops / (step_time_s * peak * n_chips)
