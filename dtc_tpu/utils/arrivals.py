"""Seeded open-loop arrival generation, shared by bench and pool.

Extracted from ``bench.py``'s serve/fleet rows (ISSUE 17): the seeded
Poisson arrival schedule and the seeded prompt set were duplicated
per-bench, and the pool's chaos spike needs the exact same request
material — one generator means a bench row, a pool smoke, and a chaos
drill all draw from the same distribution and a seed reproduces any of
them bit-for-bit.

The draw ORDER is part of the contract: arrivals first, then prompts,
from one ``np.random.RandomState(seed)`` — the order the benches have
always used, so extracting the helper changes no committed BENCH row.
"""

from __future__ import annotations

import numpy as np


def poisson_arrivals(
    rng: np.random.RandomState, n_requests: int, rps: float | None
) -> np.ndarray:
    """Cumulative arrival offsets (seconds from window start) for an
    open-loop Poisson process at ``rps`` requests/second. ``rps=None``
    is the closed-loop degenerate case: everything arrives at t=0."""
    if rps is None:
        return np.zeros(n_requests)
    return np.cumsum(rng.exponential(1.0 / rps, size=n_requests))


def seeded_prompts(
    rng: np.random.RandomState, n_requests: int, prompt_len: int,
    vocab_size: int,
) -> list[list[int]]:
    """``n_requests`` uniform-random token prompts of ``prompt_len``."""
    return [
        rng.randint(0, vocab_size, size=prompt_len).tolist()
        for _ in range(n_requests)
    ]


def arrival_schedule(
    seed: int, n_requests: int, prompt_len: int, vocab_size: int,
    rps: float | None,
) -> tuple[np.ndarray, list[list[int]]]:
    """The benches' full request material: ``(arrivals, prompts)`` from
    one seeded RNG (arrivals drawn first — see module docstring)."""
    rng = np.random.RandomState(seed)
    arrivals = poisson_arrivals(rng, n_requests, rps)
    prompts = seeded_prompts(rng, n_requests, prompt_len, vocab_size)
    return arrivals, prompts
