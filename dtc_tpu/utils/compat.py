"""jax version compatibility shims.

The codebase targets the current jax API; this module maps the few
surfaces that moved between 0.4.x and 0.5+ so the same call sites run on
either. Keep it tiny — anything that needs real per-version logic belongs
at its call site with a comment, not here.
"""

from __future__ import annotations

import inspect
from typing import Any

import jax


def _resolve_shard_map():
    """Pick the shard_map entry point and its kwarg dialect by SIGNATURE,
    not by where the function lives: there are three eras — experimental
    with ``auto``/``check_rep`` (0.4.x), top-level ``jax.shard_map`` still
    with ``auto``/``check_rep``, and top-level with ``axis_names``/
    ``check_vma``. Feature-detecting only the attribute would pass the
    newest kwargs to the middle era and TypeError on every call."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    params = inspect.signature(fn).parameters
    modern = "axis_names" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    )
    return fn, modern


_SHARD_MAP, _MODERN_KWARGS = _resolve_shard_map()


def shard_map(
    f,
    mesh,
    in_specs,
    out_specs,
    axis_names: set[str] | None = None,
    check_vma: bool = True,
) -> Any:
    """``jax.shard_map`` with the modern signature, on any supported jax.

    Modern dialect: ``axis_names`` = the axes the region is manual over,
    ``check_vma``. Legacy dialect spells the same contract ``auto`` (the
    *complement* of the manual axes) and ``check_rep``.
    """
    if _MODERN_KWARGS:
        kwargs: dict[str, Any] = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
    else:
        kwargs = {"check_rep": check_vma}
        if axis_names is not None:
            kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
