"""Nearest-rank percentile — THE percentile definition shared by bench
rows, the trace analyzer, and the quantile-histogram parity tests.

Extracted from bench.py's private ``_pct`` (ISSUE 7 satellite): three
call sites had started growing their own copies, and the registry
histogram's bucketed p50/p99 needs one exact oracle to be tested
against. Nearest-rank (no interpolation) is deliberate: for the small
samples serving benches produce (tens of requests), interpolated
percentiles manufacture values nobody measured.
"""

from __future__ import annotations

import math
from typing import Iterable


def round_opt(v: float | None, ndigits: int = 4) -> float | None:
    """Round a possibly-``None`` metric — the one rounding rule every
    percentile surface shares (the mixed-fleet reducer, the router's
    fleet summary, bench rows), so a policy change lands once."""
    return None if v is None else round(v, ndigits)


def nearest_rank(vals: Iterable[float], q: float) -> float | None:
    """Nearest-rank percentile of ``vals`` at quantile ``q`` in [0, 1].

    Returns ``None`` for an empty sample. ``q=0`` is the minimum,
    ``q=1`` the maximum; with one sample every quantile is that sample.
    The returned value is always an element of ``vals`` (never
    interpolated).
    """
    vals = sorted(vals)
    if not vals:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    # Nearest-rank: the ceil(q*n)-th smallest (1-based), clamped so q=0
    # yields the minimum instead of an out-of-range rank 0.
    rank = max(1, math.ceil(q * len(vals)))
    return vals[rank - 1]
