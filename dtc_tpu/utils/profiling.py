"""Back-compat shim: :class:`StepWindowProfiler` moved into the telemetry
subsystem (``dtc_tpu/obs/profiling.py``), hardened to warn-and-disable on
an already-active profiler session or an unwritable log dir instead of
killing the run. Import from :mod:`dtc_tpu.obs` in new code."""

from __future__ import annotations

from dtc_tpu.obs.profiling import StepWindowProfiler

__all__ = ["StepWindowProfiler"]
