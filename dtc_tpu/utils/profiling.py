"""Profiler trace capture around a training-step window.

Reference has wall-clock timing only (SURVEY.md §5). This wraps
``jax.profiler`` so a config-selected step window [start, stop) is captured
to a TensorBoard/XProf trace directory.
"""

from __future__ import annotations

import jax


class StepWindowProfiler:
    def __init__(self, start_step: int, stop_step: int, log_dir: str):
        self.start = start_step
        self.stop = stop_step
        self.log_dir = log_dir
        self._active = False
        self.enabled = stop_step > start_step

    def step(self, step: int) -> None:
        if not self.enabled:
            return
        if step == self.start and not self._active:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif step == self.stop and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
