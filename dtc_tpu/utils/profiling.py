"""Back-compat shim: :class:`StepWindowProfiler` moved into the telemetry
subsystem (``dtc_tpu/obs/profiling.py``), hardened to warn-and-disable on
an already-active profiler session or an unwritable log dir instead of
killing the run. Import from :mod:`dtc_tpu.obs` in new code.

Importing this module emits a one-time :class:`DeprecationWarning`
(module objects are cached, so the warning fires once per process) —
ISSUE 8 satellite: the README/config docs no longer reference this path,
and a future PR can delete it once nothing trips the warning.
"""

from __future__ import annotations

import warnings

from dtc_tpu.obs.profiling import StepWindowProfiler

warnings.warn(
    "dtc_tpu.utils.profiling is deprecated; StepWindowProfiler lives in "
    "dtc_tpu.obs.profiling (import from dtc_tpu.obs)",
    DeprecationWarning,
    stacklevel=2,
)

__all__ = ["StepWindowProfiler"]
