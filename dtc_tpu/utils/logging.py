"""Run logging.

CSV schema is byte-compatible with the reference's ``log.csv``
(columns ``step, elapsed_time, loss`` with cumulative elapsed_time,
`/root/reference/train/train.py:98-102`) so the reference's plot tooling —
and our ``plot.py`` — reads either. Unlike the reference (which buffers
everything in lists and writes once at exit), rows are appended
incrementally: a crash at step 4900 keeps 4899 rows.
"""

from __future__ import annotations

import csv
import os
from typing import IO


class CSVLogger:
    def __init__(self, path: str, fieldnames: tuple[str, ...] = ("step", "elapsed_time", "loss")):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._fieldnames = fieldnames
        self._fh: IO | None = open(path, "w", newline="")
        self._writer = csv.DictWriter(self._fh, fieldnames=fieldnames)
        self._writer.writeheader()

    def log(self, **row) -> None:
        """Write one row. Keys missing from ``fieldnames`` fill blank;
        unknown keys raise immediately with the valid set — instead of
        either ``csv.DictWriter``'s opaque ``ValueError`` or (worse) the
        silent drop that loses a column for an entire run."""
        if self._fh is None:
            raise ValueError(f"CSVLogger({self._path!r}) is closed")
        unknown = set(row) - set(self._fieldnames)
        if unknown:
            raise ValueError(
                f"CSVLogger({self._path!r}): unknown field(s) {sorted(unknown)}; "
                f"valid fields: {list(self._fieldnames)}"
            )
        self._writer.writerow({k: row.get(k, "") for k in self._fieldnames})

    def flush(self) -> None:
        if self._fh:
            self._fh.flush()

    def close(self) -> None:
        """Idempotent — safe to call from both a normal exit path and a
        ``finally`` block."""
        if self._fh:
            self._fh.close()
            self._fh = None
