"""Multi-host (pod) support.

The reference is strictly single-process — no ``jax.distributed.initialize``
anywhere (SURVEY.md §2.2 "Multi-host"). Here multi-host is first-class:
initialize once at entry, then every process builds the same global mesh and
feeds its local shard of the batch (see ``data/prefetch.py``); logging and
checkpoint writes happen on process 0 only.
"""

from __future__ import annotations

import os

import jax


def maybe_initialize_distributed(multihost: bool) -> None:
    """Initialize the JAX distributed runtime when running multi-process.

    Safe to call unconditionally: no-ops unless ``multihost`` is set or the
    standard cluster env (JAX_COORDINATOR_ADDRESS / TPU pod metadata) marks
    this as a multi-process run.
    """
    if jax.process_count() > 1:
        return  # already initialized
    env_says_cluster = bool(
        os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get("COORDINATOR_ADDRESS")
    )
    if not (multihost or env_says_cluster):
        return
    try:
        jax.distributed.initialize()
    except Exception as e:  # single-process fallback keeps local runs working
        print(f"[dtc_tpu] jax.distributed.initialize() skipped: {e}")


def is_lead_process() -> bool:
    return jax.process_index() == 0
