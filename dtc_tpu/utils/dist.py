"""Multi-host (pod) support.

The reference is strictly single-process — no ``jax.distributed.initialize``
anywhere (SURVEY.md §2.2 "Multi-host"). Here multi-host is first-class:
initialize once at entry — BEFORE any other JAX API touches the backend —
then every process builds the same global mesh and feeds its local shard of
the batch (see ``data/prefetch.py``); logging and checkpoint writes happen
on process 0 only.
"""

from __future__ import annotations

import os

_initialized = False

#: Env knob overriding the coordinator-init timeout (seconds). Takes
#: precedence over TrainConfig.coordinator_timeout_s so an operator can
#: shorten a stuck pod's hang without editing configs.
TIMEOUT_ENV = "DTC_COORDINATOR_TIMEOUT_S"


def _resolve_timeout(timeout_s: int | None) -> int | None:
    """Effective coordinator timeout: env knob > config > jax default.
    ``0`` means "jax's default" in BOTH the env knob and the config (so an
    operator can unset a debugging override without unexporting the var);
    negative or non-integer values are ignored with a warning."""
    env = os.environ.get(TIMEOUT_ENV)
    if env:
        try:
            v = int(env)
        except ValueError:
            v = None
        if v is not None and v > 0:
            return v
        if v == 0:
            return None  # explicit "use jax's default", overriding config
        print(
            f"[dtc_tpu] WARNING: ignoring invalid {TIMEOUT_ENV}={env!r} "
            "(want an integer >= 0; 0 = jax's default)"
        )
    if timeout_s and timeout_s > 0:
        return timeout_s
    return None  # jax's default (300s)


def maybe_initialize_distributed(
    multihost: bool, timeout_s: int | None = None
) -> None:
    """Initialize the JAX distributed runtime when running multi-process.

    MUST be the first JAX-touching call of the process: probing any backend
    API (``jax.process_count()``, ``jax.devices()``, …) first initializes
    the local backend and makes ``jax.distributed.initialize()`` raise on a
    real pod. The gate is therefore env/config only — no JAX probes.

    ``timeout_s`` (config ``coordinator_timeout_s``; env
    ``DTC_COORDINATOR_TIMEOUT_S`` overrides) bounds how long a worker waits
    for the coordinator before failing — SURVEY §5: without it a typo'd
    coordinator address hangs every host for jax's full default and the
    eventual error never names the likely causes.

    Raises on failure when multi-host was explicitly requested (config):
    a pod where every host silently falls back to independent
    single-process training is far worse than a crash. When only the
    environment hints at a cluster (a coordinator address left set by
    some other tool), failure degrades to a warning + single-process —
    the config didn't ask for multi-host.
    """
    global _initialized
    if _initialized:
        return
    env_says_cluster = bool(
        os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get("COORDINATOR_ADDRESS")
    )
    if not (multihost or env_says_cluster):
        return
    import jax

    timeout = _resolve_timeout(timeout_s)
    kwargs = {} if timeout is None else {"initialization_timeout": timeout}
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # The embedding program (a launcher, a test harness) may have
        # initialized the distributed runtime itself — that is success,
        # not failure.
        if "already initialized" not in str(e).lower():
            if multihost:
                raise RuntimeError(_init_failure_message(timeout)) from e
            raise
    except Exception as e:
        if multihost:
            raise RuntimeError(_init_failure_message(timeout)) from e
        print(
            "[dtc_tpu] WARNING: cluster env vars set but "
            "jax.distributed.initialize() failed; continuing single-process"
        )
        return
    _initialized = True


def _init_failure_message(timeout: int | None) -> str:
    coord = (
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
        or "<auto-detected>"
    )
    return (
        "multi-host initialization failed "
        f"(coordinator={coord}, timeout={timeout or 'jax default (300s)'}s). "
        "Common causes: wrong/unreachable coordinator address, a process "
        "count mismatch (a host never joined), or a firewall blocking the "
        "coordinator port. Set coordinator_timeout_s in the train config "
        f"or {TIMEOUT_ENV} to fail faster while debugging."
    )


def is_lead_process() -> bool:
    import jax

    return jax.process_index() == 0
