"""Multi-host (pod) support.

The reference is strictly single-process — no ``jax.distributed.initialize``
anywhere (SURVEY.md §2.2 "Multi-host"). Here multi-host is first-class:
initialize once at entry — BEFORE any other JAX API touches the backend —
then every process builds the same global mesh and feeds its local shard of
the batch (see ``data/prefetch.py``); logging and checkpoint writes happen
on process 0 only.
"""

from __future__ import annotations

import os

_initialized = False


def maybe_initialize_distributed(multihost: bool) -> None:
    """Initialize the JAX distributed runtime when running multi-process.

    MUST be the first JAX-touching call of the process: probing any backend
    API (``jax.process_count()``, ``jax.devices()``, …) first initializes
    the local backend and makes ``jax.distributed.initialize()`` raise on a
    real pod. The gate is therefore env/config only — no JAX probes.

    Raises on failure when multi-host was explicitly requested (config):
    a pod where every host silently falls back to independent
    single-process training is far worse than a crash. When only the
    environment hints at a cluster (a coordinator address left set by
    some other tool), failure degrades to a warning + single-process —
    the config didn't ask for multi-host.
    """
    global _initialized
    if _initialized:
        return
    env_says_cluster = bool(
        os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get("COORDINATOR_ADDRESS")
    )
    if not (multihost or env_says_cluster):
        return
    import jax

    try:
        jax.distributed.initialize()
    except RuntimeError as e:
        # The embedding program (a launcher, a test harness) may have
        # initialized the distributed runtime itself — that is success,
        # not failure.
        if "already initialized" not in str(e).lower():
            raise
    except Exception:
        if multihost:
            raise
        print(
            "[dtc_tpu] WARNING: cluster env vars set but "
            "jax.distributed.initialize() failed; continuing single-process"
        )
        return
    _initialized = True


def is_lead_process() -> bool:
    import jax

    return jax.process_index() == 0
