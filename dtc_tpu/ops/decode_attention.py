"""Fused single-query decode attention — one Pallas launch per layer.

The decode step's attention used to be a pile of small XLA ops per layer
(score einsum over the full cache, iota mask build, fp32 softmax, value
einsum — each a separate kernel launch inside the token scan), which is
what made decode launch-bound at ~4 ms/token (PERF.md round 5: an
fp32-vs-bf16 weight A/B moved nothing, so the cost is dispatch, not
bandwidth). This kernel folds the whole per-layer attention read into ONE
launch over the model-native packed KV layout:

- **Layout**: the cache is ``(B, S, H·D)`` — exactly the byte layout the
  qkv projections produce and the packed training kernels consume
  (ops/flash_attention.py round 3). Heads group ``g`` per lane block
  (``128 // D`` when that divides the head count; otherwise one block of
  all ``H·D`` lanes — Mosaic pads internally, same as the transpose
  kernels keep head_dim native). The per-head slice happens INSIDE VMEM,
  a register shuffle, never an HBM pass.
- **Masking**: the query is ONE new token at position ``start``; cache
  columns ``col <= start`` are valid (the current token's k/v are written
  at ``start`` before attention — models/gpt.py). ``start`` rides in as
  an SMEM scalar so the mask is an in-register iota compare, and KV
  blocks entirely beyond the frontier are predicated out (their compute
  never runs; at S=512 the whole cache is one tile anyway).
- **Numerics**: fp32 scores/softmax regardless of input dtype, the same
  ``exp(s - max)`` one-pass softmax as the training kernels' single-tile
  path — the XLA oracle (ops/attention.py ``decode_attention``) remains
  the parity reference, asserted token-exact in tests/test_generate.py.

The kernel handles ONLY the single-token step (``T_new == 1``); prefill
(multi-token) goes through the oracle — it runs once per sequence, the
scan body runs per token.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Shared with the training kernels; importing flash_attention also installs
# the jax-0.4.x pltpu.CompilerParams alias every pallas_call below relies on.
from dtc_tpu.ops.flash_attention import _interpret, _packed_group

NEG_INF = -1e9  # matches ops/attention.py
_LANES = 128

#: Longest cache held as ONE KV tile per (batch, group) program. The tile
#: is (S, lane_block) in the input dtype — 2 MB bf16 at S=4096/128 lanes,
#: comfortably VMEM — and a single tile needs no online-softmax scratch.
#: Past this the blocked kernel walks the cache in _DECODE_BLOCK_S chunks
#: and skips the compute for blocks beyond the write frontier (Pallas
#: still pipelines every block's copy — the skip saves VPU/MXU work,
#: not HBM reads).
_DECODE_MAX_SINGLE_S = 4096
_DECODE_BLOCK_S = 512


#: Smallest per-head amplitude treated as non-zero by the int8 quantizer:
#: an all-zero head (fresh cache rows, padding) would otherwise divide by
#: zero. round(0 / floor) == 0, so zero vectors round-trip exactly.
KV_SCALE_FLOOR = 1e-8


def quantize_kv(x: jax.Array, n_heads: int) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-(position, head) int8 quantization of a packed
    ``(..., H·D)`` k/v tensor.

    Each head's D-vector gets its own fp32 scale ``max(|x|)/127`` (the
    "per-head-block" granularity: one scale per lane group the decode
    kernels already slice by), so a large-magnitude head cannot crush a
    small one's resolution — the standard KV-quantization failure mode
    KIVI/KVQuant address with finer groups. Returns ``(int8 payload of
    x's shape, fp32 scales (..., H))``. Round-trip error is bounded by
    ``scale/2 = max(|x|)/254`` per element (pinned in
    tests/test_decode_fused.py). The in-kernel quantizers
    (ops/decode_fused.py) replicate these exact fp32 ops so the compiled
    paths cannot drift from this reference."""
    *lead, hd = x.shape
    d = hd // n_heads
    xr = x.reshape(tuple(lead) + (n_heads, d)).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xr), axis=-1)
    scale = jnp.maximum(amax, KV_SCALE_FLOOR) / 127.0
    q = jnp.clip(jnp.round(xr / scale[..., None]), -127.0, 127.0)
    return q.astype(jnp.int8).reshape(x.shape), scale


def dequantize_kv(
    q: jax.Array, scale: jax.Array, n_heads: int, dtype
) -> jax.Array:
    """Inverse of :func:`quantize_kv`: ``(..., H·D)`` int8 payload +
    ``(..., H)`` fp32 scales -> ``dtype`` values (the cache's compute
    view). The XLA-oracle decode path uses this whole-cache dequant as
    the parity reference; the kernels dequantize the same arithmetic
    in-register, per head slice, without materializing this tensor."""
    *lead, hd = q.shape
    d = hd // n_heads
    qr = q.reshape(tuple(lead) + (n_heads, d)).astype(jnp.float32)
    return (qr * scale[..., None]).reshape(q.shape).astype(dtype)


def _group(d: int, h: int) -> tuple[int, int]:
    """(heads per lane block, lane block width).

    128-lane groups per the training kernels' packed grouping rule
    (flash_attention._packed_group, shared so the two paths can't
    diverge); otherwise one block holding all H·D lanes — correct for
    any shape (the tiny CPU-test models), lane-padded by Mosaic."""
    g = _packed_group(d, h)
    return (g, _LANES) if g is not None else (h, h * d)


def supports(s: int) -> bool:
    """Whether the fused kernel handles a cache of length ``s``.

    The single-tile branch additionally clears the shared VMEM planner
    (ops/vmem.py — every ``supports_*`` gate consults it, lint-enforced
    by analysis/kernels.py). At the 14 MiB budget every cache under the
    structural ``_DECODE_MAX_SINGLE_S`` bound fits — pinned in
    tests/test_kernel_audit.py so this consult can never silently
    change routing."""
    from dtc_tpu.ops import vmem

    if s <= _DECODE_MAX_SINGLE_S and vmem.decode_single_tile_fits(s):
        return True
    return s % _DECODE_BLOCK_S == 0


def _head_kv(kt, vt, ks, vs, gg, d, out_dtype):
    """This lane block's head ``gg`` K/V tiles, dequantized to
    ``out_dtype`` when the cache is int8 (``ks``/``vs`` are the (s, g)
    per-head fp32 scale columns; None = float cache, native slices).
    The dequant is a register-resident multiply — the int8 payload is
    what crossed HBM."""
    sl = slice(gg * d, (gg + 1) * d)
    k_h, v_h = kt[:, sl], vt[:, sl]
    if ks is not None:
        k_h = (k_h.astype(jnp.float32) * ks[:, gg:gg + 1]).astype(out_dtype)
        v_h = (v_h.astype(jnp.float32) * vs[:, gg:gg + 1]).astype(out_dtype)
    elif k_h.dtype != out_dtype:
        # Down-dtyped float cache (kv_cache_dtype: bf16 under fp32
        # compute): promote to q's dtype for the dots, exactly as the
        # XLA oracle's einsum promotion does.
        k_h, v_h = k_h.astype(out_dtype), v_h.astype(out_dtype)
    return k_h, v_h


def _decode_kernel_single(start_ref, q_ref, k_ref, v_ref, *rest,
                          s, g, d, scale, per_row=False, quant=False):
    """Whole-cache-in-one-tile decode step for the g heads of this lane
    block: per head, a (1, S) score row, masked to the frontier, one-pass
    softmax, and a (1, D) output row. No scratch, no rescale passes.
    ``per_row``: the SMEM frontier is (B,) — one write position per batch
    row (the serving slots) — read at this program's batch index.
    ``quant``: the cache is int8 with per-(position, head) fp32 scales
    riding as two extra inputs; dequant happens per head slice in
    registers (the HBM read is the 1-byte payload)."""
    if quant:
        ks_ref, vs_ref, o_ref = rest
        ks, vs = ks_ref[0], vs_ref[0]              # (s, g) fp32
    else:
        (o_ref,) = rest
        ks = vs = None
    start = start_ref[pl.program_id(0)] if per_row else start_ref[0]
    qt = q_ref[0]                                  # (1, g*d)
    kt, vt = k_ref[0], v_ref[0]                    # (s, g*d)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    mask = col <= start
    for gg in range(g):
        sl = slice(gg * d, (gg + 1) * d)
        k_h, v_h = _head_kv(kt, vt, ks, vs, gg, d, qt.dtype)
        sc = jax.lax.dot_general(
            qt[:, sl] * scale, k_h, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # (1, s) fp32
        sc = jnp.where(mask, sc, NEG_INF)
        m = jnp.max(sc, axis=-1, keepdims=True)
        p = jnp.exp(sc - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        acc = jax.lax.dot_general(
            p.astype(v_h.dtype), v_h, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # (1, d)
        o_ref[0, :, sl] = (acc / l).astype(o_ref.dtype)


def _decode_kernel_blocked(start_ref, q_ref, k_ref, v_ref, *rest,
                           block_s, g, d, scale, per_row=False, quant=False):
    """Online-softmax decode step over KV blocks (caches past the
    single-tile bound). Blocks whose first column is beyond the write
    frontier are predicated out — a 32k-slot cache decoded at position
    600 COMPUTES two blocks, not 64, though the pipeline still copies in
    all 64 (compute skip, not a DMA skip). Scratch rows 0
    hold head gg's running stats in column gg (the packed-kernel
    convention); the output is written once at the last block.
    ``quant`` as in the single-tile kernel: int8 payload + per-head
    scale blocks, dequantized per head slice in registers."""
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
        ks, vs = ks_ref[0], vs_ref[0]              # (block_s, g) fp32
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        ks = vs = None
    j = pl.program_id(2)
    start = start_ref[pl.program_id(0)] if per_row else start_ref[0]

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(j * block_s <= start)
    def _():
        qt = q_ref[0]                              # (1, g*d)
        kt, vt = k_ref[0], v_ref[0]                # (block_s, g*d)
        col = j * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_s), 1
        )
        mask = col <= start
        for gg in range(g):
            sl = slice(gg * d, (gg + 1) * d)
            cl = slice(gg, gg + 1)
            k_h, v_h = _head_kv(kt, vt, ks, vs, gg, d, qt.dtype)
            sc = jax.lax.dot_general(
                qt[:, sl] * scale, k_h, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            sc = jnp.where(mask, sc, NEG_INF)
            m_prev = m_scr[:1, cl]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(sc - m_new)
            l_scr[:1, cl] = alpha * l_scr[:1, cl] + jnp.sum(
                p, axis=-1, keepdims=True
            )
            acc_scr[:1, sl] = acc_scr[:1, sl] * alpha + jax.lax.dot_general(
                p.astype(v_h.dtype), v_h, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_scr[:1, cl] = m_new

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        for gg in range(g):
            sl = slice(gg * d, (gg + 1) * d)
            cl = slice(gg, gg + 1)
            o_ref[0, :, sl] = (acc_scr[:1, sl] / l_scr[:1, cl]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("h", "d"))
def fused_decode_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, start: jax.Array,
    *, h: int, d: int,
    k_scale: jax.Array | None = None, v_scale: jax.Array | None = None,
) -> jax.Array:
    """Single-launch decode attention on the packed KV layout.

    ``q`` is ``(B, 1, H·D)`` — the one new token, model-native packed;
    ``k``/``v`` are the FULL cache ``(B, S, H·D)`` with valid columns
    ``<= start`` (the write frontier, the new token's position). ``start``
    is a scalar — one frontier for the whole batch, the ``generate`` path
    — or a ``(B,)`` vector of per-row frontiers (the serving runtime's
    continuous-batching slots; it rides in SMEM either way and each
    (batch, group) program reads its own row's scalar). With an int8
    cache (``kv_cache_dtype: int8``) ``k``/``v`` are the 1-byte payload
    and ``k_scale``/``v_scale`` the ``(B, S, H)`` fp32 per-(position,
    head) scales (:func:`quantize_kv`); dequant runs per head slice in
    registers, so the HBM traffic is the quantized bytes. Returns
    ``(B, 1, H·D)`` in q's dtype. Numerics match
    :func:`dtc_tpu.ops.attention.decode_attention` (fp32 softmax, -1e9
    mask, whole-cache dequant for int8) to fp roundoff; token-level
    decisions are exact in practice and asserted in
    tests/test_generate.py + tests/test_decode_fused.py.
    """
    b, t, hd = q.shape
    s = k.shape[1]
    if t != 1:
        raise ValueError(f"fused decode attention is single-query; got T={t}")
    if hd != h * d:
        raise ValueError(f"packed width {hd} != n_heads*head_dim {h}*{d}")
    if not supports(s):
        raise ValueError(
            f"cache length {s} unsupported (> {_DECODE_MAX_SINGLE_S} and not "
            f"a multiple of {_DECODE_BLOCK_S}); use the xla decode path"
        )
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    g, lb = _group(d, h)
    hg = hd // lb
    scale = float(d ** -0.5)
    start = jnp.asarray(start, jnp.int32)
    per_row = start.ndim == 1 and start.shape[0] == b and b > 1
    if not per_row:
        start = start.reshape((1,))

    qspec = pl.BlockSpec((1, 1, lb), lambda bi, gi, *_: (bi, 0, gi))
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    args = (start, q, k, v) + ((k_scale, v_scale) if quant else ())
    if s <= _DECODE_MAX_SINGLE_S:
        kvspec = pl.BlockSpec((1, s, lb), lambda bi, gi: (bi, 0, gi))
        # Scale blocks mirror the payload blocks one column per head: the
        # lane group [gi·g, gi·g+g) reads scale columns [gi·g, gi·g+g).
        scspec = pl.BlockSpec((1, s, g), lambda bi, gi: (bi, 0, gi))
        return pl.pallas_call(
            functools.partial(
                _decode_kernel_single, s=s, g=g, d=d, scale=scale,
                per_row=per_row, quant=quant,
            ),
            grid=(b, hg),
            in_specs=[
                sspec,
                pl.BlockSpec((1, 1, lb), lambda bi, gi: (bi, 0, gi)),
                kvspec,
                kvspec,
            ] + ([scspec, scspec] if quant else []),
            out_specs=pl.BlockSpec((1, 1, lb), lambda bi, gi: (bi, 0, gi)),
            out_shape=jax.ShapeDtypeStruct((b, 1, hd), q.dtype),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel"),
            ),
            interpret=_interpret(),
        )(*args)

    nkv = s // _DECODE_BLOCK_S
    kvspec = pl.BlockSpec((1, _DECODE_BLOCK_S, lb), lambda bi, gi, j: (bi, j, gi))
    scspec = pl.BlockSpec((1, _DECODE_BLOCK_S, g), lambda bi, gi, j: (bi, j, gi))
    return pl.pallas_call(
        functools.partial(
            _decode_kernel_blocked, block_s=_DECODE_BLOCK_S, g=g, d=d,
            scale=scale, per_row=per_row, quant=quant,
        ),
        grid=(b, hg, nkv),
        in_specs=[sspec, qspec, kvspec, kvspec]
        + ([scspec, scspec] if quant else []),
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, 1, hd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((8, _LANES), jnp.float32),  # running max (row 0)
            pltpu.VMEM((8, _LANES), jnp.float32),  # running sum (row 0)
            pltpu.VMEM((8, lb), jnp.float32),      # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(*args)
