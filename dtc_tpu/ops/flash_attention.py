"""Blockwise causal flash attention — Pallas TPU kernels, custom VJP.

Replaces the reference's O(T²)-memory einsum attention, which materialises
the full ``(B, H, T, T)`` score tensor in fp32
(`/root/reference/model/CausalSelfAttention.py:34-42`). Here scores only
ever exist one ``(block_q, block_kv)`` VMEM tile at a time:

- **Forward**: online softmax (running max ``m``, running sum ``l``) over KV
  blocks; the grid's innermost dimension walks KV blocks sequentially so the
  running statistics live in VMEM scratch across iterations. Emits the
  logsumexp alongside the output for the backward pass.
- **Backward**: flash-attention-2 style two-kernel split — one kernel
  accumulates dQ (grid walks KV innermost), one accumulates dK/dV (grid
  walks Q innermost) — each recomputing ``p = exp(s - lse)`` blockwise from
  the saved logsumexp instead of storing attention weights.
- Causal structure is exploited twice: blocks strictly above the diagonal
  are predicated out entirely (``@pl.when``), and diagonal-straddling blocks
  apply an iota position mask.

HBM-layout notes (what made this fast on a v5e):

- head_dim stays NATIVE in HBM (the flagship's 32); tiles are laid out by
  Mosaic with internal lane padding in VMEM only. An earlier version
  zero-padded q/k/v to the 128-lane width in HBM — 4× the memory traffic of
  the whole attention layer, all zeros.
- lse / delta travel as compact ``(B, H, T)`` arrays (block ``(1, 1,
  block_q)``), not lane-broadcast ``(…, 128)`` buffers (128× traffic).
- Scores/statistics are fp32 on the MXU/VPU regardless of input dtype;
  q/k/v tiles stay in their input dtype (bf16 in the mixed-precision path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # pragma: no cover — jax 0.4.x name
    pltpu.CompilerParams = pltpu.TPUCompilerParams

NEG_INF = -1e9  # matches the reference's additive mask value (ops/attention.py)
_LANES = 128  # TPU lane width (kept for stat-scratch shapes)

#: Longest sequence routed to the FUSED packed backward, which accumulates
#: dk/dv in full-T (T, 128) fp32 VMEM scratches — ~8 MB of scratch + output
#: blocks at T=4096 (measured working on a v5e); doubling T again exceeds a
#: core's VMEM. Past this, the packed SPLIT dq/dkv kernels (scratch
#: O(block), 7 tile matmuls vs the fused 5) take over — still packed
#: layout, any T.
_PACKED_MAX_T = 4096


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _mask(i, j, block_q, block_kv):
    """Causal mask for the (block_q, block_kv) tile at grid position (i, j):
    True where kv position <= q position (global coordinates)."""
    t = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
    s = j * block_kv + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
    return s <= t


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel_single(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q, block_kv):
    """One-pass forward for nkv == 1 (whole KV in one tile — the flagship's
    T=512 case). Attention at small head_dim is VPU-bound, so this skips the
    online-softmax machinery entirely: no running stats, no rescale pass, no
    scratch broadcasts. q arrives pre-scaled (see flash_causal_attention)."""
    i = pl.program_id(2)
    q = q_ref[0, 0]                          # (block_q, d), pre-scaled
    k = k_ref[0, 0]                          # (block_kv, d)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    s = jnp.where(_mask(i, 0, block_q, block_kv), s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, block_q, block_kv):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: the KV block is relevant iff its first position <= the Q
    # block's last position. Blocks strictly above the diagonal are skipped.
    @pl.when(j * block_kv <= i * block_q + block_q - 1)
    def _():
        q = q_ref[0, 0]                     # (block_q, d)
        k = k_ref[0, 0]                     # (block_kv, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                    # (block_q, block_kv) fp32; q pre-scaled
        s = jnp.where(_mask(i, j, block_q, block_kv), s, NEG_INF)

        m_prev = m_scr[:, :1]                # (block_q, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)      # rescale factor for old stats
        p = jnp.exp(s - m_new)               # (block_q, block_kv)

        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == pl.num_programs(3) - 1)
    def _():
        l = l_scr[:, :1]
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # logsumexp per q row; every row has >= 1 unmasked key (its own
        # position) so l > 0 always. Compact (block_q, 1) store.
        lse_ref[0, 0] = m_scr[:, :1] + jnp.log(l_scr[:, :1])


def _fwd_call(q, k, v, block_q, block_kv):
    b, h, t, d = q.shape
    nq, nkv = t // block_q, t // block_kv
    if nkv == 1:
        # Whole KV fits one tile: one-pass kernel, no online-softmax scratch.
        qspec3 = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0))
        kvspec3 = pl.BlockSpec((1, 1, block_kv, d), lambda bi, hi, i: (bi, hi, 0, 0))
        return pl.pallas_call(
            functools.partial(_fwd_kernel_single, block_q=block_q, block_kv=block_kv),
            grid=(b, h, nq),
            in_specs=[qspec3, kvspec3, kvspec3],
            out_specs=[
                pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, i: (bi, hi, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
                jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel"),
            ),
            interpret=_interpret(),
        )(q, k, v)
    grid = (b, h, nq, nkv)
    qspec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i, j: (bi, hi, i, 0))
    kvspec = pl.BlockSpec((1, 1, block_kv, d), lambda bi, hi, i, j: (bi, hi, j, 0))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_q=block_q, block_kv=block_kv),
        grid=grid,
        in_specs=[qspec, kvspec, kvspec],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i, j: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, i, j: (bi, hi, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum l
            pltpu.VMEM((block_q, d), jnp.float32),       # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward — fused single-block kernel (nq == nkv == 1)
# ---------------------------------------------------------------------------


def _bwd_kernel_single(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, dk_ref, dv_ref, *, block_q, block_kv):
    """Fused backward for the single-tile case: one program holds the whole
    (T, T) score tile for its (batch, head), so p is recomputed ONCE and all
    three gradients come out of the same pass — the split dq/dkv kernels
    would recompute s/p twice and double the VPU work."""
    q, do = q_ref[0, 0], do_ref[0, 0]
    k, v = k_ref[0, 0], v_ref[0, 0]
    p, ds = _p_ds(q, k, v, do, lse_ref[0, 0], delta_ref[0, 0],
                  0, 0, block_q, block_kv)
    dq_ref[0, 0] = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dq_ref.dtype)
    dk_ref[0, 0] = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dk_ref.dtype)
    dv_ref[0, 0] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# backward — dq kernel (grid walks KV innermost, dq accumulates in scratch)
# ---------------------------------------------------------------------------


def _p_ds(q, k, v, do, lse, delta, i, j, block_q, block_kv):
    """Shared backward tile math: recomputed probabilities p and the score
    gradient ds = p * (dp - delta), both (block_q, block_kv) fp32.

    q arrives pre-scaled, so no scale factor appears anywhere: the VJP of the
    outer ``q * scale`` restores dq's factor automatically, and dk's factor
    rides in through the scaled q itself. ``lse``/``delta`` are (block_q, 1)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    p = jnp.exp(s - lse)
    p = jnp.where(_mask(i, j, block_q, block_kv), p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta)
    return p, ds


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
               *, block_q, block_kv):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(j * block_kv <= i * block_q + block_q - 1)
    def _():
        _, ds = _p_ds(
            q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], do_ref[0, 0],
            lse_ref[0, 0], delta_ref[0, 0],
            i, j, block_q, block_kv,
        )
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == pl.num_programs(3) - 1)
    def _():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward — dk/dv kernel (grid walks Q innermost, dk/dv accumulate)
# ---------------------------------------------------------------------------


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                dk_scr, dv_scr, *, block_q, block_kv):
    j, i = pl.program_id(2), pl.program_id(3)  # kv block j outer, q block i inner

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(i * block_q + block_q - 1 >= j * block_kv)
    def _():
        q, do = q_ref[0, 0], do_ref[0, 0]
        p, ds = _p_ds(
            q, k_ref[0, 0], v_ref[0, 0], do,
            lse_ref[0, 0], delta_ref[0, 0],
            i, j, block_q, block_kv,
        )
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == pl.num_programs(3) - 1)
    def _():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_call(q, k, v, out, lse, do, block_q, block_kv):
    b, h, t, d = q.shape
    nq, nkv = t // block_q, t // block_kv
    # delta_i = rowsum(dO ⊙ O): tiny elementwise reduce, leave it to XLA.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)[..., None]

    if nq == 1 and nkv == 1:
        spec = pl.BlockSpec((1, 1, t, d), lambda bi, hi: (bi, hi, 0, 0))
        sspec = pl.BlockSpec((1, 1, t, 1), lambda bi, hi: (bi, hi, 0, 0))
        return pl.pallas_call(
            functools.partial(_bwd_kernel_single, block_q=t, block_kv=t),
            grid=(b, h),
            in_specs=[spec, spec, spec, spec, sspec, sspec],
            out_specs=[spec, spec, spec],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
                jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
                jax.ShapeDtypeStruct((b, h, t, d), v.dtype),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel"),
            ),
            interpret=_interpret(),
        )(q, k, v, do, lse, delta)

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i, j: (bi, hi, i, 0))
    kvspec_q_outer = pl.BlockSpec((1, 1, block_kv, d), lambda bi, hi, i, j: (bi, hi, j, 0))
    statspec = pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, i, j: (bi, hi, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_q=block_q, block_kv=block_kv),
        grid=(b, h, nq, nkv),
        in_specs=[qspec, kvspec_q_outer, kvspec_q_outer, qspec, statspec, statspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dk/dv grid: (b, h, nkv, nq) — q innermost so per-KV-block accumulators
    # persist in scratch.
    qspec_kv_outer = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, j, i: (bi, hi, i, 0))
    kvspec = pl.BlockSpec((1, 1, block_kv, d), lambda bi, hi, j, i: (bi, hi, j, 0))
    statspec_kv = pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, j, i: (bi, hi, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, block_kv=block_kv),
        grid=(b, h, nkv, nq),
        in_specs=[qspec_kv_outer, kvspec, kvspec, qspec_kv_outer, statspec_kv, statspec_kv],
        out_specs=[kvspec, kvspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper over (B, H, T, D) tensors
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, block_q, block_kv):
    out, _ = _fwd_call(q, k, v, block_q, block_kv)
    return out


def _flash_fwd(q, k, v, block_q, block_kv):
    out, lse = _fwd_call(q, k, v, block_q, block_kv)
    # Names make the kernel residuals policy-saveable under remat: with
    # jax.checkpoint_policies.save_only_these_names("flash_out", "flash_lse")
    # (ModelConfig remat="block_save_flash"), the backward pass recomputes
    # the cheap qkv projections but never re-runs this forward kernel —
    # out/lse are restored from HBM (~17 MB/layer at the flagship shape vs
    # ~0.5 ms/layer of kernel recompute; measured in PERF.md round 4).
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    q = checkpoint_name(q, "flash_q")
    k = checkpoint_name(k, "flash_k")
    v = checkpoint_name(v, "flash_v")
    return out, (q, k, v, out, lse)


def _flash_bwd(block_q, block_kv, res, do):
    q, k, v, out, lse = res
    return _bwd_call(q, k, v, out, lse, do, block_q, block_kv)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Packed (transpose-free) kernels for the single-KV-tile case.
#
# The model's natural layout is (B, T, H*D) — the raw output of the qkv
# projections. The original kernels wanted (B, H, T, D), and XLA realised
# that relayout as ~10 HBM copy passes per step (q/k/v/o forward, the same
# again under remat recompute, and do/dq/dk/dv backward): measured ~15 ms of
# a 114 ms flagship b32 step. These variants index the packed layout
# directly — a lane GROUP of g = 128 // D heads per grid slot, so every
# block is 128-lane aligned — and slice heads INSIDE VMEM, where a 32-lane
# static slice is a register shuffle, not an HBM pass. The softmax scale is
# applied to the q tile in VMEM (free) instead of as a separate HBM pass,
# and the backward's delta = rowsum(dO ⊙ O) moves into the kernel (was a
# 2.7 ms layout-hostile XLA reduce fusion).
# ---------------------------------------------------------------------------


def _packed_group(d: int, h: int) -> int | None:
    """Heads per 128-lane group, or None if the packed path can't apply."""
    if d > _LANES or _LANES % d != 0:
        return None
    g = _LANES // d
    return g if h % g == 0 else None


def _causal_block_dispatch(i, j, block_q, block_kv, accumulate):
    """Run ``accumulate(masked)`` for the causally-relevant (i, j) tile.

    One definition of the two correctness-critical predicates shared by
    all four packed multi-tile kernels: a block participates iff its first
    kv position <= the q block's last position, and it needs the (full
    VPU pass) causal select iff it straddles the diagonal — fully-below
    blocks (last kv pos <= first q pos) run unmasked. Blocks strictly
    above the diagonal run neither branch."""
    straddles = j * block_kv + block_kv - 1 > i * block_q

    @pl.when((j * block_kv <= i * block_q + block_q - 1) & straddles)
    def _():
        accumulate(True)

    @pl.when(jnp.logical_not(straddles))
    def _():
        accumulate(False)


def _packed_scores(qt, kt, sl, scale, mask):
    """fp32 score tile for head slice ``sl`` of packed q/k tiles;
    ``mask=None`` skips the causal select (fully-below-diagonal blocks)."""
    s = jax.lax.dot_general(
        qt[:, sl] * scale, kt[:, sl], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return s if mask is None else jnp.where(mask, s, NEG_INF)


def _packed_tile_bwd(qt, kt, vt, dot_, ot, lse, mask, sl, scale, delta=None):
    """Shared per-head backward tile math for the packed kernels: recompute
    p from the saved lse, form ds = p*(dp - delta), and return the three
    fp32 gradient contributions (dq, dk, dv) for head slice ``sl``.
    ``delta`` (rowsum(dO ⊙ O), depends only on the q block) may be passed
    in precomputed; None computes it from the tiles."""
    qs = qt[:, sl] * scale
    k = kt[:, sl]
    do = dot_[:, sl]
    s = jax.lax.dot_general(
        qs, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    p = jnp.exp(s - lse)
    if mask is not None:  # None = unmasked block (zigzag ring cross-chunks)
        p = jnp.where(mask, p, 0.0)
    if delta is None:
        delta = jnp.sum(
            do.astype(jnp.float32) * ot[:, sl].astype(jnp.float32),
            axis=-1, keepdims=True,
        )
    dp = jax.lax.dot_general(
        do, vt[:, sl], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta)
    dq_c = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    dk_c = jax.lax.dot_general(
        ds.astype(qs.dtype), qs, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dv_c = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dq_c, dk_c, dv_c


def _fwd_kernel_packed(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                       block_q, block_kv, g, d, scale):
    """Single-KV-tile forward on packed (B, T, H*D) inputs; one grid slot
    handles g heads living side-by-side in a 128-lane block."""
    i = pl.program_id(2)
    mask = _mask(i, 0, block_q, block_kv)
    qt, kt, vt = q_ref[0], k_ref[0], v_ref[0]      # (bq, g*d), (bkv, g*d)
    for gg in range(g):
        sl = slice(gg * d, (gg + 1) * d)
        s = _packed_scores(qt, kt, sl, scale, mask)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        acc = jax.lax.dot_general(
            p.astype(vt.dtype), vt[:, sl], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0, :, sl] = (acc / l).astype(o_ref.dtype)
        lse_ref[0, 0, :, gg : gg + 1] = m + jnp.log(l)


def _bwd_kernel_packed(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                       dq_ref, dk_ref, dv_ref, *, block_q, block_kv, g, d, scale):
    """Fused single-tile backward on packed inputs: p recomputed once per
    head group; delta computed in VMEM from do and o."""
    i = pl.program_id(2)
    mask = _mask(i, 0, block_q, block_kv)
    qt, kt, vt = q_ref[0], k_ref[0], v_ref[0]
    dot_, ot = do_ref[0], o_ref[0]
    for gg in range(g):
        sl = slice(gg * d, (gg + 1) * d)
        lse = lse_ref[0, 0, :, gg : gg + 1]        # (block_q, 1) fp32
        dq_c, dk_c, dv_c = _packed_tile_bwd(
            qt, kt, vt, dot_, ot, lse, mask, sl, scale
        )
        dq_ref[0, :, sl] = dq_c.astype(dq_ref.dtype)
        dk_ref[0, :, sl] = dk_c.astype(dk_ref.dtype)
        dv_ref[0, :, sl] = dv_c.astype(dv_ref.dtype)


def _packed_specs(t, block_q):
    """(q/o spec, kv spec) for the packed (B, T, H*D) layout. Only valid
    for the single-tile case (t == block_q): the backward writes dk/dv
    whole-tile per grid slot, which would race across q blocks otherwise."""
    dspec = pl.BlockSpec((1, block_q, _LANES), lambda bi, gi, i: (bi, i, gi))
    kvspec = pl.BlockSpec((1, t, _LANES), lambda bi, gi, i: (bi, 0, gi))
    return dspec, kvspec


# --- packed multi-tile: causal block skipping (25% less compute at 2x2) ---


def _fwd_kernel_packed_multi(q_ref, k_ref, v_ref, o_ref, lse_ref,
                             m_scr, l_scr, acc_scr, *,
                             block_q, block_kv, g, d, scale):
    """Online-softmax forward on packed layout, KV blocks walked innermost.
    Blocks strictly above the causal diagonal are predicated out entirely —
    the single-tile kernel pays for the whole T² tile, this one only for
    the lower-triangular blocks. Scratch columns gg hold head gg's running
    stats; acc uses the same lane slot as the head's output slice."""
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _accumulate(masked: bool):
        mask = _mask(i, j, block_q, block_kv) if masked else None
        qt, kt, vt = q_ref[0], k_ref[0], v_ref[0]
        for gg in range(g):
            sl = slice(gg * d, (gg + 1) * d)
            cl = slice(gg, gg + 1)
            s = _packed_scores(qt, kt, sl, scale, mask)
            m_prev = m_scr[:, cl]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_scr[:, cl] = alpha * l_scr[:, cl] + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:, sl] = acc_scr[:, sl] * alpha + jax.lax.dot_general(
                p.astype(vt.dtype), vt[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_scr[:, cl] = m_new

    # The causal select is a full VPU pass over the fp32 score tile; at
    # T/block = 8 the dispatch skips it on 28 of 36 valid blocks.
    _causal_block_dispatch(i, j, block_q, block_kv, _accumulate)

    @pl.when(j == pl.num_programs(3) - 1)
    def _():
        for gg in range(g):
            sl = slice(gg * d, (gg + 1) * d)
            cl = slice(gg, gg + 1)
            o_ref[0, :, sl] = (acc_scr[:, sl] / l_scr[:, cl]).astype(o_ref.dtype)
            lse_ref[0, 0, :, cl] = m_scr[:, cl] + jnp.log(l_scr[:, cl])


def _bwd_kernel_packed_multi(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                             dq_ref, dk_ref, dv_ref,
                             dq_scr, dk_scr, dv_scr, delta_scr, *,
                             block_q, block_kv, g, d, scale):
    """Fused backward on packed layout with causal block skipping.

    Grid (b, hg, i, j), row-major: dq for q-block i accumulates in a small
    (block_q, 128) scratch reset at j==0 and written at j==last; dk/dv
    accumulate rows pl.ds(j*block_kv) of full-length (T, 128) scratches —
    their j-blocks only complete at the final i — and are written whole at
    the last grid step. p is recomputed ONCE per valid block and feeds all
    three gradients (the split dq/dkv kernels of the transpose path
    recompute it twice)."""
    i, j = pl.program_id(2), pl.program_id(3)
    nq, nkv = pl.num_programs(2), pl.num_programs(3)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)
        # delta = rowsum(dO ⊙ O) depends only on the q block: compute it
        # once per i here (j == 0 is always causally valid) instead of
        # per KV block — saves (nkv - 1) redundant VPU reduces per head.
        dot_, ot = do_ref[0], o_ref[0]
        for gg in range(g):
            sl = slice(gg * d, (gg + 1) * d)
            delta_scr[:, gg : gg + 1] = jnp.sum(
                dot_[:, sl].astype(jnp.float32) * ot[:, sl].astype(jnp.float32),
                axis=-1, keepdims=True,
            )

    @pl.when((i == 0) & (j == 0))
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accumulate(masked: bool):
        mask = _mask(i, j, block_q, block_kv) if masked else None
        qt, kt, vt = q_ref[0], k_ref[0], v_ref[0]
        dot_, ot = do_ref[0], o_ref[0]
        rows = pl.ds(j * block_kv, block_kv)
        for gg in range(g):
            sl = slice(gg * d, (gg + 1) * d)
            lse = lse_ref[0, 0, :, gg : gg + 1]
            dq_c, dk_c, dv_c = _packed_tile_bwd(
                qt, kt, vt, dot_, ot, lse, mask, sl, scale,
                delta=delta_scr[:, gg : gg + 1],
            )
            dq_scr[:, sl] += dq_c
            dk_scr[rows, sl] += dk_c
            dv_scr[rows, sl] += dv_c

    _causal_block_dispatch(i, j, block_q, block_kv, _accumulate)

    @pl.when(j == nkv - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)

    @pl.when((i == nq - 1) & (j == nkv - 1))
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# --- packed split backward: O(block) scratch, any T -----------------------
#
# The fused multi-tile backward above holds full-length (T, 128) dk/dv
# accumulators in VMEM — past _PACKED_MAX_T those outgrow a core's VMEM.
# These two kernels are the FA2-style split on the packed layout: the dq
# kernel accumulates (block_q, 128) while walking KV blocks, the dk/dv
# kernel accumulates (block_kv, 128) while walking Q blocks. Each
# recomputes p from the saved lse (7 tile matmuls total vs the fused
# kernel's 5), so the fused path stays the default wherever it fits and
# these take over beyond it. delta = rowsum(dO ⊙ O) is precomputed by XLA
# in the lse layout (b, hg, T, g) — one cheap elementwise+reduce pass —
# instead of per-tile, which would redo it nkv (dq) / nq (dkv) times.


def _split_tile_p_ds(refs, lse_ref, delta_ref, mask, sl, gg, scale):
    """Shared split-kernel recompute for head slice ``sl``: returns
    (p, ds, qs) — probabilities from the saved lse, the score gradient
    ds = p * (dp - delta), and the pre-scaled q tile. One definition so
    the dq and dk/dv halves of the gradient cannot drift apart."""
    q_ref, k_ref, v_ref, do_ref = refs
    qs = q_ref[0][:, sl] * scale
    s = jax.lax.dot_general(
        qs, k_ref[0][:, sl], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    p = jnp.exp(s - lse_ref[0, 0, :, gg : gg + 1])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(
        do_ref[0][:, sl], v_ref[0][:, sl], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_ref[0, 0, :, gg : gg + 1])
    return p, ds, qs


def _dq_kernel_packed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dq_scr, *, block_q, block_kv, g, d, scale):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _accumulate(masked: bool):
        mask = _mask(i, j, block_q, block_kv) if masked else None
        for gg in range(g):
            sl = slice(gg * d, (gg + 1) * d)
            _, ds, _ = _split_tile_p_ds(
                (q_ref, k_ref, v_ref, do_ref), lse_ref, delta_ref,
                mask, sl, gg, scale,
            )
            kk = k_ref[0][:, sl]
            dq_scr[:, sl] += jax.lax.dot_general(
                ds.astype(kk.dtype), kk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale

    _causal_block_dispatch(i, j, block_q, block_kv, _accumulate)

    @pl.when(j == pl.num_programs(3) - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel_packed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_scr, dv_scr,
                       *, block_q, block_kv, g, d, scale):
    j, i = pl.program_id(2), pl.program_id(3)  # kv block outer, q inner

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accumulate(masked: bool):
        mask = _mask(i, j, block_q, block_kv) if masked else None
        for gg in range(g):
            sl = slice(gg * d, (gg + 1) * d)
            p, ds, qs = _split_tile_p_ds(
                (q_ref, k_ref, v_ref, do_ref), lse_ref, delta_ref,
                mask, sl, gg, scale,
            )
            dk_scr[:, sl] += jax.lax.dot_general(
                ds.astype(qs.dtype), qs, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dot_ = do_ref[0]
            dv_scr[:, sl] += jax.lax.dot_general(
                p.astype(dot_.dtype), dot_[:, sl], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    _causal_block_dispatch(i, j, block_q, block_kv, _accumulate)

    @pl.when(i == pl.num_programs(3) - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _packed_split_bwd_call(q, k, v, do, out, lse, block_q, block_kv, g, d, scale):
    b, t, hd = q.shape
    hg = hd // _LANES
    nq, nkv = t // block_q, t // block_kv
    # delta in the lse layout (b, hg, t, g): rowsum over each head's d slice.
    delta = (
        (do.astype(jnp.float32) * out.astype(jnp.float32))
        .reshape(b, t, hg, g, d)
        .sum(-1)
        .transpose(0, 2, 1, 3)
    )

    qspec = pl.BlockSpec((1, block_q, _LANES), lambda bi, gi, i, j: (bi, i, gi))
    kvspec = pl.BlockSpec((1, block_kv, _LANES), lambda bi, gi, i, j: (bi, j, gi))
    statspec = pl.BlockSpec((1, 1, block_q, g), lambda bi, gi, i, j: (bi, gi, i, 0))
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel_packed,
            block_q=block_q, block_kv=block_kv, g=g, d=d, scale=scale,
        ),
        grid=(b, hg, nq, nkv),
        in_specs=[qspec, kvspec, kvspec, qspec, statspec, statspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, t, hd), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, _LANES), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    qspec_kv = pl.BlockSpec((1, block_q, _LANES), lambda bi, gi, j, i: (bi, i, gi))
    kvspec_kv = pl.BlockSpec((1, block_kv, _LANES), lambda bi, gi, j, i: (bi, j, gi))
    statspec_kv = pl.BlockSpec((1, 1, block_q, g), lambda bi, gi, j, i: (bi, gi, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel_packed,
            block_q=block_q, block_kv=block_kv, g=g, d=d, scale=scale,
        ),
        grid=(b, hg, nkv, nq),
        in_specs=[qspec_kv, kvspec_kv, kvspec_kv, qspec_kv, statspec_kv, statspec_kv],
        out_specs=[kvspec_kv, kvspec_kv],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, hd), k.dtype),
            jax.ShapeDtypeStruct((b, t, hd), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, _LANES), jnp.float32),
            pltpu.VMEM((block_kv, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_packed(q, k, v, block_q, block_kv, g, d, scale,
                  block_q_bwd, block_kv_bwd):
    out, _ = _packed_fwd_call(q, k, v, block_q, block_kv, g, d, scale)
    return out


def _packed_fwd_call(q, k, v, block_q, block_kv, g, d, scale):
    b, t, hd = q.shape
    hg = hd // _LANES
    nq = t // block_q
    if block_kv == t and nq == 1:
        # Whole tile: one-pass kernel, no online-softmax scratch.
        dspec, kvspec = _packed_specs(t, block_q)
        lsespec = pl.BlockSpec((1, 1, block_q, g), lambda bi, gi, i: (bi, gi, i, 0))
        return pl.pallas_call(
            functools.partial(
                _fwd_kernel_packed, block_q=block_q, block_kv=t, g=g, d=d, scale=scale
            ),
            grid=(b, hg, nq),
            in_specs=[dspec, kvspec, kvspec],
            out_specs=[dspec, lsespec],
            out_shape=[
                jax.ShapeDtypeStruct((b, t, hd), q.dtype),
                jax.ShapeDtypeStruct((b, hg, t, g), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel"),
            ),
            interpret=_interpret(),
        )(q, k, v)
    nkv = t // block_kv
    qspec = pl.BlockSpec((1, block_q, _LANES), lambda bi, gi, i, j: (bi, i, gi))
    kvspec = pl.BlockSpec((1, block_kv, _LANES), lambda bi, gi, i, j: (bi, j, gi))
    lsespec = pl.BlockSpec((1, 1, block_q, g), lambda bi, gi, i, j: (bi, gi, i, 0))
    return pl.pallas_call(
        functools.partial(
            _fwd_kernel_packed_multi,
            block_q=block_q, block_kv=block_kv, g=g, d=d, scale=scale,
        ),
        grid=(b, hg, nq, nkv),
        in_specs=[qspec, kvspec, kvspec],
        out_specs=[qspec, lsespec],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, hd), q.dtype),
            jax.ShapeDtypeStruct((b, hg, t, g), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # acc (g head slices)
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v)


def _packed_flash_fwd(q, k, v, block_q, block_kv, g, d, scale,
                      block_q_bwd, block_kv_bwd):
    out, lse = _packed_fwd_call(q, k, v, block_q, block_kv, g, d, scale)
    # Policy-saveable residuals — see _flash_fwd for the rationale.
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    q = checkpoint_name(q, "flash_q")
    k = checkpoint_name(k, "flash_k")
    v = checkpoint_name(v, "flash_v")
    return out, (q, k, v, out, lse)


def _packed_flash_bwd(block_q, block_kv, g, d, scale,
                      block_q_bwd, block_kv_bwd, res, do):
    q, k, v, out, lse = res
    b, t, hd = q.shape
    hg = hd // _LANES
    # The backward's best tiling differs from the forward's (the fused
    # kernel holds dk/dv scratches the forward doesn't; measured on v5e,
    # PERF.md round 5): nonzero overrides retile it independently —
    # including OUT of the single-tile fast path, so the knob is honored
    # uniformly. The saved lse is blocked afresh by these specs, so any
    # valid tiling of the same arrays works.
    if block_q_bwd:
        block_q = block_q_bwd
    if block_kv_bwd:
        block_kv = block_kv_bwd
    nq = t // block_q
    # Guard ORDER matters (round-5 ADVICE): the T cap must be checked
    # before the single-tile fast path, or a user tiling override that
    # resolves to one whole-T tile at T > _PACKED_MAX_T reaches the fused
    # kernel — whose full-T VMEM scratches then die as an opaque Mosaic
    # compile OOM instead of this error. flash_causal_attention validates
    # the same condition at the API surface; this is the defense for
    # direct _flash_packed callers.
    if t > _PACKED_MAX_T:
        if block_kv == t and nq == 1:
            raise ValueError(
                f"packed flash backward cannot run whole-T tiles past "
                f"T={_PACKED_MAX_T} (full-T VMEM scratches): T={t} with "
                f"block_q={block_q}, block_kv={block_kv}; choose bwd "
                f"blocks < T"
            )
        # Fused kernel's full-T dk/dv VMEM scratches don't fit: split
        # dq / dkv kernels with O(block) scratch take over.
        return _packed_split_bwd_call(
            q, k, v, do, out, lse, block_q, block_kv, g, d, scale
        )
    if block_kv == t and nq == 1:
        dspec, kvspec = _packed_specs(t, block_q)
        lsespec = pl.BlockSpec((1, 1, block_q, g), lambda bi, gi, i: (bi, gi, i, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_kernel_packed, block_q=block_q, block_kv=t, g=g, d=d, scale=scale
            ),
            grid=(b, hg, nq),
            in_specs=[dspec, kvspec, kvspec, dspec, dspec, lsespec],
            out_specs=[dspec, kvspec, kvspec],
            out_shape=[
                jax.ShapeDtypeStruct((b, t, hd), q.dtype),
                jax.ShapeDtypeStruct((b, t, hd), k.dtype),
                jax.ShapeDtypeStruct((b, t, hd), v.dtype),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel"),
            ),
            interpret=_interpret(),
        )(q, k, v, do, out, lse)
        return dq, dk, dv
    nkv = t // block_kv
    qspec = pl.BlockSpec((1, block_q, _LANES), lambda bi, gi, i, j: (bi, i, gi))
    kvspec = pl.BlockSpec((1, block_kv, _LANES), lambda bi, gi, i, j: (bi, j, gi))
    lsespec = pl.BlockSpec((1, 1, block_q, g), lambda bi, gi, i, j: (bi, gi, i, 0))
    fullspec = pl.BlockSpec((1, t, _LANES), lambda bi, gi, i, j: (bi, 0, gi))
    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_kernel_packed_multi,
            block_q=block_q, block_kv=block_kv, g=g, d=d, scale=scale,
        ),
        grid=(b, hg, nq, nkv),
        in_specs=[qspec, kvspec, kvspec, qspec, qspec, lsespec],
        out_specs=[qspec, fullspec, fullspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, hd), q.dtype),
            jax.ShapeDtypeStruct((b, t, hd), k.dtype),
            jax.ShapeDtypeStruct((b, t, hd), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # dq accumulator
            pltpu.VMEM((t, _LANES), jnp.float32),        # dk accumulator
            pltpu.VMEM((t, _LANES), jnp.float32),        # dv accumulator
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # delta (per q block)
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v, do, out, lse)
    return dq, dk, dv


_flash_packed.defvjp(_packed_flash_fwd, _packed_flash_bwd)


def supports(t: int, d: int, block_q: int, block_kv: int) -> bool:
    """Whether the kernel handles this shape (used by the auto dispatcher)."""
    bq, bkv = min(block_q, t), min(block_kv, t)
    return (
        t % bq == 0 and t % bkv == 0
        and bq % 8 == 0 and bkv % _LANES == 0
        and d <= 512  # per-tile head_dim must fit VMEM comfortably
    )


def flash_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, block_q: int = 512, block_kv: int = 512,
    block_q_bwd: int = 0, block_kv_bwd: int = 0,
) -> jax.Array:
    """Causal flash attention over ``(B, T, H, D)`` tensors (op-layer layout).

    Exact (up to fp32 accumulation order) match of
    ``dense_causal_attention``; O(T) memory instead of O(T²).
    ``block_*_bwd`` retile the packed backward independently of the
    forward (0 = same as forward) — at long context the forward wants
    wide KV blocks while the backward's scratches cap its tile budget.
    """
    b, t, h, d = q.shape
    block_q, block_kv = min(block_q, t), min(block_kv, t)
    block_q_bwd, block_kv_bwd = min(block_q_bwd, t), min(block_kv_bwd, t)
    if not supports(t, d, block_q, block_kv):
        raise ValueError(
            f"flash attention unsupported for T={t}, D={d}, "
            f"block_q={block_q}, block_kv={block_kv}"
        )
    if (block_q_bwd or block_kv_bwd) and not supports(
        t, d, block_q_bwd or block_q, block_kv_bwd or block_kv
    ):
        raise ValueError(
            f"flash attention backward tiling unsupported for T={t}, "
            f"block_q_bwd={block_q_bwd}, block_kv_bwd={block_kv_bwd}"
        )
    # Past _PACKED_MAX_T no kernel can hold a whole-T tile (the one-pass
    # forward materializes (T, T) scores; fused AND split backwards hold
    # (T, 128) accumulators) — reject single-tile tilings HERE with the
    # cause named instead of letting pallas_call die in a Mosaic compile
    # OOM (round-5 ADVICE guard-order fix; the bwd-side check in
    # _packed_flash_bwd covers direct kernel callers).
    if t > _PACKED_MAX_T:
        for tag, bq_eff, bkv_eff in (
            ("", block_q, block_kv),
            ("_bwd", block_q_bwd or block_q, block_kv_bwd or block_kv),
        ):
            if bkv_eff == t and bq_eff == t:
                raise ValueError(
                    f"flash attention cannot run whole-T tiles past "
                    f"T={_PACKED_MAX_T}: T={t} with block_q{tag}={bq_eff}, "
                    f"block_kv{tag}={bkv_eff}; use blocks < T (e.g. the "
                    f"512/1024 defaults)"
                )

    g = _packed_group(d, h)
    if (block_q_bwd or block_kv_bwd) and g is None:
        # The transpose-layout fallback has no independent backward tiling;
        # silently running the forward tiling there would make sweep-tuned
        # A/B numbers lie.
        raise ValueError(
            "attention_block_{q,kv}_bwd require the packed flash path "
            f"(128 % head_dim == 0 and heads % group == 0); got D={d}, H={h}"
        )
    if g is not None:
        # Packed transpose-free path: heads group into 128-lane blocks ->
        # operate on the model-native (B, T, H*D) layout directly. reshape
        # is a bitcast; no HBM relayout anywhere. Single-tile shapes use
        # the one-pass kernels; tiled shapes the online-softmax/causal-
        # block-skipping ones. Beyond _PACKED_MAX_T the fused backward's
        # full-T dk/dv scratches outgrow VMEM and the split dq/dkv
        # kernels (all scratch O(block)) take over — packed at every T.
        scale = float(d ** -0.5)
        out = _flash_packed(
            q.reshape(b, t, h * d), k.reshape(b, t, h * d),
            v.reshape(b, t, h * d), block_q, block_kv, g, d, scale,
            block_q_bwd, block_kv_bwd,
        )
        return out.reshape(b, t, h, d)

    # Fold the softmax scale into q once here — saves a full (bq, bkv)
    # multiply pass per tile in every kernel, and its VJP restores dq's
    # scale factor automatically.
    q = q * q.dtype.type(d ** -0.5)

    # (B, T, H, D) -> (B, H, T, D). head_dim stays native: Mosaic pads the
    # VMEM tiles internally, HBM traffic stays at the true size.
    tk = lambda x: x.transpose(0, 2, 1, 3)
    out = _flash(tk(q), tk(k), tk(v), block_q, block_kv)
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Ring-block kernels: single-tile attention BLOCKS for the zigzag ring
# (ops/ring_attention.py). Same packed (B, Tc, H*D) layout and per-group
# head slicing as the kernels above, but (a) the causal mask is optional —
# zigzag cross-chunk blocks are strictly past and need none — and (b) the
# softmax statistics cross the kernel boundary explicitly: forward RETURNS
# lse so the ring can merge blocks online in jnp; backward TAKES the
# globally-merged lse (and global out for delta), the standard ring-flash
# backward contract.
# ---------------------------------------------------------------------------


def _block_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, tc, g, d, scale, causal):
    qt, kt, vt = q_ref[0], k_ref[0], v_ref[0]
    mask = _mask(0, 0, tc, tc) if causal else None
    for gg in range(g):
        sl = slice(gg * d, (gg + 1) * d)
        s = jax.lax.dot_general(
            qt[:, sl] * scale, kt[:, sl], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            s = jnp.where(mask, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        acc = jax.lax.dot_general(
            p.astype(vt.dtype), vt[:, sl], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0, :, sl] = (acc / l).astype(o_ref.dtype)
        lse_ref[0, 0, :, gg : gg + 1] = m + jnp.log(l)


def _block_bwd_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                      dq_ref, dk_ref, dv_ref, *, tc, g, d, scale, causal):
    mask = _mask(0, 0, tc, tc) if causal else None
    qt, kt, vt = q_ref[0], k_ref[0], v_ref[0]
    dot_, ot = do_ref[0], o_ref[0]
    for gg in range(g):
        sl = slice(gg * d, (gg + 1) * d)
        lse = lse_ref[0, 0, :, gg : gg + 1]
        dq_c, dk_c, dv_c = _packed_tile_bwd(
            qt, kt, vt, dot_, ot, lse, mask, sl, scale
        )
        dq_ref[0, :, sl] = dq_c.astype(dq_ref.dtype)
        dk_ref[0, :, sl] = dk_c.astype(dk_ref.dtype)
        dv_ref[0, :, sl] = dv_c.astype(dv_ref.dtype)


def _block_specs(tc, g):
    dspec = pl.BlockSpec((1, tc, _LANES), lambda bi, gi: (bi, 0, gi))
    lsespec = pl.BlockSpec((1, 1, tc, g), lambda bi, gi: (bi, gi, 0, 0))
    return dspec, lsespec


def block_supported(tc: int, h: int, d: int) -> bool:
    """Can the packed ring-block kernels handle a (B, tc, h*d) chunk?"""
    return (
        _packed_group(d, h) is not None and tc % 8 == 0 and tc <= _PACKED_MAX_T
    )


def _block_call(q, k, v, scale, causal, g, d, do=None, o=None, lse=None):
    """pallas_call wrapper for the ring-block kernels. Forward when
    ``do is None`` -> (out, lse); backward otherwise -> (dq, dk, dv) fp32."""
    b, tc, hd = q.shape
    hg = hd // _LANES
    dspec, lsespec = _block_specs(tc, g)
    if do is None:
        return pl.pallas_call(
            functools.partial(
                _block_fwd_kernel, tc=tc, g=g, d=d, scale=scale, causal=causal
            ),
            grid=(b, hg),
            in_specs=[dspec, dspec, dspec],
            out_specs=[dspec, lsespec],
            out_shape=[
                jax.ShapeDtypeStruct((b, tc, hd), q.dtype),
                jax.ShapeDtypeStruct((b, hg, tc, g), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel"),
            ),
            interpret=_interpret(),
        )(q, k, v)
    return pl.pallas_call(
        functools.partial(
            _block_bwd_kernel, tc=tc, g=g, d=d, scale=scale, causal=causal
        ),
        grid=(b, hg),
        in_specs=[dspec, dspec, dspec, dspec, dspec, lsespec],
        out_specs=[dspec, dspec, dspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, tc, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, tc, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, tc, hd), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=_interpret(),
    )(q, k, v, do, o, lse)
