"""Fused LM-head + cross-entropy with an augmented-matmul backward.

The reference computes the LM head and the loss as separate ops
(`/root/reference/model/GPTModel.py:69-74` +
`/root/reference/train/create_train_step.py:30-34`) and lets autodiff derive
the backward. On TPU that backward costs one avoidable full pass over the
logits: XLA fuses the dlogits recomputation into the dW and dh matmuls, but
the *bias* gradient ``db = sum_rows(dlogits)`` becomes its own
bandwidth-bound reduction over the (B·T, V) logits — 2.3 ms/step at the
flagship b32 shape (PERF.md round 4).

This op folds db into the dW matmul by appending a ones-column to the
activations: ``[h; 1]^T @ dlogits`` yields dW in rows [:d] and db in row d,
one matmul instead of a matmul plus a separate logits pass. Forward numerics
are bitwise identical to the unfused path (same op sequence as
``dtc_tpu.train.train_step.cross_entropy_loss``); backward differs only in
reduction order (ulp-level).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

NEG_INF = -1e9  # matches the reference's additive mask value


def head_logits(h: jax.Array, w: jax.Array, b: jax.Array, vocab_size: int) -> jax.Array:
    """LM-head logits with padded-vocab masking.

    Bitwise-matches ``nn.Dense`` (dot_general + bias in compute dtype)
    followed by the pad-column mask the model applied before this op
    existed — the non-fused eval/generate path calls this too, so the two
    paths cannot drift apart.
    """
    cdtype = h.dtype
    logits = jnp.dot(h, w.astype(cdtype)) + b.astype(cdtype)
    v = w.shape[-1]
    if v != vocab_size:
        # Pad columns contribute exp(-1e9) = 0 to any softmax, so losses and
        # samples over the padded vocab equal the unpadded ones.
        col = jax.lax.broadcasted_iota(jnp.int32, (v,), 0)
        logits = jnp.where(col < vocab_size, logits, NEG_INF).astype(logits.dtype)
    return nn.with_logical_constraint(logits, ("batch", "seq", "vocab_out"))


def _stats_loss(logits: jax.Array, y: jax.Array):
    """Mean CE + softmax stats. Same op sequence as cross_entropy_loss."""
    l32 = logits.astype(jnp.float32)
    maxl = jax.lax.stop_gradient(jnp.max(l32, axis=-1, keepdims=True))
    shifted = l32 - maxl
    logz = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1))
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    gold = jnp.sum(jnp.where(iota == y[..., None], shifted, 0.0), axis=-1)
    return (logz - gold).mean(), (maxl, logz)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_head_ce(h, w, b, y, vocab_size):
    """Mean next-token CE of ``softmax([h @ w + b | mask])`` against ``y``.

    ``h``: (..., d) compute-dtype activations; ``w``: (d, V) / ``b``: (V,)
    master params; ``y``: (...) int32 targets aligned with ``h``'s leading
    dims. Returns a float32 scalar.
    """
    loss, _ = _stats_loss(head_logits(h, w, b, vocab_size), y)
    return loss


def _fhc_fwd(h, w, b, y, vocab_size):
    logits = head_logits(h, w, b, vocab_size)
    loss, (maxl, logz) = _stats_loss(logits, y)
    return loss, (h, w, y, logits, maxl, logz)


def _fhc_bwd(vocab_size, res, g):
    h, w, y, logits, maxl, logz = res
    *lead, v = logits.shape
    d = h.shape[-1]
    n = float(np.prod(lead))
    # dlogits = (softmax - onehot) * g / N, recomputed from the saved logits
    # and stats. XLA duplicates this elementwise chain into both consumer
    # matmul fusions, so dlogits is never materialised in HBM (verified in
    # the round-4 trace: the dot fusions' byte counts equal a logits read).
    l32 = logits.astype(jnp.float32)
    p = jnp.exp(l32 - maxl - logz[..., None])
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    onehot = jnp.where(iota == y[..., None], 1.0, 0.0)
    dl = ((p - onehot) * (g / n)).astype(h.dtype)
    dl = nn.with_logical_constraint(dl, ("batch", "seq", "vocab_out"))
    dl2 = dl.reshape(-1, v)
    # The augmented matmul: db rides along as row d of [h; 1]^T @ dlogits.
    hb = jnp.concatenate([h, jnp.ones((*lead, 1), h.dtype)], axis=-1)
    dwb = jax.lax.dot_general(hb.reshape(-1, d + 1), dl2, (((0,), (0,)), ((), ())))
    dw = dwb[:d].astype(w.dtype)
    db = dwb[d].astype(w.dtype)
    dh = (
        jax.lax.dot_general(dl2, w.astype(h.dtype), (((1,), (1,)), ((), ())))
        .reshape(h.shape)
        .astype(h.dtype)
    )
    dy = np.zeros(y.shape, dtype=jax.dtypes.float0)
    return dh, dw, db, dy


fused_head_ce.defvjp(_fhc_fwd, _fhc_bwd)
