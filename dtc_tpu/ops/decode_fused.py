"""Layer-fused decode megakernel — ONE Pallas launch per token.

PERF.md round 7 pinned the b8 decode step at 8.1% of its bandwidth
roofline and attributed the gap to LAUNCH COUNT: the per-layer fused
kernel (ops/decode_attention.py) still dispatches one attention kernel
plus a handful of XLA fusions per layer per token — ~110 launches for the
12-layer flagship, each costing dispatch overhead that dwarfs the actual
byte traffic at decode shapes. This module folds the WHOLE per-layer
decode block into one resident kernel that scans the layer axis inside
its grid:

    per layer: LN1 -> q/k/v projection (+ LoRA deltas) -> int8/float
    cache write at the frontier -> single-query attention over the packed
    cache (dequant-in-register for int8) -> output projection (+ LoRA) ->
    residual -> LN2 -> MLP (+ LoRA) -> residual

so one decoded token costs O(1) launches (embed + megakernel + head +
the stacked cache scatter) instead of O(layers)·O(ops). The enabling
seams are prior refactors, not new model surgery:

- **Stacked layer params** (``nn.scan`` since the seed): every block
  weight already carries a leading ``(L,)`` axis, so a grid dimension
  over L block-indexes each layer's weights — the Pallas pipeline streams
  layer l+1's weights while layer l computes, which is exactly the
  scan-over-layers structure XLA runs, minus the per-layer dispatch.
- **The GPT-level single cache/index** (PR 4) and the **static-rank
  scalar/vector frontier branch** (PR 6): one SMEM frontier (scalar for
  ``generate``, ``(B,)`` for the serving engine's continuous-batching
  slots) drives every layer's masking and write position.
- **The stacked LoRA collection** (PR 9): per-site factors ride in as
  ``(L, in, r)`` (one shared adapter) or ``(L, B, in, r)`` (the engine's
  ``gather_slot_lora`` per-slot stack) and the low-rank deltas run
  in-kernel, so multi-tenant decode keeps the O(1)-launch property.

**Grid and memory**: grid ``(L, B)``, both dimensions sequential; a VMEM
scratch carries each row's residual stream across the L axis. Per grid
step the kernel holds one layer's weights + ONE batch row's cache tile
(weights re-fetch only when l advances — the index map is b-invariant).
:func:`supports_fused_layers` gates on an estimated per-step VMEM
working set (see ``_VMEM_BUDGET_BYTES``) and on ``max_seq_len <=
_FUSED_LAYERS_MAX_S`` — the whole-cache-row-in-one-tile regime of the
per-layer single kernel. Longer caches, prefill (multi-token) calls, and
MoE models fall back automatically to the per-layer path (which has the
blocked online-softmax flavor), so ``decode_attention: fused_layers`` is
always safe to set.

**Numerics**: fp32 LayerNorm stats (flax's fast-variance formula,
clipped at zero), fp32 scores/softmax, matmuls in compute dtype — the
same op-for-op recipe as the flax modules, asserted token-exact against
the ``xla`` einsum oracle (greedy, sampled, serving vector-index, and
stacked-LoRA paths) in tests/test_decode_fused.py. The current token's
k/v never round-trips through HBM: attention reads cache columns
``< frontier`` plus the in-register current k/v — after quantization,
so an int8 cache sees bit-identical values to the oracle's
write-then-read.

**Sharding caveat**: the megakernel is a single-device program (the
serving engine's deployment shape). Under a TP mesh the per-layer
``fused``/``xla`` paths shard over heads; ``fused_layers`` does not —
XLA cannot partition a ``pallas_call`` — so TP decode should keep the
per-layer backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Shared helpers; importing decode_attention also installs the jax-0.4.x
# pltpu.CompilerParams alias (via flash_attention) every pallas_call
# below relies on.
from dtc_tpu.ops import vmem
from dtc_tpu.ops.decode_attention import KV_SCALE_FLOOR, NEG_INF, _interpret

_DTYPES = {
    "float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16,
}

#: Longest cache the megakernel holds as one (S, H·D) tile per (layer,
#: row) grid step — the same single-tile bound as the per-layer kernel.
#: Owned by the shared planner (ops/vmem.py) since ISSUE 20.
_FUSED_LAYERS_MAX_S = vmem.FUSED_LAYERS_MAX_S

#: Widest speculative verify window the megakernel serves as one launch
#: (t query positions against the frontier, causal among themselves
#: in-register). See ops/vmem.SPEC_MAX_K; spec/core.py imports this
#: alias.
_SPEC_MAX_K = vmem.SPEC_MAX_K

#: Per-grid-step VMEM working-set budget — the ONE shared constant in
#: ops/vmem.py (ISSUE 20 unified this module's copy with
#: overlap_collectives'). The flagship (12.6 MB fp32 weights + 1.05 MB
#: bf16 row) fits single-buffered; the planner's
#: ``fits_double_buffered`` answers the cross-layer double-buffering
#: question statically (it does NOT fit at 14 MiB — PERF.md "Kernel
#: audit"), so the per-layer kernel remains the fallback if Mosaic
#: insists on prefetching.
_VMEM_BUDGET_BYTES = vmem.VMEM_BUDGET_BYTES

#: LoRA site order the kernel threads factors in (a subset, filtered by
#: presence in the model's "lora" collection).
_LORA_ATTN_SITES = ("q_proj", "k_proj", "v_proj", "out_proj")
_LORA_MLP_SITES = ("fc1", "fc2")

_LN_EPS = 1e-6  # flax.linen.LayerNorm default, the model's setting


def supports_fused_layers(cfg, t: int = 1) -> bool:
    """Whether the megakernel can serve ``cfg``'s decode at verify-window
    width ``t`` (1 = plain single-token decode).

    MoE blocks (expert dispatch inside a kernel is future work), caches
    past the single-tile bound, and per-step working sets over the VMEM
    budget all decline — callers fall back to the per-layer path. The
    byte accounting is :func:`dtc_tpu.ops.vmem.fused_layers_plan` —
    derived from the SAME grid plan :func:`_fused_layers_call` builds
    its BlockSpecs from, and t-aware since ISSUE 20: a speculative
    verify window's k query/score rows, k cache writes per layer, and
    k-wide residual scratch are priced as a surcharge over the
    single-query baseline instead of riding a gate that only priced one
    row."""
    return vmem.fused_layers_plan(cfg, t=t)["fits"]


def use_fused_layers(cfg, t_new: int, verify: bool = False) -> bool:
    """The decode_step routing predicate: knob on, single-token call (or
    a ``verify`` call of up to ``_SPEC_MAX_K`` query positions — the
    speculative k-token verify, ISSUE 19), supported shape AT THIS
    WIDTH (the planner prices the verify window's working set, not just
    a single query row). Prefill (multi-token WITHOUT ``verify``) keeps
    falling back to the per-layer path: a prompt pass is compute-bound
    and belongs to XLA's fusions, while a verify window is the same
    frontier-append regime as decode."""
    ok_t = t_new == 1 or (verify and 2 <= t_new <= _SPEC_MAX_K)
    return (
        getattr(cfg, "decode_attention", None) == "fused_layers"
        and ok_t
        and supports_fused_layers(cfg, t=t_new)
    )


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _fused_layers_kernel(
    *refs,
    h, d, s, t, dm, quant, per_row, lora_sites, lora_per_row, lora_scale,
    cdtype, kv_dtype,
):
    """One (layer, batch-row) grid step of the fused decode block.

    ``t`` is the number of in-register query positions: 1 for plain
    decode, or the speculative verify window (ISSUE 19) — the ``t``
    tokens all sit at the frontier (positions ``start .. start+t-1``),
    attend to cache columns ``< start`` plus each other causally
    in-register, and their k/v land in the ``(.., t, ..)`` frontier
    updates the caller scatters in one slice.

    ``refs`` order (inputs, then outputs, then scratch — the pallas_call
    contract): frontier (SMEM), x, 16 weight blocks (ln1 s/b, q/k/v/out
    kernel+bias, ln2 s/b, fc1/fc2 kernel+bias), K cache row, V cache row,
    [k/v scale rows], LoRA a/b pairs per site; x_out, k_new, v_new,
    [k/v scale_new]; x carry scratch."""
    it = iter(refs)
    idx_ref, x_ref = next(it), next(it)
    (ln1s, ln1b, wq, bq, wk, bk, wv, bv, wo, bo,
     ln2s, ln2b, w1, b1, w2, b2) = (next(it) for _ in range(16))
    k_ref, v_ref = next(it), next(it)
    ks_ref = vs_ref = None
    if quant:
        ks_ref, vs_ref = next(it), next(it)
    lora_refs = {site: (next(it), next(it)) for site in lora_sites}
    x_out = next(it)
    k_out, v_out = next(it), next(it)
    ks_out = vs_out = None
    if quant:
        ks_out, vs_out = next(it), next(it)
    x_scr = next(it)

    l = pl.program_id(0)
    b = pl.program_id(1)
    start = idx_ref[b] if per_row else idx_ref[0]
    att_scale = float(d) ** -0.5

    @pl.when(l == 0)
    def _():
        x_scr[pl.ds(b, 1)] = x_ref[0][None]

    x = x_scr[pl.ds(b, 1)][0]                       # (t, dm) residual

    def ln(xx, s_ref, b_ref):
        # flax LayerNorm, op-for-op: fp32 fast-variance stats clipped at
        # zero, (x - mean) * (rsqrt(var + eps) * scale) + bias, fp32 out.
        xf = xx.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.maximum(
            0.0, jnp.mean(xf * xf, axis=-1, keepdims=True) - mean * mean
        )
        mul = jax.lax.rsqrt(var + _LN_EPS) * s_ref[:]
        return (xf - mean) * mul + b_ref[:]

    def dense(xx, w_ref, bias_ref):
        # nn.Dense: inputs/kernel/bias promoted to compute dtype, plain
        # dot_general (output dtype = compute dtype), bias added after.
        return jax.lax.dot_general(
            xx.astype(cdtype), w_ref[0].astype(cdtype),
            (((1,), (0,)), ((), ())),
        ) + bias_ref[:].astype(cdtype)

    def lora(site, xx, y):
        # adapters/lora.apply_lora: y + scale * ((x @ A) @ B), factors
        # cast to compute dtype; per-row factors index this row's block.
        if site not in lora_refs:
            return y
        a_ref, b_ref = lora_refs[site]
        av = (a_ref[0, 0] if lora_per_row else a_ref[0]).astype(cdtype)
        bv = (b_ref[0, 0] if lora_per_row else b_ref[0]).astype(cdtype)
        z = jax.lax.dot_general(
            xx.astype(cdtype), av, (((1,), (0,)), ((), ())),
        )
        delta = jax.lax.dot_general(z, bv, (((1,), (0,)), ((), ())))
        return y + (lora_scale * delta).astype(y.dtype)

    # ---- attention leg ----
    h_ln = ln(x, ln1s, ln1b).astype(cdtype)
    q_vec = lora("q_proj", h_ln, dense(h_ln, wq, bq))       # (t, hd)
    k_vec = lora("k_proj", h_ln, dense(h_ln, wk, bk))
    v_vec = lora("v_proj", h_ln, dense(h_ln, wv, bv))

    kt, vt = k_ref[0, 0], v_ref[0, 0]                        # (s, hd)
    ks = ks_ref[0, 0] if quant else None                     # (s, h) fp32
    vs = vs_ref[0, 0] if quant else None
    col = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    mask = col < start  # strictly: the current tokens ride in-register
    # Causal mask AMONG the t in-register positions: row j (cache slot
    # start+j) sees in-register columns 0..j — together with the strict
    # cache mask this is exactly the oracle's ``col <= start + row``.
    rowq = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    colq = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    nmask = colq <= rowq
    if not quant:
        k_out[0, 0] = k_vec.astype(kv_dtype)
        v_out[0, 0] = v_vec.astype(kv_dtype)

    outs = []
    for gg in range(h):
        sl = slice(gg * d, (gg + 1) * d)
        # The current tokens' k/v, exactly as a reader would see them
        # AFTER the cache write: quantize (per-(position, head) fp32
        # scale, the quantize_kv reference arithmetic) then dequantize
        # in-register — int8 attention is bit-identical to the oracle's
        # write-then-dequant, and the raw values never touch HBM.
        if quant:
            kf = k_vec[:, sl].astype(jnp.float32)
            vf = v_vec[:, sl].astype(jnp.float32)
            k_sc = jnp.maximum(
                jnp.max(jnp.abs(kf), axis=-1, keepdims=True), KV_SCALE_FLOOR
            ) / 127.0                                # (t, 1)
            v_sc = jnp.maximum(
                jnp.max(jnp.abs(vf), axis=-1, keepdims=True), KV_SCALE_FLOOR
            ) / 127.0
            kq = jnp.clip(jnp.round(kf / k_sc), -127.0, 127.0)
            vq = jnp.clip(jnp.round(vf / v_sc), -127.0, 127.0)
            k_out[0, 0, :, sl] = kq.astype(kv_dtype)
            v_out[0, 0, :, sl] = vq.astype(kv_dtype)
            ks_out[0, 0, :, gg:gg + 1] = k_sc
            vs_out[0, 0, :, gg:gg + 1] = v_sc
            k_new = (kq * k_sc).astype(cdtype)
            v_new = (vq * v_sc).astype(cdtype)
            k_h = (kt[:, sl].astype(jnp.float32) * ks[:, gg:gg + 1]).astype(cdtype)
            v_h = (vt[:, sl].astype(jnp.float32) * vs[:, gg:gg + 1]).astype(cdtype)
        else:
            k_new = k_vec[:, sl].astype(kv_dtype).astype(cdtype)
            v_new = v_vec[:, sl].astype(kv_dtype).astype(cdtype)
            k_h, v_h = kt[:, sl], vt[:, sl]
            if k_h.dtype != cdtype:
                k_h, v_h = k_h.astype(cdtype), v_h.astype(cdtype)
        q_h = q_vec[:, sl] * att_scale
        sc = jax.lax.dot_general(
            q_h, k_h, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                            # (t, s) fp32
        sc = jnp.where(mask, sc, NEG_INF)
        sc_new = jax.lax.dot_general(
            q_h, k_new, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                            # (t, t) fp32
        sc_new = jnp.where(nmask, sc_new, NEG_INF)
        m = jnp.maximum(
            jnp.max(sc, axis=-1, keepdims=True),
            jnp.max(sc_new, axis=-1, keepdims=True),
        )                                            # (t, 1); row 0's own
        # diagonal score is always live, so m is finite even at start==0
        p = jnp.exp(sc - m)
        p_new = jnp.exp(sc_new - m)                  # masked cols -> 0
        lsum = (
            jnp.sum(p, axis=-1, keepdims=True)
            + jnp.sum(p_new, axis=-1, keepdims=True)
        )
        acc = jax.lax.dot_general(
            p.astype(v_h.dtype), v_h, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) + jax.lax.dot_general(
            p_new, v_new.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                            # (t, d) fp32
        outs.append((acc / lsum).astype(cdtype))
    attn = jnp.concatenate(outs, axis=1)             # (t, hd)
    o = lora("out_proj", attn, dense(attn, wo, bo))
    x = x + o.astype(x.dtype)

    # ---- MLP leg ----
    h2 = ln(x, ln2s, ln2b).astype(cdtype)
    m1 = lora("fc1", h2, dense(h2, w1, b1))
    g = jax.nn.gelu(m1, approximate=True)            # flax nn.gelu default
    m2 = lora("fc2", g, dense(g, w2, b2))
    x = x + m2.astype(x.dtype)

    x_scr[pl.ds(b, 1)] = x[None]
    x_out[0] = x  # last write (l == L-1) wins; earlier flushes are dead


# ---------------------------------------------------------------------------
# host-side wrapper + the decode-step orchestration
# ---------------------------------------------------------------------------


def _lora_inputs(lora_tree, cfg):
    """Flatten the "lora" subtree into the kernel's (sites, arrays,
    per_row) in canonical site order; absent sites simply don't appear
    (un-targeted modules, MoE's missing fc1/fc2)."""
    if lora_tree is None:
        return (), [], False
    sites, arrays = [], []
    per_row = False
    groups = (
        ("attn", _LORA_ATTN_SITES),
        ("mlp", _LORA_MLP_SITES),
    )
    for mod, names in groups:
        sub = lora_tree.get(mod, {}) if isinstance(lora_tree, dict) else {}
        for site in names:
            a = sub.get(f"{site}_a")
            if a is None:
                continue
            sites.append(site)
            arrays.extend([a, sub[f"{site}_b"]])
            per_row = a.ndim == 4
    return tuple(sites), arrays, per_row


def _fused_layers_call(x, blocks_p, blocks_c, idx, lora_tree, cfg):
    """Invoke the megakernel: ``x`` (B, t, d_model) post-embed residual
    (t == 1 for plain decode, t <= ``_SPEC_MAX_K`` for a speculative
    verify window), ``blocks_p`` the stacked block params, ``blocks_c``
    the attn cache subtree, ``idx`` the scalar or (B,) frontier. Returns
    ``(x_out, writes)`` where ``writes`` maps cache leaf name -> the
    (L, B, t, ...) frontier updates the caller scatters in."""
    b, t = x.shape[0], x.shape[1]
    dm, H, D = cfg.d_model, cfg.n_heads, cfg.head_dim
    hd, L, S = H * D, cfg.n_layers, cfg.max_seq_len
    cdtype = _DTYPES[cfg.compute_dtype]
    quant = cfg.kv_quantized
    kv_dtype = jnp.int8 if quant else _DTYPES[cfg.kv_store_dtype]

    idx = jnp.asarray(idx, jnp.int32)
    per_row = idx.ndim == 1
    idx_arr = idx if per_row else idx.reshape((1,))

    attn_p, mlp_p = blocks_p["attn"], blocks_p["mlp"]
    weights = [
        blocks_p["ln_1"]["scale"], blocks_p["ln_1"]["bias"],
        attn_p["q_proj"]["kernel"], attn_p["q_proj"]["bias"],
        attn_p["k_proj"]["kernel"], attn_p["k_proj"]["bias"],
        attn_p["v_proj"]["kernel"], attn_p["v_proj"]["bias"],
        attn_p["out_proj"]["kernel"], attn_p["out_proj"]["bias"],
        blocks_p["ln_2"]["scale"], blocks_p["ln_2"]["bias"],
        mlp_p["fc1"]["kernel"], mlp_p["fc1"]["bias"],
        mlp_p["fc2"]["kernel"], mlp_p["fc2"]["bias"],
    ]
    lora_sites, lora_arrays, lora_per_row = _lora_inputs(lora_tree, cfg)

    # Block shapes and index maps come from the shared static planner —
    # the SAME grid plan ops/vmem.fused_layers_plan prices and the
    # kernel auditor (analysis/kernels.py) lints, so the VMEM gate, the
    # committed baselines, and the launched kernel cannot drift apart.
    plan = vmem.fused_layers_grid_plan(
        cfg, t=t, b=b, lora_sites=lora_sites, lora_per_row=lora_per_row,
    )

    def _spec(entry):
        _name, shape, imap, space, _nbytes = entry
        if space == "smem":
            return pl.BlockSpec(memory_space=pltpu.SMEM)
        return pl.BlockSpec(shape, imap)

    in_specs = [_spec(e) for e in plan["in_specs"]]
    args = [idx_arr, x, *weights, blocks_c["k"], blocks_c["v"]]
    if quant:
        args += [blocks_c["k_scale"], blocks_c["v_scale"]]
    args += lora_arrays

    out_specs = [_spec(e) for e in plan["out_specs"]]
    out_shapes = [
        jax.ShapeDtypeStruct((b, t, dm), cdtype),                  # x_out
        jax.ShapeDtypeStruct((L, b, t, hd), kv_dtype),             # k_new
        jax.ShapeDtypeStruct((L, b, t, hd), kv_dtype),             # v_new
    ]
    if quant:
        out_shapes += [jax.ShapeDtypeStruct((L, b, t, H), jnp.float32)] * 2

    res = pl.pallas_call(
        functools.partial(
            _fused_layers_kernel,
            h=H, d=D, s=S, t=t, dm=dm, quant=quant, per_row=per_row,
            lora_sites=lora_sites, lora_per_row=lora_per_row,
            lora_scale=float(cfg.adapter.scale), cdtype=cdtype,
            kv_dtype=kv_dtype,
        ),
        grid=(L, b),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM(shape, cdtype) for shape, _nb in plan["scratch"]
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=_interpret(),
    )(*args)

    writes = {"k": res[1], "v": res[2]}
    if quant:
        writes["k_scale"], writes["v_scale"] = res[3], res[4]
    return res[0], writes


def _scatter_frontier(cache_leaf, update, idx):
    """Write the (L, B, t, X) frontier updates into the (L, B, S, X)
    stacked cache at the scalar — or per-row (B,) — frontier: ONE
    dynamic update per leaf for the whole layer stack (the O(1)-launch
    property the megakernel exists for). ``t`` rows land contiguously at
    ``idx .. idx+t-1`` — the verify window's k positions in one slice."""
    if idx.ndim == 0:
        return jax.lax.dynamic_update_slice(
            cache_leaf, update, (0, 0, idx, 0)
        )
    return jax.vmap(
        lambda c, u, i: jax.lax.dynamic_update_slice(c, u, (0, i, 0)),
        in_axes=(1, 1, 0), out_axes=1,
    )(cache_leaf, update, idx)


def _block_subtree(tree):
    """Descend a "stage"/"blocks" collection subtree to the per-block
    module level. The scanned ``_ScanBlock`` wraps ``Block`` as one
    auto-named child (``Block_0``), so the module dict ("attn"/"ln_1"/…)
    sits one level below "blocks" — tolerate either nesting so a future
    pinned-name refactor cannot silently break this path."""
    sub = tree["stage"]["blocks"]
    if "attn" not in sub and len(sub) == 1:
        sub = next(iter(sub.values()))
    return sub


def fused_decode_step(model, params, cache, tok, lora=None):
    """The ``decode_attention: fused_layers`` step —
    :func:`dtc_tpu.generate.decode_step`'s fast path, shared verbatim by
    the greedy scan and the serving engine. ``tok`` is (B, 1) for plain
    decode or (B, k) for a speculative verify window (ISSUE 19): the k
    logits rows come back in ONE launch, the k cache writes land in one
    stacked scatter, and rollback after partial acceptance is a frontier
    decrement by the caller (positions past the frontier are invisible —
    every read masks ``col < frontier`` — and are rewritten by whichever
    later step advances over them, so no cache surgery ever happens).

    Embed and head apply the REAL flax modules on their param subtrees
    (identical ops to the per-layer path — parity by construction); the
    layer stack runs through the megakernel; the cache write is one
    stacked scatter per K/V (+scale) leaf; the GPT-level index advances
    by one. The returned cache has the exact pytree structure
    ``model.apply(..., mutable=["cache"])`` produces, so the engine's
    traced-slot surgery and checksum table consume it unchanged.

    CALLER CONTRACT (same as GPT.__call__): cumulative decoded length
    must stay <= ``cfg.max_seq_len`` — this path hosts no checkify guard
    (``generate`` enforces the bound statically; the engine's page
    accounting enforces it per slot)."""
    from dtc_tpu.models.gpt import GPTEmbed, GPTHead

    cfg = model.cfg
    t = tok.shape[1]
    idx = jnp.asarray(cache["index"], jnp.int32)
    h = GPTEmbed(cfg).apply(
        {"params": params["embed"]}, tok, train=False,
        pos_offset=idx, decode=True,
    )
    lora_tree = None if lora is None else _block_subtree(lora)
    attn_c = _block_subtree(cache)["attn"]
    h, writes = _fused_layers_call(
        h, _block_subtree(params), attn_c, idx, lora_tree, cfg,
    )
    logits = GPTHead(cfg).apply({"params": params["head"]}, h)
    new_attn = {
        name: _scatter_frontier(attn_c[name], upd, idx)
        for name, upd in writes.items()
    }
    # Rebuild the cache with the EXACT pytree structure model.apply
    # produces (including the scanned block's auto-name level), so the
    # engine's generic tree surgery and the greedy scan's carry both see
    # an unchanged treedef.
    blocks = dict(cache["stage"]["blocks"])
    if "attn" in blocks:
        blocks["attn"] = new_attn
    else:
        inner_name = next(iter(blocks))
        blocks[inner_name] = dict(blocks[inner_name], attn=new_attn)
    return {"index": idx + t, "stage": {"blocks": blocks}}, logits
