from dtc_tpu.ops import decode_attention, moe_dispatch
from dtc_tpu.ops.attention import causal_attention

__all__ = ["causal_attention", "decode_attention", "moe_dispatch"]
