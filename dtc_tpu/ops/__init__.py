from dtc_tpu.ops import decode_attention, decode_fused, moe_dispatch
from dtc_tpu.ops.attention import causal_attention

__all__ = [
    "causal_attention", "decode_attention", "decode_fused", "moe_dispatch",
]
