# NOTE: overlap_collectives is deliberately NOT imported here — its
# transitive flash_attention -> utils.compat -> utils (-> metrics ->
# models.gpt) chain closes an import cycle when the package is loaded
# from models.gpt's own `from dtc_tpu.ops.attention import ...`. Import
# it directly (`from dtc_tpu.ops import overlap_collectives` works as a
# submodule import without package-level re-export).
from dtc_tpu.ops import decode_attention, decode_fused, moe_dispatch
from dtc_tpu.ops.attention import causal_attention

__all__ = [
    "causal_attention", "decode_attention", "decode_fused", "moe_dispatch",
]
