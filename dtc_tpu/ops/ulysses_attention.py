"""Ulysses sequence parallelism — all-to-all head-sharded attention.

The second sequence-parallel scheme next to ring attention
(ops/ring_attention.py), after DeepSpeed-Ulysses: activations travel the
network SEQUENCE-sharded over the "model" mesh axis (same RING_RULES layout
— LN/MLP/projections are embarrassingly sequence-parallel), and at the
attention boundary the shard axis is SWAPPED — sequence gathered, heads
scattered — so each device runs ordinary *local* causal attention over the
full sequence for its n_heads/P heads, then swaps back.

TPU-native design: the swap is NOT a hand-written collective. It is two
sharding constraints — seq-sharded -> head-sharded and back — and XLA's
SPMD partitioner emits the all-to-alls over ICI. Consequences the explicit
ring cannot have:

- The inner computation is just ``causal_attention(impl="auto")``: the
  packed Pallas flash kernel runs unchanged (ring needed dedicated
  block kernels and a whole-ring custom VJP).
- No nested ``shard_map``, so Ulysses composes with PIPELINE parallelism
  (the ring's manual region cannot nest inside the pipeline's — the
  trainer rejects that combination; Ulysses it accepts).
- Backward is plain autodiff; the all-to-alls transpose to all-to-alls.

Tradeoffs vs ring (when to use which): Ulysses moves 4 × activation-sized
all-to-alls per layer and needs n_heads % P == 0 (parallelism capped by
head count); ring moves 2 × KV per ring step with compute that hides the
transfers and scales to any P dividing the sequence. Reference anchor:
SURVEY §2.2 lists Ulysses as absent upstream ("not required for parity");
this implements it anyway for capability completeness.
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P


def ulysses_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "model",
    block_q: int = 512,
    block_kv: int = 512,
    block_q_bwd: int = 0,
    block_kv_bwd: int = 0,
) -> jax.Array:
    """Causal attention over ``(B, T, H, D)`` with T sharded over
    ``axis_name`` on entry/exit and H sharded inside. Call under an active
    mesh; ``H`` must divide evenly by the axis size."""
    from jax._src.core import trace_state_clean

    from dtc_tpu.ops.attention import causal_attention, dense_causal_attention
    from dtc_tpu.ops.ring_attention import _ambient_mesh

    if trace_state_clean():
        # Eager call (flax model.init): constraints need a jit trace; the
        # dense path is numerically identical and init only needs shapes.
        return dense_causal_attention(q, k, v)

    mesh = _ambient_mesh()
    par = mesh.shape[axis_name]
    h = q.shape[2]
    if par > 1 and h % par != 0:
        raise ValueError(
            f"ulysses attention needs n_heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({par})"
        )
    # seq-sharded -> head-sharded: XLA inserts the all-to-all.
    head_spec = P(None, None, axis_name, None)
    q, k, v = (jax.lax.with_sharding_constraint(x, head_spec) for x in (q, k, v))
    out = causal_attention(
        q, k, v, impl="auto", block_q=block_q, block_kv=block_kv,
        block_q_bwd=block_q_bwd, block_kv_bwd=block_kv_bwd,
    )
    # head-sharded -> seq-sharded: the inverse all-to-all.
    return jax.lax.with_sharding_constraint(out, P(None, axis_name, None, None))
