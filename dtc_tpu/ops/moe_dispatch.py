"""MoE token dispatch/combine — the two interchangeable routing backends.

The MoE FFN decomposes into (routing) -> (dispatch) -> (expert FFN) ->
(combine). Routing — softmax over router logits, top-k choice, gate
normalization, choice-major capacity fill, the Switch load-balance loss —
is computed ONCE here (:func:`top_k_routing`) and shared by both dispatch
backends, so switching ``moe_dispatch`` can never change which tokens go
where, which assignments are dropped, or the aux loss: only how the
token<->slot permutation is *executed*.

Backends (``ModelConfig.moe_dispatch``):

- ``einsum`` — GShard/Switch-style static one-hot dispatch/combine tensors
  ``(B, T, E, cap)`` contracted over T. Gather-free, MXU-shaped, but the
  dispatch/combine work grows linearly with E·cap: measured ~25-30 ms
  (~18% of the 162 ms step) at E=8 on a v5e (PERF.md round 5), the cost
  this module's second backend exists to A/B against.
- ``sort`` — MegaBlocks-style (Gale et al., 2022) sorted/segmented
  routing on static capacity: each kept assignment's destination slot
  ``expert·cap + position`` is already known from routing, so dispatch is
  an int32 slot->token permutation (scatter of indices, O(B·T·k)) plus a
  row gather into per-expert contiguous groups ``(B, E, cap, d)``, and
  combine is a row gather back weighted by the gates. Data movement is
  O(B·T·k·d) regardless of E — no (B,T,E,cap) tensors anywhere.

Both backends produce the per-expert grouped activations the SAME shape
``(B, E, cap, d)``, run the identical grouped expert FFN
(:func:`expert_ffn` — einsum over the stacked ``(E, d, d_ff)`` weights,
contiguous per-expert token blocks: a blocked matmul), and carry the same
"experts" logical axis, so the EP rule row (experts -> "model",
``parallel/sharding.py``) and the all-to-all it induces hold for either.

Everything here is pure jnp — unit-tested against a brute-force per-token
reference in ``tests/test_moe.py`` and A/B-benched in ``bench.py`` /
``scripts/sweep_moe.py``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

MOE_DISPATCH_MODES = ("einsum", "sort")


class Routing(NamedTuple):
    """Routing decisions for one MoE layer, shared by both backends.

    Shapes: B batch, T tokens/row, E experts, k choices/token, cap
    slots/expert. The capacity fill is CHOICE-major (every token's top-1
    claims slots across the sequence before any top-2 — GShard's
    offset-by-previous-round semantics), so ``pos``/``keep`` encode the
    drop policy exactly; backends must not re-derive it.
    """

    probs: jax.Array   # (B, T, E) fp32 router softmax
    gates: jax.Array   # (B, T, k) fp32 renormalized top-k gates
    idx: jax.Array     # (B, T, k) int32 expert choice per (token, rank)
    pos: jax.Array     # (B, T, k) int32 slot within the chosen expert
    keep: jax.Array    # (B, T, k) fp32 1.0 kept / 0.0 capacity-dropped
    picked: jax.Array  # (B, T, E) fp32 sum of choice one-hots (aux loss)
    counts: jax.Array  # (B, E) fp32 total assignments per expert (pre-drop)


def top_k_routing(probs: jax.Array, k: int, cap: int) -> Routing:
    """Top-k choices + choice-major static-capacity fill from router
    ``probs`` (fp32, softmaxed). One definition of the drop policy for
    every dispatch backend."""
    b, t, e = probs.shape
    gates, idx = jax.lax.top_k(probs, k)                     # (B,T,k)
    gates = gates / jnp.sum(gates, axis=-1, keepdims=True)

    counts = jnp.zeros((b, e), jnp.float32)
    picked = jnp.zeros((b, t, e), jnp.float32)
    pos_l, keep_l = [], []
    for j in range(k):
        m = jax.nn.one_hot(idx[..., j], e, dtype=jnp.float32)  # (B,T,E)
        picked = picked + m
        # Slot index within the expert: running count over the sequence
        # plus everything earlier routing choices already claimed.
        pos_e = jnp.cumsum(m, axis=1) - m + counts[:, None, :]
        keep_e = jnp.where(pos_e < cap, m, 0.0)
        # Collapse the (B,T,E) grids to per-assignment scalars: at most
        # one nonzero per (b,t) row (the chosen expert), so the sums are
        # exact picks, not reductions.
        pos_l.append(jnp.sum(pos_e * m, axis=-1).astype(jnp.int32))
        keep_l.append(jnp.sum(keep_e, axis=-1))
        counts = counts + jnp.sum(m, axis=1)

    return Routing(
        probs=probs, gates=gates, idx=idx,
        pos=jnp.stack(pos_l, axis=-1), keep=jnp.stack(keep_l, axis=-1),
        picked=picked, counts=counts,
    )


def load_balance_loss(r: Routing, k: int, coef: float) -> jax.Array:
    """Switch load-balance loss (Fedus et al. eq. 4-6), coefficient
    pre-applied: coef · E · Σ_e f_e · P_e. Pure function of the shared
    routing, so it is bitwise-identical whichever backend executes."""
    e = r.probs.shape[-1]
    f = jnp.mean(r.picked, axis=(0, 1)) / k
    p_mean = jnp.mean(r.probs, axis=(0, 1))
    return coef * e * jnp.sum(f * p_mean)


def expert_ffn(x_e, wi, bi, wo, bo):
    """Grouped expert FFN over ``(B, E, cap, d)`` token groups: each
    expert's ``cap`` tokens are contiguous, so the einsums over the
    stacked ``(E, d, d_ff)`` weights are blocked per-expert matmuls.
    Shared verbatim by both backends — only dispatch/combine differ."""
    h = jax.nn.gelu(
        jnp.einsum("becd,edf->becf", x_e, wi) + bi[None, :, None, :]
    )
    return jnp.einsum("becf,efd->becd", h, wo) + bo[None, :, None, :]


# ---------------------------------------------------------------------------
# einsum backend: one-hot (B,T,E,cap) dispatch/combine tensors
# ---------------------------------------------------------------------------


def dispatch_combine_tensors(r: Routing, cap: int) -> tuple[jax.Array, jax.Array]:
    """One-hot dispatch/combine tensors ``(B, T, E, cap)`` fp32 from the
    shared routing — the static einsum-backend permutation encoding.

    fp32 is deliberate: building them in bf16 measured 160.1 vs 158.5 ms
    (no change — XLA fuses the buildup into its consumers, PERF.md r5).
    """
    e = r.probs.shape[-1]
    k = r.idx.shape[-1]
    dispatch = None
    combine = None
    for j in range(k):
        m = jax.nn.one_hot(r.idx[..., j], e, dtype=jnp.float32)      # (B,T,E)
        # one_hot of an out-of-capacity pos is all-zero and keep is 0.0
        # there too, so dropped assignments vanish from both tensors.
        slot = (
            jax.nn.one_hot(r.pos[..., j], cap)                       # (B,T,cap)
            [..., None, :] * m[..., None] * r.keep[..., j][..., None, None]
        )                                                            # (B,T,E,cap)
        dispatch = slot if dispatch is None else dispatch + slot
        c = slot * r.gates[..., j][..., None, None]
        combine = c if combine is None else combine + c
    return dispatch, combine


def einsum_dispatch(x: jax.Array, dispatch: jax.Array) -> jax.Array:
    """Gather-free dispatch: contract the one-hot ``dispatch`` tensor over
    T. Returns per-expert groups ``(B, E, cap, d)`` in ``x.dtype``.

    Takes the prebuilt tensor (not the Routing) so the caller builds the
    dispatch/combine pair ONCE per layer — the k-round one-hot buildup is
    ~18% of the E=8 step (PERF.md) and must not be traced twice.
    """
    return jnp.einsum("btec,btd->becd", dispatch.astype(x.dtype), x)


def einsum_combine(y_e: jax.Array, combine: jax.Array) -> jax.Array:
    """Combine ``(B, E, cap, d)`` expert outputs back to ``(B, T, d)``
    through the prebuilt gate-weighted ``combine`` tensor; dropped tokens
    contribute zero."""
    return jnp.einsum("btec,becd->btd", combine.astype(y_e.dtype), y_e)


# ---------------------------------------------------------------------------
# sort backend: slot->token permutation + segment gathers
# ---------------------------------------------------------------------------


def _dest_slots(r: Routing, cap: int) -> jax.Array:
    """Flat destination slot ``expert·cap + pos`` per assignment
    ``(B, T, k)`` int32; capacity-dropped assignments point one past the
    end (E·cap), where scatters drop and gathers are masked out."""
    e = r.probs.shape[-1]
    return jnp.where(
        r.keep > 0.0, r.idx * cap + r.pos, jnp.int32(e * cap)
    ).astype(jnp.int32)


def slot_to_token(r: Routing, cap: int) -> tuple[jax.Array, jax.Array]:
    """Invert the routing into the slot->token permutation.

    Returns ``(src, filled)``: ``src`` (B, E·cap) int32 maps each expert
    slot to the token index that fills it (0 where empty — masked by
    ``filled`` (B, E, cap) fp32). O(B·T·k) int32 scatter; kept slots are
    written exactly once (slot assignment is a bijection on kept
    assignments), drops fall off the end via ``mode="drop"``.
    """
    b, t, k = r.idx.shape
    e = r.probs.shape[-1]
    dest = _dest_slots(r, cap).reshape(b, t * k)
    tok = jnp.broadcast_to(
        jnp.arange(t, dtype=jnp.int32)[None, :, None], (b, t, k)
    ).reshape(b, t * k)
    src = jnp.zeros((b, e * cap), jnp.int32)
    src = jax.vmap(lambda s, d, v: s.at[d].set(v, mode="drop"))(src, dest, tok)
    # A slot (e, c) is filled iff c < min(count_e, cap): per-expert fill
    # is sequential from 0, so filled slots are a prefix of each segment.
    filled = (
        jnp.arange(cap, dtype=jnp.float32)[None, None, :]
        < jnp.minimum(r.counts, float(cap))[:, :, None]
    ).astype(jnp.float32)
    return src, filled


def sort_dispatch(x: jax.Array, r: Routing, cap: int) -> jax.Array:
    """Dispatch by permutation: gather each slot's token row into its
    expert's contiguous segment. Data moved is O(B·E·cap·d) rows — no
    (B,T,E,cap) intermediates; empty slots are zeroed so the grouped FFN
    sees exactly what the einsum backend produces."""
    b, t, d = x.shape
    e = r.probs.shape[-1]
    src, filled = slot_to_token(r, cap)
    x_e = jnp.take_along_axis(x, src[..., None], axis=1)     # (B, E·cap, d)
    x_e = x_e * filled.reshape(b, e * cap, 1).astype(x.dtype)
    return x_e.reshape(b, e, cap, d)


def sort_combine(y_e: jax.Array, r: Routing, cap: int) -> jax.Array:
    """Combine by permutation: gather each assignment's expert output from
    its slot and sum the k gate-weighted contributions per token. Dropped
    assignments gather slot 0 of a clipped index but are zeroed by
    ``keep`` (the residual stream carries those tokens, Switch
    semantics)."""
    b, e, cap_, d = y_e.shape
    t, k = r.idx.shape[1], r.idx.shape[-1]
    dest = _dest_slots(r, cap)                               # (B, T, k)
    flat = y_e.reshape(b, e * cap, d)
    safe = jnp.minimum(dest, e * cap - 1).reshape(b, t * k)
    y_a = jnp.take_along_axis(flat, safe[..., None], axis=1).reshape(b, t, k, d)
    w = (r.gates * r.keep).astype(y_e.dtype)                 # (B, T, k)
    return jnp.sum(y_a * w[..., None], axis=2)
