"""Overlapped training collectives — fused all-gather-matmul and streamed
grad reduce-scatter over the FSDP ring (ROADMAP item 2, ISSUE 12).

The problem: XLA serializes FSDP's parameter all-gathers against the
matmuls that consume them and the gradient reduce-scatters against the
matmuls that produce them — PR 8's device-time observatory measures a
comm/compute ``overlap_ratio`` of **0.0** on the b8 reference. This module
implements the decomposition-and-overlap technique of Wang et al.
("Overlap Communication with Dependent Computation via Decomposition",
ASPLOS '23) as explicit ring schedules:

- **all-gather-then-matmul** (forward + the backward re-gather): each ICI
  ring step matmuls the parameter shard the device already holds while
  the next shard streams in — the gather hides entirely under the layer's
  MXU time.
- **streamed reduce-scatter-of-grads** (backward): grad blocks pipeline
  through the ring while the matmuls producing the later blocks are still
  running, partial-sum accumulation riding the permute.

Two interchangeable transports, one schedule:

- ``pallas`` — genuinely fused kernels: ``pltpu.make_async_remote_copy``
  RDMAs the next shard chip-to-chip while ``jnp.dot`` runs on the current
  one (the SNIPPETS [1]/[2] mechanism; same discipline as jax's
  pedagogical ring all-gather: per-chunk receive slots so no buffer is
  ever reused, chained DMA waits, a neighbor barrier on hardware).
  CPU-interpret mode runs the SAME kernels for the parity tests.
- ``decomposed`` — the ring unrolled as ``lax.ppermute`` + per-block
  ``jnp.dot`` at the XLA level. TPU's async collective-permute lets the
  scheduler overlap each permute with the previous block's matmul (the
  paper's "decomposition" without hand-written DMA); this is also the
  backend for shapes the Pallas kernels decline (blocks too small to
  lane-align on hardware, VMEM overflow) and — interpret-mode-only
  limitation — for multi-axis manual meshes off-TPU.

Both run inside a ``shard_map`` manual over the FSDP axis
(``parallel.sharding.fsdp_axis_in_scope`` finds it from the active
logical-axis rules) AND, on DP×FSDP×TP meshes, the Megatron axis — with
the two row-parallel psums explicit in the custom VJP. Full-manual over
every non-trivial axis is load-bearing twice: this jax's SPMD partitioner
rejects collectives in PARTIAL-manual regions (the PP / fsdp+ring
known-env-failure class), and a fully-local region is what lets the
Pallas kernels run under TP at all (a ``pallas_call`` cannot partition
over auto axes).

Numerics: partials accumulate in fp32 (``preferred_element_type``) and
cast to the input dtype once, so bf16 rings match the single-dot XLA
oracle to fp roundoff (asserted in tests/test_overlap_collectives.py).

Auto-fallback ladder (``overlap_dense_matmul``): eager trace / no mesh /
unmapped FSDP axis / ring of 1 / non-divisible shard or batch tails ->
the plain single dot (GSPMD's serialized path); pallas -> decomposed for
blocks too small to lane-align on hardware or VMEM overflow. The ladder
is what lets ``collectives: overlapped`` stay safe on any config — it
only changes programs it can provably take over.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from dtc_tpu.ops import vmem
from dtc_tpu.ops.flash_attention import _interpret  # noqa: F401  (shared gate)
from dtc_tpu.utils.compat import shard_map

#: VMEM budget for the fused kernels — the ONE shared constant in
#: ops/vmem.py (ISSUE 20 unified this module's copy with
#: decode_fused's): operands + per-chunk receive slots + the f32
#: accumulator must fit, else the decomposed ring runs.
_VMEM_BUDGET_BYTES = vmem.VMEM_BUDGET_BYTES

#: Lane-dim dynamic slices inside the kernels start at ``block * step``;
#: Mosaic wants them 128-aligned on hardware (interpret mode does not
#: care — how the tiny-mesh CPU tests drive the real kernels).
_LANE = vmem.LANE

#: DMA-schedule recording seam (ISSUE 20). When
#: ``analysis/kernels.capture_schedule`` installs a list here, the ring
#: kernels append one dict per schedule event — DMA start/wait, shared-
#: buffer load/store — at kernel TRACE time. Events carry only STATIC
#: metadata (ring step ``s``, buffer name, symbolic slot): under
#: shard_map the kernel body traces once with ``lax.axis_index`` a
#: tracer, so concrete slots are written as ("rel", off) =
#: ``(device_idx + off) % ring`` or ("abs", k), and the auditor
#: instantiates them per device to reconstruct the CONCURRENT schedule
#: interpret-mode execution serializes. Zero overhead when None (every
#: hook is a no-op attribute check).
_SCHED_LOG = None


def _sched(kind: str, **fields) -> None:
    if _SCHED_LOG is not None:
        _SCHED_LOG.append(dict(kind=kind, **fields))


def _backend_override() -> str:
    """DTC_OVERLAP env: '' = auto, 'pallas' | 'decomposed' force a
    transport (pallas off-TPU runs interpret mode — the test hook),
    '0'/'xla' disable the ring entirely (plain serialized dot)."""
    return os.environ.get("DTC_OVERLAP", "")


def _pallas_ok(
    m: int, k_loc: int, n_loc: int, ring: int, shard_axis: int,
    itemsize: int,
) -> bool:
    """Can the fused kernels take this matmul — INCLUDING its backward?
    (Shapes are the LOCAL shard_map-region shapes; ``m`` = flattened
    token rows per device.) One backend decision covers three kernel
    launches (fwd all-gather-matmul, the bwd dx re-gather, the bwd dw
    matmul+reduce-scatter), so the VMEM budget must clear the WORST of
    their working sets — gating on the forward alone would select pallas
    for a shape whose backward then dies in Mosaic instead of taking the
    documented decomposed fallback. The byte accounting is
    :func:`dtc_tpu.ops.vmem.overlap_plan` (the shared planner the
    kernel auditor baselines)."""
    plan = vmem.overlap_plan(m, k_loc, n_loc, ring, shard_axis, itemsize)
    if not _interpret() and not plan["lane_aligned"]:
        return False
    return plan["fits"]


def resolve_backend(
    m: int, k_loc: int, n_loc: int, ring: int, shard_axis: int,
    itemsize: int,
) -> str:
    """'pallas' | 'decomposed' | 'xla' for this (shape, env)."""
    ov = _backend_override()
    if ov in ("0", "xla"):
        return "xla"
    if ov == "decomposed":
        return "decomposed"
    if ov == "pallas":
        return "pallas"
    if jax.default_backend() == "tpu" and _pallas_ok(
        m, k_loc, n_loc, ring, shard_axis, itemsize
    ):
        return "pallas"
    return "decomposed"


# ---------------------------------------------------------------------------
# shared schedule helpers


def _right_perm(ring: int) -> list[tuple[int, int]]:
    return [(i, (i + 1) % ring) for i in range(ring)]


def _neighbor_device_id(mesh, axis_name: str, idx):
    """Remote-copy ``device_id`` for the right ring neighbor.

    Every non-trivial mesh axis is MANUAL here (the op shard_maps over
    the FSDP ring AND any TP axis — see overlap_dense_matmul), so each
    axis coordinate is available in-kernel: the ring axis steps to
    ``idx + 1``, size-1 axes sit at 0, and a manual TP axis keeps its own
    ``lax.axis_index``. Interpret mode supports only scalar LOGICAL ids —
    the row-major linearization of those coordinates; hardware gets the
    MESH coordinate tuple."""
    sizes = {n: int(s) for n, s in zip(mesh.axis_names, mesh.shape.values())}
    ring = sizes[axis_name]
    right = lax.rem(idx + 1, ring)
    coords = tuple(
        right if name == axis_name
        else (0 if sizes[name] == 1 else lax.axis_index(name))
        for name in mesh.axis_names
    )
    if not _interpret():
        return coords, pltpu.DeviceIdType.MESH
    linear = jnp.int32(0)
    for name, coord in zip(mesh.axis_names, coords):
        linear = linear * sizes[name] + coord
    return linear, pltpu.DeviceIdType.LOGICAL


def _neighbor_barrier(mesh, axis_name: str) -> None:
    """Both ring neighbors must be inside the kernel before any RDMA
    lands in their scratch. Hardware only: interpret mode has no barrier
    primitive — and no cross-kernel race either (the emulator sequences
    DMAs deterministically)."""
    if _interpret():
        return
    sizes = {n: int(s) for n, s in zip(mesh.axis_names, mesh.shape.values())}
    idx = lax.axis_index(axis_name)
    ring = sizes[axis_name]

    def coords(pos):
        return tuple(
            pos if name == axis_name
            else (0 if sizes[name] == 1 else lax.axis_index(name))
            for name in mesh.axis_names
        )

    sem = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(sem, 1, device_id=coords(lax.rem(idx + 1, ring)))
    pltpu.semaphore_signal(
        sem, 1, device_id=coords(lax.rem(idx - 1 + ring, ring))
    )
    pltpu.semaphore_wait(sem, 2)


def _contract(xs, w_cur, w_t: bool):
    """One ring step's partial matmul, fp32 accumulation. ``w_t`` selects
    which w axis contracts: False -> xs @ w_cur, True -> xs @ w_curᵀ."""
    dims = (((1,), (1,)), ((), ())) if w_t else (((1,), (0,)), ((), ()))
    return lax.dot_general(
        xs, w_cur, dims, preferred_element_type=jnp.float32
    )


def _grad_partial(a, b):
    """aᵀ @ b over the local token rows, fp32 — the per-block grad matmul
    both reduce-scatter transports stream through the ring."""
    return lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


# ---------------------------------------------------------------------------
# pallas transport — the genuinely fused kernels


def _overlap_ag_matmul_kernel(
    x_ref, w_ref, o_ref, w_slots, send_sem, recv_sem, *,
    ring, axis_name, mesh, slice_x, slice_out, w_t, blk_in, blk_out,
):
    """Fused ring all-gather-matmul: at step s the device matmuls the
    shard it holds (own at s=0, chunk ``(idx - s) % ring`` after) while
    the RDMA forwarding that shard to the right neighbor is in flight.

    Per-chunk receive slots (``w_slots[c]`` holds chunk c, written exactly
    once) + chained ``dma.wait()`` — the jax ring-all-gather discipline —
    so there is no buffer reuse and no flow-control semaphore needed.
    ``dma.wait()`` waits BOTH our send and the symmetric incoming copy, so
    reaching step s guarantees chunk ``(idx - s)`` has landed."""
    idx = lax.axis_index(axis_name)
    _sched("kernel", name="ag_matmul", ring=ring)
    _neighbor_barrier(mesh, axis_name)
    device_id, id_type = _neighbor_device_id(mesh, axis_name, idx)
    dma = None
    for s in range(ring):
        src = lax.rem(idx - s + ring, ring)
        if s > 0:
            _sched("dma_wait", step=s)
            dma.wait()
        if s < ring - 1:
            src_ref = w_ref if s == 0 else w_slots.at[src]
            # The copy lands in the RIGHT neighbor's w_slots at the same
            # chunk index (idx - s), i.e. the slot the neighbor reads at
            # ITS step s+1 — recorded sender-relative; the auditor
            # resolves absolute (device, slot) pairs.
            _sched(
                "dma_start", step=s,
                src_buf=("w_own" if s == 0 else "w_slots"),
                src_slot=(None if s == 0 else ("rel", -s)),
                dst_buf="w_slots", dst_slot=("rel", -s), dst_device=1,
            )
            dma = pltpu.make_async_remote_copy(
                src_ref=src_ref,
                dst_ref=w_slots.at[src],
                send_sem=send_sem,
                recv_sem=recv_sem,
                device_id=device_id,
                device_id_type=id_type,
            )
            dma.start()
        # Compute on the chunk while the forward RDMA is in flight — the
        # overlap the serialized all-gather-then-matmul never gets.
        _sched(
            "read", step=s,
            buf=("w_own" if s == 0 else "w_slots"),
            slot=(None if s == 0 else ("rel", -s)),
        )
        w_cur = w_ref[...] if s == 0 else w_slots[src]
        xs = (
            x_ref[:, pl.ds(src * blk_in, blk_in)] if slice_x else x_ref[...]
        )
        part = _contract(xs, w_cur, w_t)
        _sched("write", step=s, buf="o", slot=None)
        if slice_out:
            o_ref[:, pl.ds(src * blk_out, blk_out)] = part
        elif s == 0:
            o_ref[...] = part
        else:
            o_ref[...] = o_ref[...] + part


def _overlap_rs_matmul_kernel(
    a_ref, b_ref, o_ref, recv_buf, stage, send_sem, recv_sem, *,
    ring, axis_name, mesh, slice_a, blk,
):
    """Fused matmul + streamed ring reduce-scatter of the product.

    Grad block j starts its ring journey at device ``(j + 1) % ring`` and
    travels right, each device adding its local partial — so at step s
    device i computes the partial for block ``(i - s - 1) % ring``, adds
    the accumulator that just arrived, and sends onward WHILE the next
    block's matmul runs. After ``ring`` steps block i is fully reduced at
    device i: the reduce-scatter rode the ring under the grad matmuls.
    Receive slots are per-step (written once — no reuse race); the send
    stage is safe to rewrite because ``dma.wait()`` covers the previous
    send's completion."""
    idx = lax.axis_index(axis_name)
    _sched("kernel", name="rs_matmul", ring=ring)
    _neighbor_barrier(mesh, axis_name)
    device_id, id_type = _neighbor_device_id(mesh, axis_name, idx)
    dma = None
    acc = None
    for s in range(ring):
        j = lax.rem(idx - s - 1 + ring, ring)
        if slice_a:
            part = _grad_partial(a_ref[:, pl.ds(j * blk, blk)], b_ref[...])
        else:
            part = _grad_partial(a_ref[...], b_ref[:, pl.ds(j * blk, blk)])
        if s == 0:
            acc = part
        else:
            _sched("dma_wait", step=s)
            dma.wait()
            _sched("read", step=s, buf="recv", slot=("abs", s - 1))
            acc = recv_buf[s - 1] + part
        if s < ring - 1:
            # The stage rewrite is only safe because the wait above also
            # covered OUR previous send — the exact discipline the
            # auditor's send-rewrite rule checks.
            _sched("write", step=s, buf="stage", slot=None)
            stage[...] = acc
            _sched(
                "dma_start", step=s,
                src_buf="stage", src_slot=None,
                dst_buf="recv", dst_slot=("abs", s), dst_device=1,
            )
            dma = pltpu.make_async_remote_copy(
                src_ref=stage,
                dst_ref=recv_buf.at[s],
                send_sem=send_sem,
                recv_sem=recv_sem,
                device_id=device_id,
                device_id_type=id_type,
            )
            dma.start()
        else:
            _sched("write", step=s, buf="o", slot=None)
            o_ref[...] = acc


def _collective_compiler_params():
    """Kernels holding a barrier semaphore need a collective_id; interpret
    mode takes no compiler params."""
    if _interpret():
        return {}
    return {"compiler_params": pltpu.CompilerParams(collective_id=7)}


def _pallas_ag_matmul(
    xl, wl, *, ring, axis_name, mesh, slice_x, slice_out, w_t,
):
    """shard_map-local fused all-gather-matmul. ``xl`` (m, K) token rows,
    ``wl`` the local shard; returns the full (m, N_out) product in fp32."""
    m = xl.shape[0]
    if w_t:
        n_out = wl.shape[0] * (ring if slice_out else 1)
        blk_out = wl.shape[0]
    else:
        n_out = wl.shape[1] * (ring if slice_out else 1)
        blk_out = wl.shape[1]
    blk_in = wl.shape[1] if w_t else wl.shape[0]
    kernel = functools.partial(
        _overlap_ag_matmul_kernel, ring=ring, axis_name=axis_name, mesh=mesh,
        slice_x=slice_x, slice_out=slice_out, w_t=w_t,
        blk_in=blk_in, blk_out=blk_out,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, n_out), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((ring,) + wl.shape, wl.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        interpret=_interpret(),
        **_collective_compiler_params(),
    )(xl, wl)


def _pallas_rs_matmul(al, bl, *, ring, axis_name, mesh, slice_a):
    """shard_map-local fused matmul + grad reduce-scatter:
    ``RS_blocks(alᵀ @ bl)`` with the block axis over ``al``'s columns
    (slice_a) or ``bl``'s columns. Returns this device's fp32 block."""
    if slice_a:
        blk = al.shape[1] // ring
        out_shape = (blk, bl.shape[1])
    else:
        blk = bl.shape[1] // ring
        out_shape = (al.shape[1], blk)
    kernel = functools.partial(
        _overlap_rs_matmul_kernel, ring=ring, axis_name=axis_name, mesh=mesh,
        slice_a=slice_a, blk=blk,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((max(ring - 1, 1),) + out_shape, jnp.float32),
            pltpu.VMEM(out_shape, jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        interpret=_interpret(),
        **_collective_compiler_params(),
    )(al, bl)


# ---------------------------------------------------------------------------
# decomposed transport — the same schedules as ppermute + dot


def _decomposed_ag_matmul(
    xl, wl, idx, *, ring, axis_name, slice_x, slice_out, w_t,
):
    """ppermute ring with the identical step schedule as the kernel: XLA's
    async collective-permute overlaps each hop with the previous block's
    matmul (the unrolled loop makes every step schedulable — same
    rationale as ring_attention's unrolled ring).

    ``idx`` is the device's ring position, threaded in as a sharded-iota
    operand rather than ``lax.axis_index``: under a PARTIAL-manual region
    (the DP×FSDP×TP mesh, where "model" stays auto) this jax's SPMD
    partitioner rejects axis_index's PartitionId lowering — the same env
    limitation tests/known_env_failures.json records for PP and
    fsdp+ring; the iota operand sidesteps it on every backend."""
    perm = _right_perm(ring)
    m = xl.shape[0]
    blk_in = wl.shape[1] if w_t else wl.shape[0]
    blk_out = wl.shape[0] if w_t else wl.shape[1]
    n_out = blk_out * (ring if slice_out else 1)
    out = jnp.zeros((m, n_out), jnp.float32)
    w_cur = wl
    for s in range(ring):
        src = (idx - s) % ring
        xs = (
            lax.dynamic_slice_in_dim(xl, src * blk_in, blk_in, axis=1)
            if slice_x else xl
        )
        part = _contract(xs, w_cur, w_t)
        if slice_out:
            out = lax.dynamic_update_slice(out, part, (0, src * blk_out))
        else:
            out = out + part
        if s < ring - 1:
            w_cur = lax.ppermute(w_cur, axis_name, perm)
    return out


def _decomposed_rs_matmul(al, bl, idx, *, ring, axis_name, slice_a):
    """Streamed grad reduce-scatter at the XLA level: the partial-sum
    accumulator ppermutes right while the next block's matmul runs.
    ``idx``: sharded-iota ring position (see _decomposed_ag_matmul)."""
    perm = _right_perm(ring)
    blk = (al.shape[1] if slice_a else bl.shape[1]) // ring
    acc = None
    for s in range(ring):
        j = (idx - s - 1) % ring
        if slice_a:
            part = _grad_partial(
                lax.dynamic_slice_in_dim(al, j * blk, blk, axis=1), bl
            )
        else:
            part = _grad_partial(
                al, lax.dynamic_slice_in_dim(bl, j * blk, blk, axis=1)
            )
        acc = part if acc is None else acc + part
        if s < ring - 1:
            acc = lax.ppermute(acc, axis_name, perm)
    return acc


# ---------------------------------------------------------------------------
# the custom-vjp op (shard_map-local), one per (ring, mode, backend)


def _make_local_matmul(ring, axis_name, mesh, shard_axis, backend, out_dtype):
    """Build the shard_map-LOCAL fused matmul with its explicit backward:

    forward: all-gather-matmul (contract mode gathers the K shards and
    accumulates partials; out mode writes output column blocks).
    backward: dx re-gathers W through a second ring pass (ZeRO-3
    semantics — params are re-gathered for backward, never stored
    gathered), dw is the streamed matmul+reduce-scatter.

    TP reductions live OUTSIDE this custom VJP, on purpose: the out-mode
    forward's row-parallel psum is applied by the caller (so jax's own
    psum transpose composes with the shard_map boundary), and the
    contract-mode dx psum is shard_map's replicated-input transpose rule
    itself (a spec that omits the TP axis auto-psums its cotangent —
    verified against this jax in tests). Hand-rolling either INSIDE the
    VJP double-counts. The ring schedules never touch the TP axis.

    The local fn takes ``(xl, wl, il)`` with ``il`` the (1,) sharded-iota
    ring position (int32, zero cotangent): the decomposed transport needs
    it in place of ``lax.axis_index`` (see _decomposed_ag_matmul); the
    pallas kernels read their index in-kernel (Mosaic's own device id)."""
    if backend == "pallas":
        def ag(xl, wl, idx, **kw):
            del idx
            return _pallas_ag_matmul(
                xl, wl, ring=ring, axis_name=axis_name, mesh=mesh, **kw
            )

        def rs(al, bl, idx, **kw):
            del idx
            return _pallas_rs_matmul(
                al, bl, ring=ring, axis_name=axis_name, mesh=mesh, **kw
            )
    else:
        ag = functools.partial(
            _decomposed_ag_matmul, ring=ring, axis_name=axis_name
        )
        rs = functools.partial(
            _decomposed_rs_matmul, ring=ring, axis_name=axis_name
        )

    contract = shard_axis == 0

    def _fwd_impl(xl, wl, idx):
        # contract: out = sum_k x[:, blk_k] @ w_k ; out: out[:, blk_k] = x @ w_k
        return ag(
            xl, wl, idx, slice_x=contract, slice_out=not contract, w_t=False
        ).astype(out_dtype)

    @jax.custom_vjp
    def mm(xl, wl, il):
        return _fwd_impl(xl, wl, il[0])

    def mm_fwd(xl, wl, il):
        return _fwd_impl(xl, wl, il[0]), (xl, wl, il)

    def mm_bwd(res, dy):
        import numpy as np

        xl, wl, il = res
        idx = il[0]
        dy = dy.astype(out_dtype)
        if contract:
            # dx[:, blk_k] = dy @ w_kᵀ  (ring re-gather, out-block writes).
            # Under TP this is each rank's PARTIAL over its N/tp output
            # columns — the cross-rank sum is shard_map's own transpose
            # of the replicated-x in_spec (see docstring), not ours.
            dx = ag(dy, wl, idx, slice_x=False, slice_out=True, w_t=True)
            # dw_k = RS over K-blocks of xᵀ @ dy (streamed with its matmuls)
            dw = rs(xl, dy, idx, slice_a=True)
        else:
            # dx = sum_k dy[:, blk_k] @ w_kᵀ
            dx = ag(dy, wl, idx, slice_x=True, slice_out=False, w_t=True)
            # dw_k = RS over N-blocks of xᵀ @ dy
            dw = rs(xl, dy, idx, slice_a=False)
        return (
            dx.astype(xl.dtype), dw.astype(wl.dtype),
            np.zeros(il.shape, jax.dtypes.float0),
        )

    mm.defvjp(mm_fwd, mm_bwd)
    return mm


# ---------------------------------------------------------------------------
# public entry points


def _plain_dot(x, w):
    """The serialized fallback — a single dot, GSPMD inserts whatever
    collectives the shardings demand (the exact path overlapped mode
    replaces when it CAN)."""
    return jnp.matmul(x, w)


def overlap_dense_matmul(
    x: jax.Array,
    w: jax.Array,
    *,
    shard_axis: int,
    axis_name: str | None,
    tp_axis: str | None = None,
    mesh=None,
    backend: str | None = None,
) -> jax.Array:
    """``x @ w`` with the FSDP gather/reduce-scatter overlapped.

    ``x``: (..., K) activations (leading axes flattened to token rows —
    the batch axis is expected sharded over ``axis_name``); ``w``: (K, N)
    logical weight whose ``shard_axis`` (0 = contraction, 1 = output) is
    sharded over ``axis_name``. ``tp_axis``: the Megatron axis sharding
    w's OTHER dimension on a DP×FSDP×TP mesh — the region then goes
    manual over both axes (this jax's SPMD partitioner rejects
    partial-manual collectives — the PP/fsdp+ring known-env-failure
    class; full-manual also keeps the Pallas kernels usable under TP)
    with the two row-parallel psums made explicit in the custom VJP.

    Any inapplicable case — eager trace, no mesh/axis, ring of 1,
    non-divisible shard or batch tails — falls back to the plain
    serialized dot, so this is ALWAYS safe to call.
    """
    from jax._src.core import trace_state_clean

    if axis_name is None or trace_state_clean():
        return _plain_dot(x, w)
    if mesh is None:
        from dtc_tpu.parallel.sharding import ambient_mesh

        mesh = ambient_mesh(allow_empty=True)
        if mesh is None:
            return _plain_dot(x, w)
    shape = dict(zip(mesh.axis_names, (int(s) for s in mesh.shape.values())))
    ring = shape.get(axis_name, 1)
    if ring <= 1:
        return _plain_dot(x, w)
    if tp_axis is not None and (
        tp_axis == axis_name or shape.get(tp_axis, 1) <= 1
    ):
        tp_axis = None
    tp = shape.get(tp_axis, 1) if tp_axis is not None else 1
    k, n = int(w.shape[0]), int(w.shape[1])
    b = int(x.shape[0])
    ring_dim, tp_dim = (k, n) if shard_axis == 0 else (n, k)
    if ring_dim % ring != 0 or tp_dim % tp != 0 or b % ring != 0:
        # Non-divisible block tails (or a batch narrower than the ring —
        # generate/serving calls): the ring schedule has no tail handling
        # by design; the serialized dot is the documented fallback.
        return _plain_dot(x, w)

    m_local = 1
    for d in x.shape[:-1]:
        m_local *= int(d)
    m_local //= ring
    # LOCAL operand dims inside the manual region: x's contraction width
    # and the output width this device assembles.
    k_loc = k if shard_axis == 0 else k // tp
    n_loc = n // tp if shard_axis == 0 else n
    if backend is None:
        backend = resolve_backend(
            m_local, k_loc, n_loc, ring, shard_axis, x.dtype.itemsize
        )
    if backend == "xla":
        return _plain_dot(x, w)
    if backend == "pallas" and (
        not _pallas_ok(m_local, k_loc, n_loc, ring, shard_axis,
                       x.dtype.itemsize)
        # Interpret mode cannot emulate remote DMA across a multi-axis
        # manual mesh (LOGICAL ids are single-axis-only there); hardware
        # takes the MESH-coordinate path. CPU tests cover pallas on pure
        # FSDP rings and decomposed on the DP×FSDP×TP mesh.
        or (tp_axis is not None and _interpret())
    ):
        backend = "decomposed"

    out_dtype = jnp.result_type(x.dtype, w.dtype)
    mm = _make_local_matmul(
        ring, axis_name, mesh, shard_axis, backend, out_dtype
    )

    def local(xl, wl, il):
        rows = xl.reshape(-1, xl.shape[-1])
        out = mm(rows, wl, il)
        if shard_axis == 1 and tp_axis is not None:
            # Row-parallel output (out_proj/fc2 under TP): each TP rank
            # assembled the full-N product from its K/tp contraction
            # slice — the Megatron all-reduce. OUTSIDE the custom VJP so
            # jax's psum transpose composes with the shard_map boundary
            # (hand-rolling it inside mis-scales the cotangent).
            out = lax.psum(out, tp_axis)
        return out.reshape(*xl.shape[:-1], out.shape[-1])

    mids = [None] * (x.ndim - 2)
    if shard_axis == 0:
        x_spec = P(axis_name, *mids, None)
        w_spec = P(axis_name, tp_axis)
        out_spec = P(axis_name, *mids, tp_axis)
    else:
        x_spec = P(axis_name, *mids, tp_axis)
        w_spec = P(tp_axis, axis_name)
        out_spec = P(axis_name, *mids, None)
    manual = {axis_name} | ({tp_axis} if tp_axis is not None else set())
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(x_spec, w_spec, P(axis_name)),
        out_specs=out_spec,
        axis_names=manual,
        check_vma=False,
    )(x, w, jnp.arange(ring, dtype=jnp.int32))


def reduce_scatter_matmul(
    a: jax.Array,
    b: jax.Array,
    *,
    shard_axis: int,
    axis_name: str,
    mesh=None,
    backend: str | None = None,
) -> jax.Array:
    """Standalone streamed reduce-scatter-of-a-matmul: computes
    ``aᵀ @ b`` summed over the ring's token shards, scattered blockwise
    over ``shard_axis`` of the product (0 = a-columns, 1 = b-columns).
    This is exactly the backward dw op; exposed so the tests (and future
    callers — e.g. a hand-scheduled optimizer) can drive it directly
    against the ``psum_scatter`` oracle."""
    if mesh is None:
        from dtc_tpu.parallel.sharding import ambient_mesh

        mesh = ambient_mesh()
    shape = dict(zip(mesh.axis_names, (int(s) for s in mesh.shape.values())))
    ring = shape.get(axis_name, 1)
    if ring <= 1:
        return _grad_partial(a.reshape(-1, a.shape[-1]),
                             b.reshape(-1, b.shape[-1]))
    # Same fallback ladder as overlap_dense_matmul: env override first
    # ('0'/'xla' means no fused kernel here — the decomposed ring still
    # produces the reduce-scatter, just at the XLA level), then the
    # lane/VMEM gate. Shapes the kernel declines take the decomposed ring
    # instead of dying in Mosaic.
    m_local = 1
    for d in a.shape[:-1]:
        m_local *= int(d)
    m_local //= ring
    k_cols, n_cols = int(a.shape[-1]), int(b.shape[-1])
    blk = (k_cols if shard_axis == 0 else n_cols) // ring
    if blk == 0 or (k_cols if shard_axis == 0 else n_cols) % ring != 0:
        raise ValueError(
            f"reduce_scatter_matmul: scatter dim "
            f"{k_cols if shard_axis == 0 else n_cols} not divisible by "
            f"ring {ring}"
        )
    if backend is None:
        ov = _backend_override()
        if ov in ("0", "xla", "decomposed"):
            backend = "decomposed"
        elif ov == "pallas":
            backend = "pallas"
        else:
            backend = (
                "pallas" if jax.default_backend() == "tpu" else "decomposed"
            )
    if backend == "pallas":
        # Same accounting as overlap_plan's bwd_dw_rs leg — the shared
        # planner's single implementation (was a third inline copy).
        fits = vmem.rs_standalone_bytes(
            m_local, k_cols, n_cols, ring, shard_axis, a.dtype.itemsize
        ) <= _VMEM_BUDGET_BYTES
        if (not _interpret() and blk % _LANE != 0) or not fits:
            backend = "decomposed"

    def local(al, bl, il):
        al = al.reshape(-1, al.shape[-1])
        bl = bl.reshape(-1, bl.shape[-1])
        if backend == "pallas":
            return _pallas_rs_matmul(
                al, bl, ring=ring, axis_name=axis_name, mesh=mesh,
                slice_a=shard_axis == 0,
            )
        return _decomposed_rs_matmul(
            al, bl, il[0], ring=ring, axis_name=axis_name,
            slice_a=shard_axis == 0,
        )

    row_spec = P(axis_name, *([None] * (a.ndim - 1)))
    out_spec = (
        P(axis_name, None) if shard_axis == 0 else P(None, axis_name)
    )
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(row_spec, row_spec, P(axis_name)),
        out_specs=out_spec,
        axis_names={axis_name},
        check_vma=False,
    )(a, b, jnp.arange(ring, dtype=jnp.int32))
