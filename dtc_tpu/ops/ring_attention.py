"""Ring attention — sequence-parallel causal attention over the mesh.

Long-context capability the reference entirely lacks (its attention
materialises the full (B,H,T,T) score tensor and caps max_seq_len at 512,
`/root/reference/model/CausalSelfAttention.py:34-42`). The SEQUENCE axis of
q/k/v is sharded over the mesh's ``model`` axis (RING_RULES in
parallel/sharding.py): key/value blocks rotate around the ring via
``lax.ppermute`` — the same ICI-neighbor collective machinery as the
pipeline (parallel/pipeline.py) — while an online softmax merges each
block's contribution. Per-device score memory is O(T_local²) and activation
memory O(T/ring), so max sequence length scales linearly with ring size.

Two schedules:

- ``zigzag`` (default) — causal-efficient AND load-balanced. The sequence
  is split into 2R chunks; device i works on chunks (C_i, C_{2R-1-i}), so
  every device computes exactly 2 half-chunk blocks per ring step (plus one
  extra diagonal at step 0) instead of a full T_local² block that may be
  entirely masked away. Total score FLOPs drop from T²/R per device to
  ~T²/2R — the causal half — and the work is IDENTICAL across devices, so
  no ring rank idles while the last rank computes (round-3 VERDICT weak #3:
  the uniform schedule wastes ~2× FLOPs and bubbles on a real ring). The
  zigzag layout is converted to/from the model's contiguous sharding inside
  this op with two ppermutes each way (chunk parity gives a clean
  2-matching: chunks c and 2R-1-c always have opposite parity).
- ``uniform`` — the round-3 schedule, kept for A/B cost accounting: every
  device executes all R steps on full T_local² blocks; future blocks are
  computed then masked to zero.

Structure notes:

- ``jax.shard_map`` manual over ``model`` ONLY; ``data`` (and ``pipe``)
  stay GSPMD-auto, so ring attention composes with DP/FSDP for free.
- Backward is plain autodiff: ``ppermute`` transposes to the inverse
  rotation, so gradient KV blocks counter-rotate automatically — no manual
  backward schedule.
- Numerics match ``dense_causal_attention``: fp32 scores/softmax, -1e9
  additive mask, accumulate in fp32, cast out to the input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dtc_tpu.utils.compat import shard_map

NEG_INF = -1e9


def _ambient_mesh():
    """The mesh to hand the inner shard_map — the shared
    ``parallel.sharding.ambient_mesh`` (abstract mesh under a jit trace so
    ring attention nests inside the pipeline's manual region; physical
    mesh from the trainer's ``with mesh:`` context otherwise)."""
    from dtc_tpu.parallel.sharding import ambient_mesh

    return ambient_mesh()


def _block(qc, kc, vc, scale, diag: bool):
    """One half-chunk attention block: returns UNNORMALISED (m, l, o).

    ``diag=True`` applies the local lower-triangle causal mask (the chunk
    attends to itself); full blocks are strictly-past and need none.
    """
    s = jnp.einsum(
        "bthd,bshd->bhts", qc, kc, preferred_element_type=jnp.float32
    ) * scale
    if diag:
        tl = qc.shape[1]
        row = lax.broadcasted_iota(jnp.int32, (tl, tl), 0)
        col = lax.broadcasted_iota(jnp.int32, (tl, tl), 1)
        s = jnp.where((col <= row)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # (B,H,Tc)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum(
        "bhts,bshd->bthd", p.astype(vc.dtype), vc,
        preferred_element_type=jnp.float32,
    )
    return m, l, o


def _merge(stats, blk, pred=None):
    """Online-softmax merge of a block into running (m, l, acc); ``pred``
    (scalar bool) gates the merge without branching — SPMD-friendly."""
    m_run, l_run, acc = stats
    m_b, l_b, o_b = blk
    m_new = jnp.maximum(m_run, m_b)
    alpha = jnp.exp(m_run - m_new)
    beta = jnp.exp(m_b - m_new)
    l_new = alpha * l_run + beta * l_b
    acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + o_b * beta.transpose(0, 2, 1)[..., None]
    if pred is None:
        return m_new, l_new, acc_new
    keep = lambda new, old: jnp.where(pred, new, old)
    return keep(m_new, m_run), keep(l_new, l_run), keep(acc_new, acc)


def _zigzag_perms(ring: int):
    """Contiguous->zigzag chunk routing as two ppermute permutations.

    Chunk c of 2R lives contiguously on device c//2 (slot c%2) and in zigzag
    on device z(c) = min(c, 2R-1-c) (slot 0 if c < R else 1). Restricted to
    one parity class z is injective, so parity yields a perfect 2-matching.
    Returns (perm_even, perm_odd) with perm_even[i] = z(2i), i.e. where
    device i's even chunk goes.
    """
    z = lambda c: c if c < ring else 2 * ring - 1 - c
    perm_even = [(i, z(2 * i)) for i in range(ring)]
    perm_odd = [(i, z(2 * i + 1)) for i in range(ring)]
    return perm_even, perm_odd


def _use_block_kernels(tc: int, h: int, d: int) -> bool:
    """Route per-block compute through the packed Pallas kernels? On TPU
    whenever the chunk shape qualifies; force with DTC_RING_FLASH=1 (kernels
    run in interpret mode off-TPU — how the CPU-mesh tests cover this path)
    or disable with DTC_RING_FLASH=0."""
    import os

    from dtc_tpu.ops import flash_attention as fa

    flag = os.environ.get("DTC_RING_FLASH", "")
    if flag == "0":
        return False
    if not fa.block_supported(tc, h, d):
        return False
    if flag == "1":
        return True
    return jax.default_backend() == "tpu"


def _make_zigzag_flash(ring: int, axis_name: str, kv_perm, scale: float,
                       g: int, d: int):
    """Whole-ring custom VJP over zigzag-LOCAL packed (B, Tl, H*D) chunks,
    per-block compute in the packed Pallas kernels (flash_attention.py's
    ring-block kernels). Runs INSIDE the shard_map.

    Standard ring-flash contract: forward merges normalised block outputs
    via logaddexp'd lse; backward re-rotates KV for a second pass, calling
    the block backward kernel with the GLOBAL lse/out (delta is computed
    in-kernel from global do·out) while dk/dv accumulators travel with
    their KV blocks and arrive home after a full cycle — no hand-written
    schedule asymmetry, identical block structure to the forward.
    """

    def _bcast(lse_w, tc):
        # (B, hg, Tc, g) -> (B, Tc, H*D): packed head index is gi*g + j.
        b, hg, _, gg = lse_w.shape
        x = lse_w.transpose(0, 2, 1, 3).reshape(b, tc, hg * gg)
        return jnp.repeat(x, d, axis=-1)

    def _merge_lse(run, blk, tc, pred=None):
        """Normalised-output merge (out, lse) — distinct from the dense
        path's unnormalised (m, l, acc) module-level _merge. The running
        ``out`` accumulates in fp32 (cast to the input dtype once, at the
        end of the ring) per the module contract."""
        out_run, lse_run = run
        o_b, lse_b = blk
        lse_new = jnp.logaddexp(lse_run, lse_b)
        w1 = _bcast(jnp.exp(lse_run - lse_new), tc)
        w2 = _bcast(jnp.exp(lse_b - lse_new), tc)
        out_new = out_run * w1 + o_b.astype(jnp.float32) * w2
        if pred is None:
            return out_new, lse_new
        return (
            jnp.where(pred, out_new, out_run),
            jnp.where(pred, lse_new, lse_run),
        )

    def _fwd_ring(qp, kp, vp):
        from dtc_tpu.ops.flash_attention import _block_call

        idx = lax.axis_index(axis_name)
        tc = qp.shape[1] // 2
        qa, qb = jnp.split(qp, 2, axis=1)
        ka, kb = jnp.split(kp, 2, axis=1)
        va, vb = jnp.split(vp, 2, axis=1)
        # Step 0: local causality over the chunk pair (3 half-blocks).
        oa0, lse_a0 = _block_call(qa, ka, va, scale, True, g, d)
        st_a = (oa0.astype(jnp.float32), lse_a0)
        ob0, lse_b0 = _block_call(qb, ka, va, scale, False, g, d)
        st_b = _merge_lse(
            (ob0.astype(jnp.float32), lse_b0),
            _block_call(qb, kb, vb, scale, True, g, d),
            tc,
        )
        k_cur, v_cur = kp, vp
        for s in range(1, ring):
            k_cur = lax.ppermute(k_cur, axis_name, kv_perm)
            v_cur = lax.ppermute(v_cur, axis_name, kv_perm)
            src = (idx - s) % ring
            k0, k1 = jnp.split(k_cur, 2, axis=1)
            v0, v1 = jnp.split(v_cur, 2, axis=1)
            st_b = _merge_lse(st_b, _block_call(qb, k0, v0, scale, False, g, d), tc)
            past = src < idx
            q_sel = jnp.where(past, qa, qb)
            k_sel = jnp.where(past, k0, k1)
            v_sel = jnp.where(past, v0, v1)
            blk = _block_call(q_sel, k_sel, v_sel, scale, False, g, d)
            st_a = _merge_lse(st_a, blk, tc, pred=past)
            st_b = _merge_lse(st_b, blk, tc, pred=jnp.logical_not(past))
        out = jnp.concatenate([st_a[0], st_b[0]], axis=1).astype(qp.dtype)
        return out, st_a[1], st_b[1]

    @jax.custom_vjp
    def zigzag_flash(qp, kp, vp):
        out, _, _ = _fwd_ring(qp, kp, vp)
        return out

    def zz_fwd(qp, kp, vp):
        out, lse_a, lse_b = _fwd_ring(qp, kp, vp)
        return out, (qp, kp, vp, out, lse_a, lse_b)

    def zz_bwd(res, do):
        from dtc_tpu.ops.flash_attention import _block_call

        qp, kp, vp, out, lse_a, lse_b = res
        idx = lax.axis_index(axis_name)
        tc = qp.shape[1] // 2
        qa, qb = jnp.split(qp, 2, axis=1)
        doa, dob = jnp.split(do, 2, axis=1)
        oa, ob = jnp.split(out, 2, axis=1)
        f32 = jnp.float32
        dqa = jnp.zeros_like(qa, f32)
        dqb = jnp.zeros_like(qb, f32)
        k_cur, v_cur = kp, vp
        dk_acc = jnp.zeros_like(kp, f32)
        dv_acc = jnp.zeros_like(vp, f32)
        for s in range(ring):
            src = (idx - s) % ring
            k0, k1 = jnp.split(k_cur, 2, axis=1)
            v0, v1 = jnp.split(v_cur, 2, axis=1)
            dk0 = jnp.zeros_like(k0, f32)
            dk1 = jnp.zeros_like(k1, f32)
            dv0 = jnp.zeros_like(v0, f32)
            dv1 = jnp.zeros_like(v1, f32)
            if s == 0:
                dq_c, dk_c, dv_c = _block_call(
                    qa, k0, v0, scale, True, g, d, do=doa, o=oa, lse=lse_a
                )
                dqa += dq_c; dk0 += dk_c; dv0 += dv_c
                dq_c, dk_c, dv_c = _block_call(
                    qb, k0, v0, scale, False, g, d, do=dob, o=ob, lse=lse_b
                )
                dqb += dq_c; dk0 += dk_c; dv0 += dv_c
                dq_c, dk_c, dv_c = _block_call(
                    qb, k1, v1, scale, True, g, d, do=dob, o=ob, lse=lse_b
                )
                dqb += dq_c; dk1 += dk_c; dv1 += dv_c
            else:
                dq_c, dk_c, dv_c = _block_call(
                    qb, k0, v0, scale, False, g, d, do=dob, o=ob, lse=lse_b
                )
                dqb += dq_c; dk0 += dk_c; dv0 += dv_c
                past = src < idx
                q_sel = jnp.where(past, qa, qb)
                k_sel = jnp.where(past, k0, k1)
                v_sel = jnp.where(past, v0, v1)
                do_sel = jnp.where(past, doa, dob)
                o_sel = jnp.where(past, oa, ob)
                lse_sel = jnp.where(past, lse_a, lse_b)
                dq_c, dk_c, dv_c = _block_call(
                    q_sel, k_sel, v_sel, scale, False, g, d,
                    do=do_sel, o=o_sel, lse=lse_sel,
                )
                zero = jnp.zeros_like(dq_c)
                dqa += jnp.where(past, dq_c, zero)
                dqb += jnp.where(past, zero, dq_c)
                dk0 += jnp.where(past, dk_c, zero)
                dk1 += jnp.where(past, zero, dk_c)
                dv0 += jnp.where(past, dv_c, zero)
                dv1 += jnp.where(past, zero, dv_c)
            dk_acc = dk_acc + jnp.concatenate([dk0, dk1], axis=1)
            dv_acc = dv_acc + jnp.concatenate([dv0, dv1], axis=1)
            # Rotate the traveling gradient accumulators; after the final
            # rotation (ring total) they are home. KV itself has no
            # consumer after the last step — skip its dead ppermutes.
            if s != ring - 1:
                k_cur = lax.ppermute(k_cur, axis_name, kv_perm)
                v_cur = lax.ppermute(v_cur, axis_name, kv_perm)
            dk_acc = lax.ppermute(dk_acc, axis_name, kv_perm)
            dv_acc = lax.ppermute(dv_acc, axis_name, kv_perm)
        dq = jnp.concatenate([dqa, dqb], axis=1).astype(qp.dtype)
        return dq, dk_acc.astype(kp.dtype), dv_acc.astype(vp.dtype)

    zigzag_flash.defvjp(zz_fwd, zz_bwd)
    return zigzag_flash


def ring_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "model",
    mesh=None,
    schedule: str = "zigzag",
) -> jax.Array:
    """Causal attention over ``(B, T, H, D)`` with T sharded over ``axis_name``.

    Call under an active mesh; T must divide evenly by 2 * ring size.
    ``schedule``: "zigzag" (causal-efficient, load-balanced — default) or
    "uniform" (round-3 behavior: all blocks computed, future ones masked).
    """
    from jax._src.core import trace_state_clean

    if schedule not in ("zigzag", "uniform"):
        raise ValueError(f"unknown ring schedule {schedule!r}")

    if trace_state_clean():
        # Eager call — flax ``model.init`` runs the forward outside jit, and
        # partial-manual shard_map only exists under a jit trace. The dense
        # path is numerically identical (init only consumes shapes).
        from dtc_tpu.ops.attention import dense_causal_attention

        return dense_causal_attention(q, k, v)

    mesh = mesh if mesh is not None else _ambient_mesh()
    ring = mesh.shape[axis_name]
    b, t, h, d = q.shape
    scale = d ** -0.5

    if ring == 1:
        from dtc_tpu.ops.attention import dense_causal_attention

        return dense_causal_attention(q, k, v)

    if schedule == "uniform":
        if t % ring != 0:
            raise ValueError(f"seq len {t} not divisible by ring size {ring}")
        return _uniform_ring(q, k, v, axis_name, mesh, ring, scale)

    if t % (2 * ring) != 0:
        raise ValueError(
            f"seq len {t} not divisible by 2*ring size {2 * ring} "
            "(zigzag needs two chunks per device)"
        )

    kv_perm = [(i, (i + 1) % ring) for i in range(ring)]
    to_zig_even, to_zig_odd = _zigzag_perms(ring)
    # Inverse routing: device d's even chunk (d or 2R-1-d, whichever is
    # even) goes home to contiguous device chunk//2.
    from_zig_even = [(dst, src) for src, dst in to_zig_even]
    from_zig_odd = [(dst, src) for src, dst in to_zig_odd]

    def to_zigzag(x, idx):
        """(B, Tl, H, D) contiguous [C_2i, C_2i+1] -> zigzag [C_i, C_{2R-1-i}]."""
        lo, hi = jnp.split(x, 2, axis=1)  # even chunk 2i, odd chunk 2i+1
        recv_even = lax.ppermute(lo, axis_name, to_zig_even)
        recv_odd = lax.ppermute(hi, axis_name, to_zig_odd)
        # Slot 0 holds chunk idx — even iff idx is even.
        even_first = (idx % 2 == 0)
        a = jnp.where(even_first, recv_even, recv_odd)
        bb = jnp.where(even_first, recv_odd, recv_even)
        return jnp.concatenate([a, bb], axis=1)

    def from_zigzag(x, idx):
        """Inverse of to_zigzag."""
        a, bb = jnp.split(x, 2, axis=1)  # chunks idx, 2R-1-idx
        even_first = (idx % 2 == 0)
        ev = jnp.where(even_first, a, bb)   # the even-numbered chunk
        od = jnp.where(even_first, bb, a)
        recv_lo = lax.ppermute(ev, axis_name, from_zig_even)
        recv_hi = lax.ppermute(od, axis_name, from_zig_odd)
        return jnp.concatenate([recv_lo, recv_hi], axis=1)

    tc_local = t // (2 * ring)
    use_kernels = _use_block_kernels(tc_local, h, d)
    if use_kernels:
        from dtc_tpu.ops.flash_attention import _packed_group

        zz_flash = _make_zigzag_flash(
            ring, axis_name, kv_perm, scale, _packed_group(d, h), d
        )

    def local_ring(q_blk, k_blk, v_blk):
        # Shapes here are (B, T/ring, H, D); batch stays GSPMD-auto.
        idx = lax.axis_index(axis_name)
        qz = to_zigzag(q_blk, idx)
        kz = to_zigzag(k_blk, idx)
        vz = to_zigzag(v_blk, idx)

        if use_kernels:
            bb, tl = qz.shape[0], qz.shape[1]
            pk = lambda x: x.reshape(bb, tl, h * d)   # layout bitcast
            out = zz_flash(pk(qz), pk(kz), pk(vz))
            return from_zigzag(out.reshape(bb, tl, h, d), idx).astype(q_blk.dtype)

        qa, qb = jnp.split(qz, 2, axis=1)   # chunks C_idx, C_{2R-1-idx}

        # Step 0 (local): C_idx self-diag, C_{2R-1-idx} x C_idx full,
        # C_{2R-1-idx} self-diag — exactly plain causality over the
        # concatenated local pair, 3 half-blocks.
        ka, kb = jnp.split(kz, 2, axis=1)
        va, vb = jnp.split(vz, 2, axis=1)
        stats_a = _block(qa, ka, va, scale, diag=True)
        stats_b = _merge(
            _block(qb, ka, va, scale, diag=False),
            _block(qb, kb, vb, scale, diag=True),
        )

        # Unrolled ring loop (ring sizes are one-hop-per-device small): XLA
        # can overlap each ppermute with the previous step's block compute,
        # and cost_analysis counts every step (a lax.scan body is counted
        # once regardless of trip count, hiding the FLOPs the schedule is
        # designed to remove — tests/test_ring_attention.py asserts on it).
        k_cur, v_cur, st_a, st_b = kz, vz, stats_a, stats_b
        for s in range(1, ring):
            # Step s uses KV from device (idx - s) % ring.
            k_cur = lax.ppermute(k_cur, axis_name, kv_perm)
            v_cur = lax.ppermute(v_cur, axis_name, kv_perm)
            src = (idx - s) % ring
            k0, k1 = jnp.split(k_cur, 2, axis=1)  # chunks C_src, C_{2R-1-src}
            v0, v1 = jnp.split(v_cur, 2, axis=1)
            # Fixed block: q C_{2R-1-idx} x kv C_src — strictly past for
            # every src != idx, always needed, never masked.
            st_b = _merge(st_b, _block(qb, k0, v0, scale, diag=False))
            # Variable block: src < idx -> q C_idx x kv C_src (past);
            # src > idx -> q C_{2R-1-idx} x kv C_{2R-1-src} (past). One
            # block either way — constant work per device per step.
            past = src < idx
            q_sel = jnp.where(past, qa, qb)
            k_sel = jnp.where(past, k0, k1)
            v_sel = jnp.where(past, v0, v1)
            blk = _block(q_sel, k_sel, v_sel, scale, diag=False)
            st_a = _merge(st_a, blk, pred=past)
            st_b = _merge(st_b, blk, pred=jnp.logical_not(past))

        def finish(st):
            m, l, acc = st
            return acc / l.transpose(0, 2, 1)[..., None]

        out = jnp.concatenate([finish(st_a), finish(st_b)], axis=1)
        return from_zigzag(out, idx).astype(q_blk.dtype)

    spec = P(None, axis_name, None, None)
    return shard_map(
        local_ring,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis_name},
        check_vma=False,
    )(q, k, v)


def _uniform_ring(q, k, v, axis_name, mesh, ring, scale):
    """Round-3 uniform schedule: every device executes all ``ring`` steps on
    full T_local² blocks; blocks entirely in the causal future are computed
    and masked to zero. Kept for A/B cost accounting against zigzag
    (tests/test_ring_attention.py asserts the FLOPs ratio)."""
    b, t, h, d = q.shape

    def local_ring(q_blk, k_blk, v_blk):
        idx = lax.axis_index(axis_name)
        t_loc = q_blk.shape[1]
        perm = [(i, (i + 1) % ring) for i in range(ring)]
        row = lax.broadcasted_iota(jnp.int32, (t_loc, t_loc), 0)
        col = lax.broadcasted_iota(jnp.int32, (t_loc, t_loc), 1)

        m_run = jnp.full((b, h, t_loc), NEG_INF, jnp.float32)
        l_run = jnp.zeros((b, h, t_loc), jnp.float32)
        acc = jnp.zeros((b, t_loc, h, d), jnp.float32)
        k_cur, v_cur = k_blk, v_blk
        # Unrolled like the zigzag loop, so cost_analysis compares the two
        # schedules' true per-step FLOPs (scan bodies are counted once).
        for s in range(ring):
            src = (idx - s) % ring  # global block id the rotating KV holds
            scores = jnp.einsum(
                "bthd,bshd->bhts", q_blk, k_cur,
                preferred_element_type=jnp.float32,
            ) * scale
            mask = (src * t_loc + col) <= (idx * t_loc + row)
            scores = jnp.where(mask[None, None], scores, NEG_INF)

            m_new = jnp.maximum(m_run, jnp.max(scores, axis=-1))
            alpha = jnp.exp(m_run - m_new)                   # (B,H,Tl)
            p = jnp.exp(scores - m_new[..., None])           # (B,H,Tl,Sl)
            l_run = alpha * l_run + jnp.sum(p, axis=-1)
            acc = acc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
                "bhts,bshd->bthd", p.astype(v_cur.dtype), v_cur,
                preferred_element_type=jnp.float32,
            )
            m_run = m_new
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        out = acc / l_run.transpose(0, 2, 1)[..., None]
        return out.astype(q_blk.dtype)

    spec = P(None, axis_name, None, None)
    return shard_map(
        local_ring,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis_name},
        check_vma=False,
    )(q, k, v)
