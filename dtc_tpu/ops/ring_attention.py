"""Ring attention — sequence-parallel causal attention over the mesh.

Long-context capability the reference entirely lacks (its attention
materialises the full (B,H,T,T) score tensor and caps max_seq_len at 512,
`/root/reference/model/CausalSelfAttention.py:34-42`). Here the SEQUENCE
axis of q/k/v is sharded over the mesh's ``model`` axis (RING_RULES in
parallel/sharding.py): each device keeps its query block resident while
key/value blocks rotate around the ring via ``lax.ppermute`` — the same
ICI-neighbor collective machinery as the pipeline (parallel/pipeline.py) —
and a running online softmax merges each block's contribution. Per-device
score memory is O(T_local²) and activation memory O(T/ring), so max
sequence length scales linearly with ring size.

Structure notes:

- ``jax.shard_map`` manual over ``model`` ONLY; ``data`` (and ``pipe``)
  stay GSPMD-auto, so ring attention composes with DP for free.
- Uniform collective schedule: every device executes the same m ring steps
  (blocks entirely in the causal future contribute zeros via the mask)
  — no data-dependent branching, mirroring the pipeline's design.
- Backward is plain autodiff: ``ppermute`` transposes to the inverse
  rotation, so gradient KV blocks counter-rotate automatically — no manual
  backward schedule.
- Numerics match ``dense_causal_attention``: fp32 scores/softmax, -1e9
  additive mask, accumulate in fp32, cast out to the input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e9


def _ambient_mesh():
    """The mesh to hand the inner shard_map.

    Under a jit with an active trace context this is the ABSTRACT mesh —
    which carries per-axis Manual/Auto state, so ring attention nests
    correctly inside another manual region (the pipeline's shard_map over
    "pipe": the abstract mesh there is Manual on pipe, Auto elsewhere, and
    shard_map requires the passed mesh to match it exactly). Falls back to
    the physical mesh installed by the trainer's ``with mesh:`` context.
    """
    from jax.sharding import get_abstract_mesh

    amesh = get_abstract_mesh()
    if not amesh.empty:
        return amesh
    from jax._src.mesh import thread_resources

    mesh = thread_resources.env.physical_mesh
    if mesh.empty:
        raise RuntimeError(
            "ring attention needs an active mesh context (`with mesh:`); "
            "none is installed"
        )
    return mesh


def ring_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "model",
    mesh=None,
) -> jax.Array:
    """Causal attention over ``(B, T, H, D)`` with T sharded over ``axis_name``.

    Call under an active mesh; T must divide evenly by the ring size.
    """
    from jax._src.core import trace_state_clean

    if trace_state_clean():
        # Eager call — flax ``model.init`` runs the forward outside jit, and
        # partial-manual shard_map only exists under a jit trace. The dense
        # path is numerically identical (init only consumes shapes).
        from dtc_tpu.ops.attention import dense_causal_attention

        return dense_causal_attention(q, k, v)

    mesh = mesh if mesh is not None else _ambient_mesh()
    ring = mesh.shape[axis_name]
    b, t, h, d = q.shape
    if t % ring != 0:
        raise ValueError(f"seq len {t} not divisible by ring size {ring}")
    scale = d ** -0.5

    def local_ring(q_blk, k_blk, v_blk):
        # Shapes here are (B, T/ring, H, D); batch stays GSPMD-auto.
        idx = lax.axis_index(axis_name)
        t_loc = q_blk.shape[1]
        perm = [(i, (i + 1) % ring) for i in range(ring)]
        row = jax.lax.broadcasted_iota(jnp.int32, (t_loc, t_loc), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (t_loc, t_loc), 1)

        def step(carry, s):
            k_cur, v_cur, m_run, l_run, acc = carry
            src = (idx - s) % ring  # global block id the rotating KV holds
            scores = jnp.einsum(
                "bthd,bshd->bhts", q_blk, k_cur,
                preferred_element_type=jnp.float32,
            ) * scale
            # Causal mask on GLOBAL positions: query idx*t_loc+row vs key
            # src*t_loc+col. Blocks fully in the future mask to all -inf and
            # contribute exp(-1e9 - m_run) = 0 (the first step, src == idx,
            # is the diagonal block, so m_run is real from step 0 on).
            mask = (src * t_loc + col) <= (idx * t_loc + row)
            scores = jnp.where(mask[None, None], scores, NEG_INF)

            m_new = jnp.maximum(m_run, jnp.max(scores, axis=-1))
            alpha = jnp.exp(m_run - m_new)                   # (B,H,Tl)
            p = jnp.exp(scores - m_new[..., None])           # (B,H,Tl,Sl)
            l_new = alpha * l_run + jnp.sum(p, axis=-1)
            acc = acc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
                "bhts,bshd->bthd", p.astype(v_cur.dtype), v_cur,
                preferred_element_type=jnp.float32,
            )
            # Rotate KV one hop; uniform schedule keeps the last rotation
            # (KV returns home) rather than branching on the step index.
            k_next = lax.ppermute(k_cur, axis_name, perm)
            v_next = lax.ppermute(v_cur, axis_name, perm)
            return (k_next, v_next, m_new, l_new, acc), None

        m0 = jnp.full((b, h, t_loc), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, t_loc), jnp.float32)
        acc0 = jnp.zeros((b, t_loc, h, d), jnp.float32)
        (_, _, _, l_fin, acc), _ = lax.scan(
            step, (k_blk, v_blk, m0, l0, acc0), jnp.arange(ring)
        )
        out = acc / l_fin.transpose(0, 2, 1)[..., None]
        return out.astype(q_blk.dtype)

    spec = P(None, axis_name, None, None)
    return jax.shard_map(
        local_ring,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis_name},
        check_vma=False,
    )(q, k, v)
