"""Attention ops with pluggable implementations.

The reference hard-codes one O(T²)-memory einsum attention that materialises
the full ``(B, H, T, T)`` score tensor and an additive ``-1e9`` mask built in
the embedding layer (`/root/reference/model/CausalSelfAttention.py:34-42`,
`/root/reference/model/GPTModel.py:50-51`). Here attention is an *op* with
three implementations behind one interface:

- ``dense``  — XLA einsum path, fp32 softmax, mask fused via ``where`` on an
  iota comparison (no (1,1,T,T) mask buffer travels through the model).
  Reference semantics; used for CPU tests and as the autodiff baseline.
- ``flash``  — blockwise Pallas TPU kernel (ops/flash_attention.py): O(T)
  memory, VMEM-tiled, for long sequences.
- ``ring``   — sequence-parallel ring attention (ops/ring_attention.py):
  KV blocks rotate over the mesh via ppermute while queries stay put.

``auto`` picks flash on TPU when shapes are tile-friendly, else dense.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # matches the reference's additive mask value


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def dense_causal_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Reference-semantics causal attention.

    Args are ``(B, T, H, D)``. Scores and softmax run in float32 regardless
    of input dtype (bf16-safe); output is cast back to the input dtype.
    Exactly :func:`decode_attention` with a zero offset and full-length
    keys — ONE masked-softmax core serves both training and decode, so
    their numerics cannot drift apart.
    """
    return decode_attention(q, k, v, jnp.int32(0))


def decode_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, start: jax.Array
) -> jax.Array:
    """Attention for KV-cache decode: ``q`` is ``(B, T_new, H, D)`` for the
    tokens being appended at position ``start``; ``k``/``v`` are the FULL
    cache ``(B, S, H, D)`` (valid through ``start + T_new``). Causality:
    query row r (global position start + r) sees cache columns
    ``col <= start + r``; columns beyond the write frontier are masked the
    same way. fp32 scores/softmax, same -1e9 semantics as training.

    ``start`` is a scalar (every batch row at the same position — the
    ``generate`` path) or a ``(B,)`` vector of per-row write frontiers
    (the serving runtime's continuous-batching slots, each request at its
    own position)."""
    b, t, h, d = q.shape
    s = k.shape[1]
    scale = d ** -0.5
    scores = jnp.einsum(
        "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
    ) * scale
    row = jax.lax.broadcasted_iota(jnp.int32, (t, s), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (t, s), 1)
    if getattr(start, "ndim", 0) == 1:
        # Per-row frontier: mask is (B, T, S), one frontier per batch row.
        mask = col[None] <= start[:, None, None] + row[None]
        scores = jnp.where(mask[:, None], scores, NEG_INF)
    else:
        mask = col <= start + row
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", weights.astype(v.dtype), v)
    return out.astype(q.dtype)


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "auto",
    block_q: int = 512,
    block_kv: int = 512,
    block_q_bwd: int = 0,
    block_kv_bwd: int = 0,
) -> jax.Array:
    """Dispatch causal self-attention over ``(B, T, H, D)`` tensors."""
    if impl == "auto":
        from dtc_tpu.ops import flash_attention

        t, d = q.shape[1], q.shape[3]
        # head_dim is zero-padded to the lane width inside the kernel, so the
        # flagship shape (head_dim=32, T=512) qualifies; only the sequence
        # tiling has to divide.
        if _on_tpu() and t >= 256 and flash_attention.supports(t, d, block_q, block_kv):
            impl = "flash"
        else:
            impl = "dense"
    if impl == "dense":
        return dense_causal_attention(q, k, v)
    if impl == "flash":
        from dtc_tpu.ops.flash_attention import flash_causal_attention

        return flash_causal_attention(
            q, k, v, block_q=block_q, block_kv=block_kv,
            block_q_bwd=block_q_bwd, block_kv_bwd=block_kv_bwd,
        )
    if impl == "ring":
        from dtc_tpu.ops.ring_attention import ring_causal_attention

        return ring_causal_attention(q, k, v)
    if impl == "ulysses":
        from dtc_tpu.ops.ulysses_attention import ulysses_causal_attention

        return ulysses_causal_attention(
            q, k, v, block_q=block_q, block_kv=block_kv,
            block_q_bwd=block_q_bwd, block_kv_bwd=block_kv_bwd,
        )
    raise ValueError(f"unknown attention impl {impl!r}")
