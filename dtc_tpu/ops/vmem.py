"""Static VMEM/SMEM planner — ONE byte accounting for every Pallas gate.

Before ISSUE 20 the repo carried two hand-rolled 14 MiB estimators
(``_VMEM_BUDGET_BYTES`` in decode_fused.py and overlap_collectives.py)
plus a third inline copy in ``reduce_scatter_matmul`` — three places for
the same arithmetic to drift. This module is the single implementation:

- **exact per-grid-step byte plans** derived from the kernels' own
  BlockSpecs + scratch_shapes (the megakernel's specs are literally BUILT
  from :func:`fused_layers_grid_plan`, so gate and kernel cannot
  disagree about a block shape);
- **the gates** every ``supports_*`` / ``_pallas_ok`` routing predicate
  consults (``dtc_tpu/analysis/kernels.py`` lints that they do);
- **the committed baselines' fingerprints** — ``analysis/kernels.py``
  emits these plans per (kernel, ladder rung) under
  ``analysis/baselines/`` with the report.py drift gate, including the
  static answer to PR 10's open question: does megakernel cross-layer
  weight double-buffering fit at each rung (``fits_double_buffered``).

Deliberately jax-free: pure integer arithmetic over config dims, cheap
enough for routing predicates on every trace and importable from
anywhere (ops/, analysis/, scripts/) without dependency cycles.

All plans are PIPELINE-RESIDENT accounting: what Mosaic must co-locate
in VMEM for one grid step (input blocks + output blocks + scratch),
with in-register transients (score tiles, softmax rows) reported as a
separate *modeled* term — the 14 MiB budget intentionally sits ~2 MiB
under the ~16 MB/core of a v5e so single-query transients live in the
headroom, exactly the convention the old estimators used. Gates price
only what the old gates priced (weights + cache row, plus the ISSUE-20
spec-window surcharge RELATIVE to the single-query baseline), so
routing decisions are unchanged for every previously-supported shape.
"""

from __future__ import annotations

from typing import Any

#: Per-grid-step VMEM working-set budget shared by every fused-kernel
#: gate (was duplicated as ``_VMEM_BUDGET_BYTES`` in decode_fused.py and
#: overlap_collectives.py). ~16 MB/core on v5e; 14 MiB leaves headroom
#: for in-register activations, Mosaic's own spill, and semaphores.
VMEM_BUDGET_BYTES = 14 * 1024 * 1024

#: Mosaic lane width: lane-dim dynamic slices on hardware must be
#: 128-aligned; interpret mode does not care (how the tiny CPU tests
#: drive the real kernels).
LANE = 128

#: Widest speculative verify window the megakernel serves in one launch
#: (re-exported as ``decode_fused._SPEC_MAX_K``). Tiny by design:
#: speculation past ~8 proposals is acceptance-rate-limited, and a small
#: static bound keeps the (t, S) score tiles inside the single-query
#: VMEM headroom (the gate prices the surcharge — see
#: :func:`fused_layers_plan`).
SPEC_MAX_K = 8

#: Longest cache the megakernel holds as one (S, H·D) tile per (layer,
#: row) grid step (re-exported as ``decode_fused._FUSED_LAYERS_MAX_S``).
FUSED_LAYERS_MAX_S = 4096

#: LoRA dense sites the megakernel threads factors for, with their
#: (in, out) dims as functions of (d_model, H·D, d_ff) — the same
#: canonical order as ``decode_fused._LORA_ATTN_SITES + _LORA_MLP_SITES``.
LORA_SITES = ("q_proj", "k_proj", "v_proj", "out_proj", "fc1", "fc2")

#: The megakernel's 16 per-layer weight blocks — the layer-streamed
#: class whose index maps MUST be b-invariant ("weights re-fetch per
#: layer, not per row"); shared by the byte plan and the kernel lint.
WEIGHT_BLOCK_NAMES = frozenset({
    "ln1_scale", "ln1_bias", "wq", "bq", "wk", "bk", "wv", "bv",
    "wo", "bo", "ln2_scale", "ln2_bias", "w1", "b1", "w2", "b2",
})


def _dtype_bytes(name: str) -> int:
    from dtc_tpu.config.schema import DTYPE_BYTES

    return DTYPE_BYTES.get(name, 4)


def _prod(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def packed_group(d: int, h: int) -> tuple[int, int]:
    """(heads per lane block, lane block width) — the packed-layout
    grouping rule of ``flash_attention._packed_group`` /
    ``decode_attention._group`` (mirrored here so the planner stays
    jax-free; tests/test_kernel_audit.py pins the two against each
    other). 128-lane groups when head_dim divides the lane width and the
    group divides the head count; otherwise ONE block of all H·D lanes
    (tiny-model shapes, Mosaic pads internally)."""
    if d <= LANE and LANE % d == 0 and h % (LANE // d) == 0:
        return LANE // d, LANE
    return h, h * d


def _lora_dims(cfg) -> dict[str, tuple[int, int]]:
    dm, ff = cfg.d_model, cfg.d_ff
    hd = cfg.n_heads * cfg.head_dim
    return {
        "q_proj": (dm, hd), "k_proj": (dm, hd), "v_proj": (dm, hd),
        "out_proj": (hd, dm), "fc1": (dm, ff), "fc2": (ff, dm),
    }


def lora_sites_for(cfg) -> tuple[str, ...]:
    """The megakernel LoRA sites a config's adapter targets (canonical
    order; empty when adapters are off or the model is MoE — expert MLPs
    carry no fc1/fc2 dense sites)."""
    ad = getattr(cfg, "adapter", None)
    if ad is None or ad.rank <= 0:
        return ()
    targets = set(ad.target_modules)
    sites = [s for s in LORA_SITES if s in targets]
    if cfg.moe_experts > 0:
        sites = [s for s in sites if s not in ("fc1", "fc2")]
    return tuple(sites)


# ---------------------------------------------------------------------------
# decode megakernel (ops/decode_fused.py)
# ---------------------------------------------------------------------------


def fused_layers_grid_plan(
    cfg, t: int = 1, b: int = 1,
    lora_sites: tuple[str, ...] = (), lora_per_row: bool = False,
) -> dict[str, Any]:
    """The megakernel's grid/BlockSpec layout, symbolically.

    This is the SOURCE of ``decode_fused._fused_layers_call``'s specs —
    the kernel wrapper converts these entries into ``pl.BlockSpec``s, so
    the byte plan below and the launched kernel share one definition of
    every block shape and index map. Returns::

        {"grid": (L, b),
         "in_specs":  [(name, block_shape|None, index_map|None,
                        space, dtype_bytes), ...],
         "out_specs": [...same...],
         "scratch":   [(shape, dtype_bytes), ...]}

    ``block_shape is None`` means whole-array (the SMEM frontier).
    Index maps are plain callables of the grid coords ``(l, bb)`` —
    pure, and b-invariant exactly for the weight blocks (the "weights
    re-fetch per layer, not per row" pipelining contract
    ``analysis/kernels.py`` lints)."""
    dm, ff, H = cfg.d_model, cfg.d_ff, cfg.n_heads
    hd = H * cfg.head_dim
    L, S = cfg.n_layers, cfg.max_seq_len
    pb = _dtype_bytes(cfg.param_dtype)
    cb = _dtype_bytes(cfg.compute_dtype)
    quant = cfg.kv_quantized
    kvb = 1 if quant else _dtype_bytes(cfg.kv_store_dtype)

    def wmap(rank):
        return lambda l, bb, _r=rank: (l,) + (0,) * (_r - 1)

    row4 = lambda l, bb: (l, bb, 0, 0)  # noqa: E731
    xmap = lambda l, bb: (bb, 0, 0)     # noqa: E731

    weight_feats = [
        ("ln1_scale", (dm,)), ("ln1_bias", (dm,)),
        ("wq", (dm, hd)), ("bq", (hd,)),
        ("wk", (dm, hd)), ("bk", (hd,)),
        ("wv", (dm, hd)), ("bv", (hd,)),
        ("wo", (hd, dm)), ("bo", (dm,)),
        ("ln2_scale", (dm,)), ("ln2_bias", (dm,)),
        ("w1", (dm, ff)), ("b1", (ff,)),
        ("w2", (ff, dm)), ("b2", (dm,)),
    ]
    in_specs: list[tuple] = [
        ("frontier", None, None, "smem", 4),
        ("x", (1, t, dm), xmap, "vmem", cb),
    ]
    for name, feat in weight_feats:
        shape = (1,) + feat
        in_specs.append((name, shape, wmap(len(shape)), "vmem", pb))
    in_specs += [
        ("k_row", (1, 1, S, hd), row4, "vmem", kvb),
        ("v_row", (1, 1, S, hd), row4, "vmem", kvb),
    ]
    if quant:
        in_specs += [
            ("k_scale_row", (1, 1, S, H), row4, "vmem", 4),
            ("v_scale_row", (1, 1, S, H), row4, "vmem", 4),
        ]
    rank = getattr(getattr(cfg, "adapter", None), "rank", 0)
    dims = _lora_dims(cfg)
    for site in lora_sites:
        din, dout = dims[site]
        for suffix, shp in (("a", (din, rank)), ("b", (rank, dout))):
            if lora_per_row:
                spec = (f"{site}_{suffix}", (1, 1) + shp, row4, "vmem", 4)
            else:
                full = (1,) + shp
                spec = (f"{site}_{suffix}", full, wmap(len(full)), "vmem", 4)
            in_specs.append(spec)

    out_specs = [
        ("x_out", (1, t, dm), xmap, "vmem", cb),
        ("k_new", (1, 1, t, hd), row4, "vmem", kvb),
        ("v_new", (1, 1, t, hd), row4, "vmem", kvb),
    ]
    if quant:
        out_specs += [
            ("k_scale_new", (1, 1, t, H), row4, "vmem", 4),
            ("v_scale_new", (1, 1, t, H), row4, "vmem", 4),
        ]
    return {
        "grid": (L, b),
        "in_specs": in_specs,
        "out_specs": out_specs,
        "scratch": [((max(b, 8), t, dm), cb)],
    }


def fused_layers_plan(cfg, t: int = 1, b: int = 1) -> dict[str, Any]:
    """Exact per-grid-step VMEM/SMEM byte plan for the decode megakernel
    at verify-window width ``t`` (1 = plain decode) and batch ``b``.

    Components (bytes, all per (layer, row) grid step):

    - ``weights`` — one layer's 16 stacked blocks, param dtype. Exact
      per-tensor shapes (the old estimator's ``4·(d² + d)`` assumed
      ``H·D == d_model``; q/k/v/out are really ``(d, H·D)``/``(H·D, d)``).
    - ``cache_row`` — one row's K/V tiles (+ fp32 scales when int8).
    - ``lora`` — the targeted sites' factor blocks (per-row and shared
      layouts stream identical bytes per step: one layer's (in, r) pair
      either way).
    - ``io`` — the x/x_out blocks and the t frontier cache-write blocks
      (+ scale writes) — the per-step t-proportional traffic PR 19 added.
    - ``scratch`` — the residual-carry VMEM scratch, ``(max(b,8), t, dm)``.
    - ``smem`` — the frontier scalars.
    - ``modeled_transients`` — in-register score/softmax tiles
      (``2·t·S·4 + 2·t²·4`` fp32 per head iteration), NOT BlockSpec
      bytes: reported for honesty, lives in the budget's headroom.

    Gate semantics (``gate_bytes``): the historical rule priced
    ``weights + cache_row`` against the budget with single-query io/
    transients absorbed by the 2 MiB headroom. The ISSUE-20 fix keeps
    that calibration and adds the SPEC-WINDOW SURCHARGE — the t-driven
    growth of io + scratch + transients RELATIVE to t=1 (k query/score
    rows, k cache writes per layer) — so a verify window cannot ride a
    gate that only priced one query row. ``fits`` folds in the MoE and
    single-tile-cache structural bounds: it IS ``supports_fused_layers``.

    ``fits_double_buffered`` answers PR 10's open question statically:
    2× every streamed block (weights, cache row, LoRA, io — Mosaic
    prefetches grid step n+1 while n computes) + scratch + smem under
    the budget."""
    S = cfg.max_seq_len

    def _transients(tt: int) -> int:
        return 2 * tt * S * 4 + 2 * tt * tt * 4

    def _groups(tt: int) -> dict[str, int]:
        plan = fused_layers_grid_plan(
            cfg, t=tt, b=b, lora_sites=lora_sites_for(cfg),
            lora_per_row=False,
        )
        groups: dict[str, int] = {
            "weights": 0, "cache_row": 0, "lora": 0, "io": 0,
            "scratch": 0, "smem": 0,
        }
        weight_names = {
            "ln1_scale", "ln1_bias", "wq", "bq", "wk", "bk", "wv", "bv",
            "wo", "bo", "ln2_scale", "ln2_bias", "w1", "b1", "w2", "b2",
        }
        for name, shape, _imap, space, nbytes in plan["in_specs"]:
            if space == "smem":
                groups["smem"] += nbytes * max(b, 1)
            elif name in weight_names:
                groups["weights"] += _prod(shape) * nbytes
            elif name.endswith(("_a", "_b")):
                groups["lora"] += _prod(shape) * nbytes
            elif name in ("k_row", "v_row", "k_scale_row", "v_scale_row"):
                groups["cache_row"] += _prod(shape) * nbytes
            else:
                groups["io"] += _prod(shape) * nbytes
        for name, shape, _imap, _space, nbytes in plan["out_specs"]:
            groups["io"] += _prod(shape) * nbytes
        for shape, nbytes in plan["scratch"]:
            groups["scratch"] += _prod(shape) * nbytes
        return groups

    groups = _groups(t)
    transients = _transients(t)
    base = _groups(1)
    # The t-driven growth of io + scratch + in-register transients over
    # the single-query baseline — derived from the SAME grid plan the
    # kernel launches with, not a parallel formula.
    surcharge = (
        (groups["io"] - base["io"])
        + (groups["scratch"] - base["scratch"])
        + (transients - _transients(1))
    )
    gate_bytes = groups["weights"] + groups["cache_row"] + surcharge
    per_step = sum(groups.values())
    streamed = (
        groups["weights"] + groups["cache_row"] + groups["lora"]
        + groups["io"]
    )
    db_bytes = 2 * streamed + groups["scratch"] + groups["smem"]
    structural = cfg.moe_experts == 0 and S <= FUSED_LAYERS_MAX_S
    return {
        "kernel": "fused_layers",
        "grid": [cfg.n_layers, b],
        "t": t,
        "bytes": dict(groups),
        "per_step_bytes": per_step,
        "modeled_transient_bytes": transients,
        "spec_surcharge_bytes": surcharge,
        "gate_bytes": gate_bytes,
        "budget_bytes": VMEM_BUDGET_BYTES,
        "fits": structural and gate_bytes <= VMEM_BUDGET_BYTES,
        "double_buffered_bytes": db_bytes,
        "fits_double_buffered": structural and db_bytes <= VMEM_BUDGET_BYTES,
    }


# ---------------------------------------------------------------------------
# per-layer decode kernels (ops/decode_attention.py)
# ---------------------------------------------------------------------------


def decode_single_plan(cfg, s: int | None = None) -> dict[str, Any]:
    """Per-grid-step bytes of the single-tile decode kernel: grid
    ``(B, H·D/lane_block)``, one program holds q (1,1,lb), the full
    (1,s,lb) K and V tiles (+ (1,s,g) fp32 scale columns when int8) and
    the (1,1,lb) output. No scratch."""
    if s is None:
        s = cfg.max_seq_len
    g, lb = packed_group(cfg.head_dim, cfg.n_heads)
    cb = _dtype_bytes(cfg.compute_dtype)
    quant = cfg.kv_quantized
    kvb = 1 if quant else _dtype_bytes(cfg.kv_store_dtype)
    kv = 2 * s * lb * kvb
    scales = 2 * s * g * 4 if quant else 0
    io = 2 * lb * cb  # q block + output block
    total = kv + scales + io
    return {
        "kernel": "decode_single", "s": s, "lane_block": lb, "group": g,
        "bytes": {"kv_tiles": kv, "scales": scales, "io": io, "scratch": 0},
        "per_step_bytes": total,
        "budget_bytes": VMEM_BUDGET_BYTES,
        "fits": total <= VMEM_BUDGET_BYTES,
    }


def decode_blocked_plan(
    cfg, s: int | None = None, block_s: int = 512,
) -> dict[str, Any]:
    """Per-grid-step bytes of the blocked (online-softmax) decode
    kernel: KV walks in ``block_s`` chunks; scratch carries the running
    max/sum (two (8, 128) fp32 rows) and the (8, lane_block) fp32 output
    accumulator."""
    if s is None:
        s = cfg.max_seq_len
    g, lb = packed_group(cfg.head_dim, cfg.n_heads)
    cb = _dtype_bytes(cfg.compute_dtype)
    quant = cfg.kv_quantized
    kvb = 1 if quant else _dtype_bytes(cfg.kv_store_dtype)
    kv = 2 * block_s * lb * kvb
    scales = 2 * block_s * g * 4 if quant else 0
    io = 2 * lb * cb
    scratch = 2 * 8 * LANE * 4 + 8 * lb * 4
    total = kv + scales + io + scratch
    return {
        "kernel": "decode_blocked", "s": s, "block_s": block_s,
        "lane_block": lb, "group": g,
        "bytes": {"kv_tiles": kv, "scales": scales, "io": io,
                  "scratch": scratch},
        "per_step_bytes": total,
        "budget_bytes": VMEM_BUDGET_BYTES,
        "fits": total <= VMEM_BUDGET_BYTES,
    }


def decode_single_tile_fits(s: int, lanes: int = LANE) -> bool:
    """Worst-case (fp32 payload, 128-lane block) single-tile fit for a
    cache of length ``s`` — the VMEM leg of ``decode_attention.supports``
    (the structural ``s <= _DECODE_MAX_SINGLE_S`` bound remains the
    caller's; at the 14 MiB budget every single-tile-bounded cache fits,
    pinned in tests so the gate refactor cannot change routing)."""
    return 2 * s * lanes * 4 + 2 * s * 4 <= VMEM_BUDGET_BYTES


# ---------------------------------------------------------------------------
# ring collective kernels (ops/overlap_collectives.py)
# ---------------------------------------------------------------------------


def overlap_plan(
    m: int, k_loc: int, n_loc: int, ring: int, shard_axis: int,
    itemsize: int,
) -> dict[str, Any]:
    """Per-launch VMEM byte plan for the fused ring kernels, all three
    launches one backend decision covers (the PR 11 worst-of-three rule:
    fwd all-gather-matmul, bwd dx re-gather, bwd dw matmul+reduce-
    scatter). Shapes are the LOCAL shard_map-region shapes; ``m`` =
    flattened token rows per device.

    - fwd ag: x (m, k_loc) + fp32 out (m, n_loc) + the (ring receive
      slots + own shard) weight scratch.
    - bwd dx ag: dy (m, n_loc) + fp32 dx (m, k_loc) + the same slot set.
    - bwd dw rs: both operands + fp32 (recv slots + stage + out) of dw
      (:func:`rs_standalone_bytes` — also ``reduce_scatter_matmul``'s
      own gate)."""
    blk = (k_loc if shard_axis == 0 else n_loc) // ring
    wshard = (
        (k_loc // ring) * n_loc if shard_axis == 0
        else k_loc * (n_loc // ring)
    )
    slots = (ring + 1) * wshard
    legs = {
        "fwd_ag": m * k_loc * itemsize + m * n_loc * 4 + slots * itemsize,
        "bwd_dx_ag": m * n_loc * itemsize + m * k_loc * 4 + slots * itemsize,
        "bwd_dw_rs": rs_standalone_bytes(
            m, k_loc, n_loc, ring, shard_axis, itemsize
        ),
    }
    worst = max(legs.values())
    return {
        "kernel": "overlap_ring",
        "m": m, "k_loc": k_loc, "n_loc": n_loc, "ring": ring,
        "shard_axis": shard_axis, "itemsize": itemsize,
        "block": blk,
        "lane_aligned": blk % LANE == 0,
        "wshard_bytes": wshard * itemsize,
        "legs": legs,
        "worst_bytes": worst,
        "budget_bytes": VMEM_BUDGET_BYTES,
        "fits": worst <= VMEM_BUDGET_BYTES,
    }


def rs_standalone_bytes(
    m: int, k_cols: int, n_cols: int, ring: int, shard_axis: int,
    itemsize: int,
) -> int:
    """The streamed matmul+reduce-scatter launch working set: both
    operands + fp32 (ring-1 recv slots + stage + out) ≈ (ring+1) blocks
    of the scattered product."""
    blk = (k_cols if shard_axis == 0 else n_cols) // ring
    wshard = blk * (n_cols if shard_axis == 0 else k_cols)
    return m * (k_cols + n_cols) * itemsize + (ring + 1) * wshard * 4
