"""Held-out eval split for streaming datasets.

FineWeb streaming has no validation split; round-3 VERDICT weak #6: the
"eval set" was literally the first ``eval_batches`` training batches, so
eval_log.csv measured memorization. Here every ``every``-th packed batch
from the head of the training stream is DIVERTED into the eval set (spread
over the first ``(count-1)*every + 1`` batches, not one contiguous head
block) and training never sees it — disjoint by construction, asserted in
tests/test_data.py.
"""

from __future__ import annotations

import itertools
from typing import Iterator, Tuple

import numpy as np


def diverted_indices(every: int, count: int) -> set[int]:
    """0-based stream indices routed to the eval set."""
    return {k * every for k in range(count)}


def divert_holdout(
    it: Iterator[np.ndarray], every: int, count: int
) -> Tuple[Iterator[np.ndarray], list[np.ndarray]]:
    """Split ``it`` into (training iterator, eval set).

    Eagerly consumes the first ``(count-1)*every + 1`` batches: stream
    indices {0, every, 2*every, ...} become the eval set, the rest are
    buffered and replayed to training before the live stream continues.
    """
    if count <= 0:
        return it, []
    div = diverted_indices(every, count)
    span = (count - 1) * every + 1
    eval_set: list[np.ndarray] = []
    buffered: list[np.ndarray] = []
    for i in range(span):
        batch = next(it)
        (eval_set if i in div else buffered).append(batch)
    return itertools.chain(buffered, it), eval_set


def stream_index_for(train_index: int, withheld: set[int]) -> int:
    """1-based SOURCE-stream yield index of the ``train_index``-th (1-based)
    batch delivered to training when the 0-based source indices in
    ``withheld`` are diverted/dropped. The trainer uses this to checkpoint
    the stream position corresponding to what training actually consumed."""
    if not withheld:
        return train_index
    seen = 0
    for s in itertools.count():
        if s in withheld:
            continue
        seen += 1
        if seen == train_index:
            return s + 1
    raise AssertionError("unreachable")


def holdout_stream_index(train_index: int, every: int, count: int) -> int:
    """:func:`stream_index_for` under a :func:`divert_holdout` split."""
    return stream_index_for(train_index, diverted_indices(every, count))
