"""Streamed FineWeb-Edu batches (reference data path).

Same source, split, and packing semantics as
`/root/reference/data/fineweb_edu.py:15-39` — HuggingFace streaming of
``HuggingFaceFW/fineweb-edu`` train split, per-document tokenization,
boundary-free concatenation — but the packing is delegated to
:func:`dtc_tpu.data.packing.pack_token_stream` and tokenization can run in a
background thread so the (network + CPU)-bound work overlaps device compute
instead of sitting on the training critical path (the reference tokenizes
synchronously inside the step loop, SURVEY.md §3.4).

Multi-host: documents are striped round-robin by ``process_index`` /
``process_count`` so every pod host tokenizes a DISJOINT slice of the
stream (the reference is single-process and has no notion of this).
"""

from __future__ import annotations

from typing import Iterable, Iterator

import numpy as np

from dtc_tpu.data.packing import pack_token_stream
from dtc_tpu.data.tokenizer import get_tokenizer


def stride_documents(
    documents: Iterable, process_index: int, process_count: int
) -> Iterator:
    """Round-robin stripe of a document stream: process p sees items
    p, p+N, p+2N, … — disjoint across processes, union = full stream."""
    for i, item in enumerate(documents):
        if i % process_count == process_index:
            yield item


def _document_tokens(
    tokenizer, process_index: int, process_count: int
) -> Iterator[list[int]]:
    from datasets import load_dataset  # network-bound import kept local

    ds = load_dataset("HuggingFaceFW/fineweb-edu", split="train", streaming=True)
    for item in stride_documents(ds, process_index, process_count):
        yield tokenizer.encode(item["text"])


def fineweb_batch_iterator(
    batch_size: int,
    seq_len: int,
    tokenizer=None,
    *,
    process_index: int = 0,
    process_count: int = 1,
    documents: Iterator[list[int]] | None = None,
) -> Iterator[np.ndarray]:
    """Yield (batch_size, seq_len) int32 batches from streamed FineWeb-Edu.

    ``documents`` injects a pre-tokenized document stream (tests / offline);
    when given it is ALSO striped by process, so the multi-host contract is
    testable without the network.
    """
    if documents is not None:
        docs = stride_documents(documents, process_index, process_count)
    else:
        tokenizer = tokenizer or get_tokenizer()
        docs = _document_tokens(tokenizer, process_index, process_count)
    yield from pack_token_stream(docs, batch_size, seq_len)
