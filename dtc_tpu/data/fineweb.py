"""Streamed FineWeb-Edu batches (reference data path).

Same source, split, and packing semantics as
`/root/reference/data/fineweb_edu.py:15-39` — HuggingFace streaming of
``HuggingFaceFW/fineweb-edu`` train split, per-document tokenization,
boundary-free concatenation — but the packing is delegated to
:func:`dtc_tpu.data.packing.pack_token_stream` and tokenization can run in a
background thread so the (network + CPU)-bound work overlaps device compute
instead of sitting on the training critical path (the reference tokenizes
synchronously inside the step loop, SURVEY.md §3.4).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from dtc_tpu.data.packing import pack_token_stream
from dtc_tpu.data.tokenizer import get_tokenizer


def _document_tokens(tokenizer) -> Iterator[list[int]]:
    from datasets import load_dataset  # network-bound import kept local

    ds = load_dataset("HuggingFaceFW/fineweb-edu", split="train", streaming=True)
    for item in ds:
        yield tokenizer.encode(item["text"])


def fineweb_batch_iterator(
    batch_size: int,
    seq_len: int,
    tokenizer=None,
) -> Iterator[np.ndarray]:
    """Yield (batch_size, seq_len) int32 batches from streamed FineWeb-Edu."""
    tokenizer = tokenizer or get_tokenizer()
    yield from pack_token_stream(_document_tokens(tokenizer), batch_size, seq_len)
