"""Streamed FineWeb-Edu batches (reference data path).

Same source, split, and packing semantics as
`/root/reference/data/fineweb_edu.py:15-39` — HuggingFace streaming of
``HuggingFaceFW/fineweb-edu`` train split, per-document tokenization,
boundary-free concatenation — but the packing is delegated to
:class:`dtc_tpu.data.packing.TokenPacker` and tokenization can run in a
background thread so the (network + CPU)-bound work overlaps device compute
instead of sitting on the training critical path (the reference tokenizes
synchronously inside the step loop, SURVEY.md §3.4).

Multi-host: documents are striped round-robin by ``process_index`` /
``process_count`` so every pod host tokenizes a DISJOINT slice of the
stream (the reference is single-process and has no notion of this).

Resume: :class:`FinewebStream` tracks a per-batch position (documents
consumed + leftover buffer tokens) that the trainer checkpoints alongside
the Orbax state; a resumed run seeks — ``dataset.skip`` over already-read
raw documents, buffer restored — instead of re-downloading and
re-tokenizing everything consumed so far.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Iterable, Iterator

import numpy as np

from dtc_tpu.data.packing import TokenPacker
from dtc_tpu.data.tokenizer import get_tokenizer


def stride_documents(
    documents: Iterable, process_index: int, process_count: int,
    start_index: int = 0,
) -> Iterator:
    """Round-robin stripe of a document stream: process p sees items with
    ABSOLUTE index ≡ p (mod N) — disjoint across processes, union = full
    stream. ``start_index`` is the absolute index of the first item of
    ``documents`` (nonzero when the underlying stream was ``.skip()``-ed),
    so striping stays aligned across resumes."""
    for i, item in enumerate(documents, start=start_index):
        if i % process_count == process_index:
            yield item


def _wrap_resilient(
    open_at, raw_skip: int, retry=None, chaos=None, on_recovery=None,
    cancel=None,
) -> Iterator:
    """Compose the raw-document source with the chaos hook (inside, so the
    injected fault exercises the real healing path) and the retry wrapper
    (outside): one uninterrupted, exactly-once document sequence across any
    number of re-opens. ``open_at(index)`` returns a fresh raw iterator
    whose first item has absolute index ``index``."""
    def factory(index: int) -> Iterator:
        it = open_at(index)
        if chaos is not None:
            it = chaos.wrap_raw_documents(it, index)
        return it

    if retry is None or not getattr(retry, "enabled", True):
        return factory(raw_skip)
    from dtc_tpu.resilience.retry import resilient_iterator

    return resilient_iterator(
        factory,
        start_index=raw_skip,
        max_attempts=retry.max_attempts,
        backoff_s=retry.backoff_s,
        backoff_max_s=retry.backoff_max_s,
        jitter=retry.jitter,
        max_elapsed_s=getattr(retry, "max_elapsed_s", 0.0),
        on_event=on_recovery,
        cancel=cancel,
    )


def _document_tokens(
    tokenizer, process_index: int, process_count: int, raw_skip: int = 0,
    retry=None, chaos=None, on_recovery=None, cancel=None,
) -> Iterator[list[int]]:
    def open_at(index: int) -> Iterator:
        from datasets import load_dataset  # network-bound import kept local

        ds = load_dataset(
            "HuggingFaceFW/fineweb-edu", split="train", streaming=True
        )
        if index:
            # Server/shard-aware skip: neither a resumed run nor a
            # mid-stream retry re-downloads or re-tokenizes consumed docs.
            ds = ds.skip(index)
        return iter(ds)

    raw = _wrap_resilient(open_at, raw_skip, retry, chaos, on_recovery, cancel)
    for item in stride_documents(raw, process_index, process_count, raw_skip):
        yield tokenizer.encode(item["text"])


class FinewebStream:
    """Resumable FineWeb batch iterator.

    Yields (batch_size, seq_len) int32 batches. ``position`` (from a prior
    stream's :meth:`position_after`) seeks the document source and restores
    the packer buffer, so the resumed stream continues batch-exactly where
    the checkpointed one stopped. A bounded history of per-yield positions
    lets the trainer ask for the position as of the batch TRAINING consumed
    even while the prefetch pipeline has pulled a few batches ahead.

    ``documents`` injects a pre-tokenized RAW document stream (tests /
    offline); it is striped and skipped exactly like the network path — and
    when given as a SEQUENCE it is also re-openable, so the self-healing
    retry path (``retry``/``chaos``) runs end-to-end offline in tier-1
    tests exactly as it would against HuggingFace streaming.
    """

    def __init__(
        self,
        batch_size: int,
        seq_len: int,
        tokenizer=None,
        *,
        process_index: int = 0,
        process_count: int = 1,
        documents: Iterator[list[int]] | None = None,
        position: dict | None = None,
        history: int = 64,
        retry=None,
        chaos=None,
        on_recovery=None,
        cancel=None,
    ):
        pos = position or {"docs_consumed": 0, "buffer": []}
        skip = int(pos["docs_consumed"])  # STRIPED documents already consumed
        # The k-th striped document for process p is raw index p + k*N: after
        # `skip` striped docs the next raw index to read is p + skip*N, so
        # skipping skip*N raw documents keeps every process phase-aligned.
        raw_skip = skip * process_count
        if documents is not None:
            if hasattr(documents, "__getitem__"):
                # Sequence: true seek (mirrors the network path's ds.skip) —
                # already-consumed documents are never touched again, which
                # the resume tests assert. Re-openable, so retry/chaos
                # compose exactly like the network path.
                raw = _wrap_resilient(
                    lambda index: iter(documents[index:]),
                    raw_skip, retry, chaos, on_recovery, cancel,
                )
            else:
                # A plain iterator cannot be re-opened: no healing possible.
                raw = itertools.islice(documents, raw_skip, None)
                if chaos is not None:
                    raw = chaos.wrap_raw_documents(raw, raw_skip)
            docs = stride_documents(raw, process_index, process_count, raw_skip)
        else:
            docs = _document_tokens(
                tokenizer or get_tokenizer(), process_index, process_count,
                raw_skip, retry=retry, chaos=chaos, on_recovery=on_recovery,
                cancel=cancel,
            )
        self._packer = TokenPacker(
            docs, batch_size, seq_len, docs_consumed=skip, buffer=pos["buffer"]
        )
        #: stream index of the most recently yielded batch (1-based count).
        self.yielded = 0
        self._history: deque[tuple[int, dict]] = deque(maxlen=history)
        # __next__ runs on the prefetch worker thread while position_after
        # runs on the main thread at checkpoint time — guard the deque.
        self._lock = threading.Lock()

    def __iter__(self) -> "FinewebStream":
        return self

    def __next__(self) -> np.ndarray:
        batch = next(self._packer)
        with self._lock:
            self.yielded += 1
            self._history.append((self.yielded, self._packer.position()))
        return batch

    def position_after(self, stream_index: int) -> dict:
        """The resume position as of the ``stream_index``-th yielded batch
        (1-based). Only a bounded window of recent yields is retained —
        enough to cover prefetch look-ahead and eval-holdout gaps (the
        trainer sizes ``history`` past the holdout span)."""
        with self._lock:
            entries = list(self._history)
        for n, p in entries:
            if n == stream_index:
                return p
        raise KeyError(
            f"position for stream index {stream_index} not in history "
            f"(have {[n for n, _ in entries]}); increase history="
        )


def fineweb_batch_iterator(
    batch_size: int,
    seq_len: int,
    tokenizer=None,
    *,
    process_index: int = 0,
    process_count: int = 1,
    documents: Iterator[list[int]] | None = None,
) -> Iterator[np.ndarray]:
    """Yield (batch_size, seq_len) int32 batches from streamed FineWeb-Edu.
    Thin wrapper over :class:`FinewebStream` (kept for call-site compat)."""
    return FinewebStream(
        batch_size, seq_len, tokenizer,
        process_index=process_index, process_count=process_count,
        documents=documents,
    )
