"""Token-stream packing, factored out as a pure generator.

Keeps the reference's exact packing semantics for loss-curve parity
(`/root/reference/data/fineweb_edu.py:25-39`): documents are tokenized,
concatenated into one flat buffer with NO separator tokens or boundary
masking, and cut into dense ``(batch, seq_len)`` int32 arrays in stream
order. Unlike the reference, the packer is independent of the data source
and tokenizer, so it is unit-testable without network access.
"""

from __future__ import annotations

from typing import Iterable, Iterator

import numpy as np


class TokenPacker:
    """Stateful packer with a RESUMABLE position.

    Same packing semantics as :func:`pack_token_stream`, exposed as an
    iterator whose :meth:`position` — (documents consumed, leftover buffer
    tokens) — fully determines the remaining stream. A checkpointed
    position lets a resumed run seek (skip documents at the source, restore
    the partial buffer) instead of re-tokenizing everything consumed so far
    (round-3 VERDICT weak #5: resume was an O(steps) drain loop).
    """

    def __init__(
        self,
        token_chunks: Iterable[list[int] | np.ndarray],
        batch_size: int,
        seq_len: int,
        *,
        docs_consumed: int = 0,
        buffer: list[int] | np.ndarray | None = None,
    ):
        self._chunks = iter(token_chunks)
        self._need = batch_size * seq_len
        self._shape = (batch_size, seq_len)
        self.docs_consumed = docs_consumed
        self._buffer = np.asarray(
            buffer if buffer is not None else [], dtype=np.int32
        )

    def __iter__(self) -> "TokenPacker":
        return self

    def __next__(self) -> np.ndarray:
        while self._buffer.size < self._need:
            chunk = np.asarray(next(self._chunks), dtype=np.int32)
            self.docs_consumed += 1
            self._buffer = (
                np.concatenate([self._buffer, chunk]) if self._buffer.size else chunk
            )
        batch = self._buffer[: self._need].reshape(self._shape)
        self._buffer = self._buffer[self._need :]
        return batch

    def position(self) -> dict:
        """JSON-serializable resume point: reconstructing a packer over the
        same document stream with ``docs_consumed`` documents skipped and
        this buffer yields the identical remaining batch stream."""
        return {
            "docs_consumed": int(self.docs_consumed),
            "buffer": self._buffer.tolist(),
        }


def pack_token_stream(
    token_chunks: Iterable[list[int] | np.ndarray],
    batch_size: int,
    seq_len: int,
) -> Iterator[np.ndarray]:
    """Pack an iterable of token chunks into dense (batch_size, seq_len) batches."""
    return TokenPacker(token_chunks, batch_size, seq_len)
