"""Token-stream packing, factored out as a pure generator.

Keeps the reference's exact packing semantics for loss-curve parity
(`/root/reference/data/fineweb_edu.py:25-39`): documents are tokenized,
concatenated into one flat buffer with NO separator tokens or boundary
masking, and cut into dense ``(batch, seq_len)`` int32 arrays in stream
order. Unlike the reference, the packer is independent of the data source
and tokenizer, so it is unit-testable without network access.
"""

from __future__ import annotations

from typing import Iterable, Iterator

import numpy as np


def pack_token_stream(
    token_chunks: Iterable[list[int] | np.ndarray],
    batch_size: int,
    seq_len: int,
) -> Iterator[np.ndarray]:
    """Pack an iterable of token chunks into dense (batch_size, seq_len) batches."""
    need = batch_size * seq_len
    buffer = np.empty(0, dtype=np.int32)
    for chunk in token_chunks:
        chunk = np.asarray(chunk, dtype=np.int32)
        buffer = np.concatenate([buffer, chunk]) if buffer.size else chunk
        while buffer.size >= need:
            batch = buffer[:need].reshape(batch_size, seq_len)
            buffer = buffer[need:]
            yield batch
