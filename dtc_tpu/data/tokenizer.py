"""Tokenizer with an offline fallback.

The reference unconditionally downloads the GPT-2 tokenizer
(`/root/reference/data/fineweb_edu.py:8-12`), which hangs in a zero-egress
environment. Here the HF load is attempted local-files-first, then online
only if the environment allows; otherwise a deterministic byte-level
fallback with the same padded vocab size (50258) keeps every model shape
identical to the reference workload.
"""

from __future__ import annotations

import os

#: GPT-2 vocab (50257) + the reference's added <pad> token
#: (/root/reference/data/fineweb_edu.py:10-11) => 50258.
GPT2_PADDED_VOCAB = 50258


class ByteTokenizer:
    """UTF-8 byte fallback tokenizer, vocab padded to match GPT-2+<pad>."""

    def __init__(self, vocab_size: int = GPT2_PADDED_VOCAB):
        self._vocab_size = vocab_size

    def __len__(self) -> int:
        return self._vocab_size

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        return bytes(int(i) % 256 for i in ids).decode("utf-8", errors="replace")


def get_tokenizer(
    allow_download: bool | None = None, allow_byte_fallback: bool | None = None
):
    """GPT-2 tokenizer with a <pad> token added (vocab 50258), reference
    parity with `/root/reference/data/fineweb_edu.py:8-12`.

    If the real tokenizer cannot be loaded this RAISES by default: a byte-level
    substitute has the right vocab size but entirely different token semantics,
    so a `dataset: fineweb` run would silently train a different language model.
    The :class:`ByteTokenizer` fallback is opt-in via ``allow_byte_fallback=True``
    or ``DTC_ALLOW_BYTE_FALLBACK=1``, and prints a WARNING when taken.
    """
    if allow_download is None:
        allow_download = os.environ.get("DTC_ALLOW_DOWNLOAD", "0") == "1"
    if allow_byte_fallback is None:
        allow_byte_fallback = os.environ.get("DTC_ALLOW_BYTE_FALLBACK", "0") == "1"
    try:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained("gpt2", local_files_only=not allow_download)
        tok.add_special_tokens({"pad_token": "<pad>"})
        return tok
    except Exception as e:
        if not allow_byte_fallback:
            raise RuntimeError(
                "Could not load the GPT-2 tokenizer (offline cache miss or "
                f"download failure: {e!r}). Refusing to silently substitute a "
                "byte-level tokenizer — it changes training semantics. Set "
                "DTC_ALLOW_BYTE_FALLBACK=1 (or allow_byte_fallback=True) to "
                "opt into the ByteTokenizer fallback."
            ) from e
        print(
            "WARNING: GPT-2 tokenizer unavailable; using byte-level fallback "
            "tokenizer (same vocab size, DIFFERENT token semantics)."
        )
        return ByteTokenizer()
