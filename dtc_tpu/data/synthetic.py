"""Deterministic synthetic token data.

The reference has no offline data path at all — every run (and any test)
needs live HuggingFace streaming (`/root/reference/data/fineweb_edu.py:21`).
This iterator produces a reproducible, learnable token stream for tests and
benchmarks: a Zipf-ish unigram distribution with short-range repetition
structure so the loss actually decreases (pure uniform noise would pin the
loss at log(vocab)).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def synthetic_batch_iterator(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    start: int = 0,
) -> Iterator[np.ndarray]:
    """Yield deterministic (batch_size, seq_len) int32 batches.

    Batch ``i`` for a given (seed, shape, vocab) is identical across runs,
    processes, and mesh shapes — the property the cross-strategy parity
    tests rely on. ``start`` begins the stream at batch index ``start``
    in O(1) (used by checkpoint resume to skip consumed batches).
    """
    i = start
    while True:
        rng = np.random.default_rng(np.random.SeedSequence([seed, i]))
        # Zipf-distributed unigrams, clipped into vocab.
        base = rng.zipf(1.3, size=(batch_size, seq_len)).astype(np.int64)
        tokens = (base - 1) % vocab_size
        # Inject copy structure: each position repeats the token 8 back with p=0.5.
        copy_mask = rng.random((batch_size, seq_len)) < 0.5
        shifted = np.roll(tokens, 8, axis=1)
        tokens = np.where(copy_mask, shifted, tokens)
        yield tokens.astype(np.int32)
        i += 1


def synthetic_row(seq_len: int, vocab_size: int, seed: int, row: int) -> np.ndarray:
    """One deterministic ``(seq_len,)`` row, independently seeded by its
    ROW index — the primitive of the batch-shape-independent stream below
    (row ``r`` is identical whatever batch groups it)."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, 1, row]))
    base = rng.zipf(1.3, size=(seq_len,)).astype(np.int64)
    tokens = (base - 1) % vocab_size
    copy_mask = rng.random((seq_len,)) < 0.5
    shifted = np.roll(tokens, 8)
    tokens = np.where(copy_mask, shifted, tokens)
    return tokens.astype(np.int32)


def synthetic_row_batches(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    start_row: int = 0,
) -> Iterator[np.ndarray]:
    """Row-stream batching: the elastic-shrink data contract (ISSUE 15).

    Unlike :func:`synthetic_batch_iterator` — whose batch ``i`` content
    depends on the BATCH SHAPE (the whole batch is one RNG draw) — this
    stream is a flat sequence of independently-seeded rows; a batch of
    size ``B`` starting at row ``r`` consumes rows ``[r, r + B)``. Token
    accounting is therefore batch-shape-independent: after consuming
    ``T`` tokens at any batch size, ``start_row = T // (seq_len)`` resumes
    the SAME flat row sequence at any other batch size — the property an
    elastic resize that changes the global batch relies on to re-seek the
    stream by global tokens-consumed (pinned in tests/test_data.py).
    Elastic trainer runs (``resilience.elastic.enabled`` with
    ``dataset: synthetic``) use this stream.
    """
    r = start_row
    while True:
        yield np.stack(
            [synthetic_row(seq_len, vocab_size, seed, r + b)
             for b in range(batch_size)]
        )
        r += batch_size
