"""Deterministic synthetic token data.

The reference has no offline data path at all — every run (and any test)
needs live HuggingFace streaming (`/root/reference/data/fineweb_edu.py:21`).
This iterator produces a reproducible, learnable token stream for tests and
benchmarks: a Zipf-ish unigram distribution with short-range repetition
structure so the loss actually decreases (pure uniform noise would pin the
loss at log(vocab)).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def synthetic_batch_iterator(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    start: int = 0,
) -> Iterator[np.ndarray]:
    """Yield deterministic (batch_size, seq_len) int32 batches.

    Batch ``i`` for a given (seed, shape, vocab) is identical across runs,
    processes, and mesh shapes — the property the cross-strategy parity
    tests rely on. ``start`` begins the stream at batch index ``start``
    in O(1) (used by checkpoint resume to skip consumed batches).
    """
    i = start
    while True:
        rng = np.random.default_rng(np.random.SeedSequence([seed, i]))
        # Zipf-distributed unigrams, clipped into vocab.
        base = rng.zipf(1.3, size=(batch_size, seq_len)).astype(np.int64)
        tokens = (base - 1) % vocab_size
        # Inject copy structure: each position repeats the token 8 back with p=0.5.
        copy_mask = rng.random((batch_size, seq_len)) < 0.5
        shifted = np.roll(tokens, 8, axis=1)
        tokens = np.where(copy_mask, shifted, tokens)
        yield tokens.astype(np.int32)
        i += 1
