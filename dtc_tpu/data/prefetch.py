"""Host->device batch feeding with background prefetch.

The reference feeds the device synchronously: `next(iterator)` tokenizes on
the host, then `jnp.array(...)` transfers, all inside the timed step loop
(`/root/reference/train/train.py:74-78`). On a pod that starves the chips.

Here a background thread runs the host-side iterator and eagerly places
batches on the mesh with their NamedSharding, keeping `queue_size` batches
in flight. Multi-host runs go through
`jax.make_array_from_process_local_data`, so each process feeds only its
shard of the global batch.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


def put_batch(x: np.ndarray, mesh: Mesh, spec: P) -> jax.Array:
    """Place a host batch on the mesh (multi-host aware)."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sharding, x)
    return jax.device_put(x, sharding)


def split_put(batch: np.ndarray, mesh: Mesh, spec: P) -> tuple[jax.Array, jax.Array]:
    """Split a (B, T+1) token batch into next-token (x, y) device arrays
    (x = [:, :-1], y = [:, 1:], as the reference does at
    /root/reference/train/train.py:76-77) placed with ``spec``."""
    x = put_batch(np.ascontiguousarray(batch[:, :-1]), mesh, spec)
    y = put_batch(np.ascontiguousarray(batch[:, 1:]), mesh, spec)
    return x, y


class ShardedPrefetchIterator:
    """Wrap a host batch iterator; yield (x, y) device arrays.

    Splits each (batch, seq_len+1) token array into next-token-prediction
    inputs/targets (x = [:, :-1], y = [:, 1:], as the reference does at
    /root/reference/train/train.py:76-77) and device_puts with the batch
    PartitionSpec. ``queue_size=0`` degrades to fully synchronous feeding.

    Failure contract (SURVEY §5 "a data-stream error kills the run" — as a
    hang, the worst way): an exception inside the worker thread reaches the
    consumer as the ORIGINAL exception (error + sentinel through the queue);
    a worker that dies without even delivering its sentinel — interpreter
    teardown, a C-level crash in the tokenizer — surfaces as a typed
    :class:`~dtc_tpu.resilience.errors.DataStreamError` via a bounded-wait
    liveness check instead of blocking ``get()`` forever. ``close()`` shuts
    the worker down so a trainer rollback can rebuild the pipeline without
    leaking threads.
    """

    _POLL_S = 1.0  # consumer liveness-check cadence; never limits throughput

    def __init__(
        self,
        host_iterator: Iterator[np.ndarray],
        mesh: Mesh,
        spec: P,
        queue_size: int = 2,
    ):
        self._it = host_iterator
        self._mesh = mesh
        self._spec = spec
        self._queue_size = queue_size
        self._queue: queue.Queue | None = None
        self._err: BaseException | None = None
        self._done = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if queue_size > 0:
            self._queue = queue.Queue(maxsize=queue_size)
            self._thread = threading.Thread(target=self._worker, daemon=True)
            self._thread.start()

    def _split_put(self, batch: np.ndarray):
        return split_put(batch, self._mesh, self._spec)

    def _put(self, item) -> bool:
        """Bounded put that aborts when the consumer called close() — a
        full queue with a departed consumer must not pin the thread."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            for batch in self._it:
                if not self._put(self._split_put(batch)):
                    return  # closed: skip the sentinel, nobody is reading
        except BaseException as e:  # surfaced on the consumer side
            self._err = e
        finally:
            if not self._stop.is_set():
                self._put(None)

    def __iter__(self):
        return self

    def __next__(self):
        if self._queue is None:
            return self._split_put(next(self._it))
        if self._done:
            raise StopIteration  # sentinel already consumed; stay iterable
        while True:
            try:
                item = self._queue.get(timeout=self._POLL_S)
                break
            except queue.Empty:
                if self._thread is not None and not self._thread.is_alive():
                    # The worker may have put its final sentinel and exited
                    # in the instant our timeout expired — drain once more
                    # before declaring it dead, or a clean end-of-stream
                    # becomes a spurious crash.
                    try:
                        item = self._queue.get_nowait()
                        break
                    except queue.Empty:
                        pass
                    from dtc_tpu.resilience.errors import DataStreamError

                    raise DataStreamError(
                        "prefetch worker thread died without delivering a "
                        "batch or an error sentinel"
                    ) from self._err
        if item is None:
            self._done = True
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the worker and release the queue. Idempotent; safe to call
        from the consumer at any point (e.g. trainer rollback)."""
        self._stop.set()
        if self._queue is not None:
            # Unblock a worker stuck in put() by draining.
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
