from dtc_tpu.data.packing import pack_token_stream
from dtc_tpu.data.synthetic import synthetic_batch_iterator
from dtc_tpu.data.prefetch import ShardedPrefetchIterator

__all__ = ["pack_token_stream", "synthetic_batch_iterator", "ShardedPrefetchIterator"]
