"""Compiled train steps.

One ``jax.jit`` step covers single-device, DP, TP, and DP×TP: the reference's
per-strategy input-constraint branch (`/root/reference/train/create_train_step.py:37-44`)
collapses into the logical batch spec, and XLA's SPMD partitioner derives
every collective (DP gradient all-reduce, TP all-gather / all-reduce) from
the sharding annotations — no hand-written communication.

Pipeline (and 3D) steps live in ``dtc_tpu.parallel.pipeline`` and are
selected by :func:`create_train_step` when the mesh's ``pipe`` axis is > 1.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from flax.training.train_state import TrainState
from jax.sharding import Mesh

from dtc_tpu.parallel.sharding import DEFAULT_RULES

PyTree = Any


@struct.dataclass
class Batch:
    """Input/target token batch (same shape contract as the reference's
    Batch pytree, /root/reference/train/create_train_step.py:15-21)."""

    x: jax.Array
    y: jax.Array


def sum_aux_loss(mutated: dict) -> jax.Array:
    """Total of the sowed "aux_loss" collection (MoE load-balance terms,
    coefficient pre-applied; zero for dense models). One definition shared
    by the GSPMD step and both pipeline schedules."""
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree.leaves(mutated.get("aux_loss", {})):
        total = total + jnp.sum(leaf)
    return total


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross entropy, float32, gather-free.

    Numerically identical to
    ``optax.softmax_cross_entropy_with_integer_labels`` but selects the gold
    logit with an iota-match + reduction instead of ``take_along_axis``:
    a vocab-*sharded* gather cannot be partitioned by XLA SPMD inside a
    partially-manual (shard_map) region — and the masked reduction shards
    cleanly over a vocab-parallel (TP) logits axis anyway.

    Delegates to the single CE implementation in ``ops/fused_ce.py`` so the
    eval path and the fused train path cannot drift apart.
    """
    from dtc_tpu.ops.fused_ce import _stats_loss

    return _stats_loss(logits, targets)[0]


def create_gspmd_train_step(
    mesh: Mesh,
    rules: Sequence[tuple[str, str | None]] = DEFAULT_RULES,
) -> Callable[[TrainState, Batch, jax.Array], tuple[TrainState, jax.Array]]:
    """Build the jitted DP/TP/DP×TP train step.

    The returned function must be called with ``mesh`` / ``rules`` contexts
    active (the trainer owns those); params/opt-state sharding flows in from
    the arguments, batch sharding from the logical ("batch","seq") constraint.
    """

    # Donating the state lets XLA update params/opt-state in place instead of
    # allocating a second ~1.1 GB copy (fp32 master params + two AdamW moments)
    # and copying every step.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, batch: Batch, rng: jax.Array):
        x = nn.with_logical_constraint(batch.x, ("batch", "seq"))
        y = nn.with_logical_constraint(batch.y, ("batch", "seq"))

        def loss_fn(params: PyTree) -> jax.Array:
            # targets route the head through the fused head+CE op: same loss
            # value bitwise, one logits pass fewer in backward (fused_ce.py).
            # "aux_loss" carries MoE load-balance terms (coefficient already
            # applied at sow time); empty for dense models.
            loss, mut = state.apply_fn(
                {"params": params}, x, train=True, rngs={"dropout": rng},
                targets=y, mutable=["aux_loss"],
            )
            return loss + sum_aux_loss(mut)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        state = state.apply_gradients(grads=grads)
        return state, loss

    return train_step


def create_eval_step(
    mesh: Mesh,
    model,
    rules: Sequence[tuple[str, str | None]] = DEFAULT_RULES,
) -> Callable[[PyTree, Batch], jax.Array]:
    """Jitted loss-only evaluation step (no dropout, no update).

    Takes bare params (not a TrainState) so the trainer can feed it
    unstacked pipeline params: eval always runs the plain GSPMD forward,
    whatever strategy training uses.
    """

    @jax.jit
    def eval_step(params: PyTree, batch: Batch) -> jax.Array:
        x = nn.with_logical_constraint(batch.x, ("batch", "seq"))
        y = nn.with_logical_constraint(batch.y, ("batch", "seq"))
        logits = model.apply({"params": params}, x, train=False)
        return cross_entropy_loss(logits, y)

    return eval_step


def create_train_step(
    mesh: Mesh,
    *,
    model=None,
    num_microbatches: int = 1,
    rules: Sequence[tuple[str, str | None]] = DEFAULT_RULES,
    pp_schedule: str = "gpipe",
    pp_virtual: int = 1,
):
    """Strategy-dispatching factory: GSPMD step, or pipeline step when the
    mesh has a non-trivial ``pipe`` axis (GPipe, or plain/interleaved 1F1B
    per ``pp_schedule`` / ``pp_virtual``)."""
    if mesh.shape.get("pipe", 1) > 1:
        assert model is not None, "pipeline step needs the model for staged apply"
        if pp_schedule == "1f1b":
            from dtc_tpu.parallel.pipeline import create_1f1b_train_step

            return create_1f1b_train_step(
                model, mesh, num_microbatches=num_microbatches, rules=rules,
                virtual=pp_virtual,
            )
        from dtc_tpu.parallel.pipeline import create_pp_train_step

        return create_pp_train_step(
            model, mesh, num_microbatches=num_microbatches, rules=rules
        )
    return create_gspmd_train_step(mesh, rules)
