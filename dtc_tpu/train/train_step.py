"""Compiled train steps.

One ``jax.jit`` step covers single-device, DP, TP, and DP×TP: the reference's
per-strategy input-constraint branch (`/root/reference/train/create_train_step.py:37-44`)
collapses into the logical batch spec, and XLA's SPMD partitioner derives
every collective (DP gradient all-reduce, TP all-gather / all-reduce) from
the sharding annotations — no hand-written communication.

Pipeline (and 3D) steps live in ``dtc_tpu.parallel.pipeline`` and are
selected by :func:`create_train_step` when the mesh's ``pipe`` axis is > 1.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from flax.training.train_state import TrainState
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from dtc_tpu.parallel.sharding import DEFAULT_RULES

PyTree = Any


def normalize_spec(spec: P, mesh: Mesh) -> P:
    """Canonicalize a PartitionSpec the way GSPMD does: drop mesh axes of
    size 1 (sharding over them is a no-op) and strip trailing ``None``
    entries, so ``P(None, 'data', 'model')`` on a model=1 mesh becomes
    ``P(None, 'data')`` and ``P(None, None)`` becomes ``P()``.

    Initial placement and the step's out_shardings both use this form;
    without it they disagree with the compiler's normalized outputs and
    every run pays a second identical-program compile (see
    :func:`state_shardings`).
    """
    def keep(part):
        if part is None:
            return None
        if isinstance(part, str):
            return part if mesh.shape.get(part, 1) > 1 else None
        live = tuple(a for a in part if mesh.shape.get(a, 1) > 1)
        return live if live else None

    parts = [keep(p) for p in spec]
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def state_shardings(state: TrainState, mesh: Mesh) -> PyTree:
    """Per-leaf NamedShardings of a placed TrainState (replicated P() for
    any leaf not already carrying a mesh sharding — optax counts, step).

    Used as the step's ``out_shardings`` so the updated state leaves the
    executable with EXACTLY its input shardings. Without this, GSPMD
    normalizes degenerate specs (e.g. ``P(None, 'model')`` on a mesh where
    model=1 collapses to ``P()``), so the first step's donated output no
    longer matches the second step's input signature and XLA silently
    compiles a SECOND executable for the same step — a cold-start cost the
    obs subsystem's compile watcher surfaced (README "Observability").
    """
    def leaf(a: Any) -> NamedSharding:
        if isinstance(a, jax.Array) and isinstance(a.sharding, NamedSharding):
            return a.sharding
        return NamedSharding(mesh, P())

    return jax.tree.map(leaf, state)


def canonicalize_state_placement(state: TrainState, mesh: Mesh) -> TrainState:
    """Commit every non-mesh leaf (optax counts on the default device,
    the Python-int ``step``) to a replicated NamedSharding with a strong
    dtype, so step N's input signature equals step 1's."""
    def leaf(a: Any) -> Any:
        if isinstance(a, jax.Array) and isinstance(a.sharding, NamedSharding):
            return a
        arr = jnp.asarray(a)
        if arr.weak_type:
            arr = jax.lax.convert_element_type(arr, arr.dtype)
        return jax.device_put(arr, NamedSharding(mesh, P()))

    return jax.tree.map(leaf, state)


def resolve_precision(opt_cfg, model_cfg):
    """Route ``OptimConfig.precision`` onto the model config — the exact
    pattern of :func:`resolve_collectives`, so every train-step consumer
    (trainer, bench, audit lowering) resolves the policy through ONE
    definition and the lowered-and-audited program cannot diverge from the
    trained one.

    - ``fp32`` (default): the model config passes through untouched —
      every existing program is byte-identical.
    - ``bf16_mixed``: the model stores bf16 params and runs bf16 matmuls
      (``param_dtype``/``compute_dtype`` both lifted to ``bfloat16``);
      the fp32 master weights + fp32 AdamW moments live in the optimizer
      (``train/optimizer.with_master_weights`` — create_optimizer reads
      the same knob). The model's fp32-mandatory islands (softmax, LN
      variance, CE loss) are fp32 by construction in models/gpt.py and
      certified by the graph auditor's numerics pass. float16 configs are
      rejected: fp16 needs loss scaling this repo does not implement, and
      silently training fp16 under a knob named bf16_mixed would be worse
      than an error.
    """
    import dataclasses

    if getattr(opt_cfg, "precision", "fp32") != "bf16_mixed":
        return model_cfg
    if "float16" in (model_cfg.param_dtype, model_cfg.compute_dtype):
        raise ValueError(
            "precision: bf16_mixed cannot combine with a float16 model "
            "config (fp16 would need loss scaling); use bfloat16/float32 "
            "model dtypes and let the policy lift them"
        )
    if (
        model_cfg.param_dtype == "bfloat16"
        and model_cfg.compute_dtype == "bfloat16"
    ):
        return model_cfg
    return dataclasses.replace(
        model_cfg, param_dtype="bfloat16", compute_dtype="bfloat16"
    )


def resolve_collectives(train_cfg, model_cfg, mesh: Mesh | None = None):
    """Route ``TrainConfig.collectives`` onto the model config (the dense
    layers are where the ring schedules live — ops/overlap_collectives.py,
    ISSUE 12), with the mode's validity checked HERE so every train-step
    consumer (trainer, bench, audit lowering) applies one rule:

    - ``overlapped`` + pipeline parallelism is rejected: the ring's
      shard_map over the FSDP axis cannot nest under the pipeline's
      manual region the way its collectives would need (same restriction
      as ring attention), and FSDP rules never combine with pipe > 1 in
      this repo anyway.
    - otherwise the model config comes back with ``collectives`` set; for
      rules that do not shard "embed_p" the mode is inert by design
      (OverlapDense falls back to the serialized dot per call).

    Either config may request the mode: the effective value is
    "overlapped" when EITHER TrainConfig or ModelConfig says so —
    ModelConfig.collectives is a public validated knob, and a train-level
    default of "xla" must not silently revert it.
    """
    import dataclasses

    train_mode = getattr(train_cfg, "collectives", "xla")
    mode = (
        "overlapped"
        if "overlapped" in (train_mode, model_cfg.collectives)
        else "xla"
    )
    pipe = (
        mesh.shape.get("pipe", 1) if mesh is not None
        else max(train_cfg.mesh.pipe, 1) * train_cfg.mesh.dcn_pipe
    )
    # The pipeline rejection must fire for EVERY route into the mode —
    # including a model-config-only request that needs no replace below.
    if mode == "overlapped" and (train_cfg.parallel == "pp" or pipe > 1):
        raise ValueError(
            "collectives: overlapped is not supported under pipeline "
            "parallelism (the FSDP ring's shard_map cannot nest inside "
            "the pipeline's manual region); use a mesh with pipe == 1 — "
            "overlapped composes with DP/FSDP/TP"
        )
    if mode == model_cfg.collectives:
        return model_cfg
    return dataclasses.replace(model_cfg, collectives=mode)


@struct.dataclass
class Batch:
    """Input/target token batch (same shape contract as the reference's
    Batch pytree, /root/reference/train/create_train_step.py:15-21)."""

    x: jax.Array
    y: jax.Array


def sum_aux_loss(mutated: dict) -> jax.Array:
    """Total of the sowed "aux_loss" collection (MoE load-balance terms,
    coefficient pre-applied; zero for dense models). One definition shared
    by the GSPMD step and both pipeline schedules."""
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree.leaves(mutated.get("aux_loss", {})):
        total = total + jnp.sum(leaf)
    return total


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross entropy, float32, gather-free.

    Numerically identical to
    ``optax.softmax_cross_entropy_with_integer_labels`` but selects the gold
    logit with an iota-match + reduction instead of ``take_along_axis``:
    a vocab-*sharded* gather cannot be partitioned by XLA SPMD inside a
    partially-manual (shard_map) region — and the masked reduction shards
    cleanly over a vocab-parallel (TP) logits axis anyway.

    Delegates to the single CE implementation in ``ops/fused_ce.py`` so the
    eval path and the fused train path cannot drift apart.
    """
    from dtc_tpu.ops.fused_ce import _stats_loss

    return _stats_loss(logits, targets)[0]


def create_gspmd_train_step(
    mesh: Mesh,
    rules: Sequence[tuple[str, str | None]] = DEFAULT_RULES,
    state: TrainState | None = None,
    base_params: PyTree | None = None,
) -> Callable[[TrainState, Batch, jax.Array], tuple[TrainState, jax.Array]]:
    """Build the jitted DP/TP/DP×TP train step.

    The returned function must be called with ``mesh`` / ``rules`` contexts
    active (the trainer owns those); params/opt-state sharding flows in from
    the arguments, batch sharding from the logical ("batch","seq") constraint.

    Passing the (placed) initial ``state`` pins the step's out_shardings to
    the state's shardings, so every call hits ONE executable — see
    :func:`state_shardings` for the double-compile this avoids.

    With ``base_params`` (the LoRA finetune path, dtc_tpu/adapters/) the
    state holds ONLY the adapter ("lora") subtree: the frozen base rides
    in as a non-donated, non-differentiated argument, gradients and the
    optimizer update touch the adapter alone — which is exactly what makes
    adapter checkpoints/rollback operate on the tiny subtree for free.
    """
    jit_kwargs: dict[str, Any] = {"donate_argnums": (0,)}
    if state is not None:
        jit_kwargs["out_shardings"] = (
            state_shardings(state, mesh), NamedSharding(mesh, P())
        )

    # Donating the state lets XLA update params/opt-state in place instead of
    # allocating a second ~1.1 GB copy (fp32 master params + two AdamW moments)
    # and copying every step.
    @functools.partial(jax.jit, **jit_kwargs)
    def train_step(state: TrainState, batch: Batch, rng: jax.Array):
        x = nn.with_logical_constraint(batch.x, ("batch", "seq"))
        y = nn.with_logical_constraint(batch.y, ("batch", "seq"))

        def loss_fn(params: PyTree) -> jax.Array:
            # targets route the head through the fused head+CE op: same loss
            # value bitwise, one logits pass fewer in backward (fused_ce.py).
            # "aux_loss" carries MoE load-balance terms (coefficient already
            # applied at sow time); empty for dense models.
            # named_scope "fwd" (ISSUE 8): every primal op's HLO op_name
            # metadata carries .../fwd/..., the backward pass carries the
            # autodiff transpose(jvp(fwd)) wrapper — the devprof
            # attribution derives the fwd/bwd phase split from exactly
            # this (obs/devprof.classify_scope). Trace-time only; the
            # compiled program is unchanged.
            with jax.named_scope("fwd"):
                loss, mut = state.apply_fn(
                    {"params": params}, x, train=True, rngs={"dropout": rng},
                    targets=y, mutable=["aux_loss"],
                )
                return loss + sum_aux_loss(mut)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        with jax.named_scope("optimizer"):
            state = state.apply_gradients(grads=grads)
        return state, loss

    if base_params is None:
        return train_step

    @functools.partial(jax.jit, **jit_kwargs)
    def lora_step(
        state: TrainState, base: PyTree, batch: Batch, rng: jax.Array
    ):
        x = nn.with_logical_constraint(batch.x, ("batch", "seq"))
        y = nn.with_logical_constraint(batch.y, ("batch", "seq"))

        def loss_fn(lora: PyTree) -> jax.Array:
            with jax.named_scope("fwd"):
                loss, mut = state.apply_fn(
                    {"params": base, "lora": lora}, x, train=True,
                    rngs={"dropout": rng}, targets=y, mutable=["aux_loss"],
                )
                return loss + sum_aux_loss(mut)

        # Differentiate ONLY the adapter subtree; base param gradients are
        # never formed (frozen base — not stop_gradient'd post hoc).
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        with jax.named_scope("optimizer"):
            state = state.apply_gradients(grads=grads)
        return state, loss

    # Bind the frozen base as an EXPLICIT (traced, undonated) argument —
    # not a closure constant, which would bake the full base weights into
    # the jaxpr — while keeping the trainer-facing (state, batch, rng)
    # signature every call site already uses.
    def step(state: TrainState, batch: Batch, rng: jax.Array):
        return lora_step(state, base_params, batch, rng)

    return step


def create_eval_step(
    mesh: Mesh,
    model,
    rules: Sequence[tuple[str, str | None]] = DEFAULT_RULES,
    base_params: PyTree | None = None,
) -> Callable[[PyTree, Batch], jax.Array]:
    """Jitted loss-only evaluation step (no dropout, no update).

    Takes bare params (not a TrainState) so the trainer can feed it
    unstacked pipeline params: eval always runs the plain GSPMD forward,
    whatever strategy training uses. With ``base_params`` (adapter runs)
    the first argument is the LoRA subtree instead — the same thing the
    trainer's ``state.params`` holds in that mode — and the frozen base
    rides in as a bound argument.
    """

    @jax.jit
    def eval_step(params: PyTree, base: PyTree | None, batch: Batch) -> jax.Array:
        x = nn.with_logical_constraint(batch.x, ("batch", "seq"))
        y = nn.with_logical_constraint(batch.y, ("batch", "seq"))
        variables = (
            {"params": params} if base is None
            else {"params": base, "lora": params}
        )
        logits = model.apply(variables, x, train=False)
        return cross_entropy_loss(logits, y)

    return lambda params, batch: eval_step(params, base_params, batch)


def create_train_step(
    mesh: Mesh,
    *,
    model=None,
    num_microbatches: int = 1,
    rules: Sequence[tuple[str, str | None]] = DEFAULT_RULES,
    pp_schedule: str = "gpipe",
    pp_virtual: int = 1,
    state: TrainState | None = None,
    base_params: PyTree | None = None,
):
    """Strategy-dispatching factory: GSPMD step, or pipeline step when the
    mesh has a non-trivial ``pipe`` axis (GPipe, or plain/interleaved 1F1B
    per ``pp_schedule`` / ``pp_virtual``). ``state`` (optional, GSPMD path)
    pins out_shardings to avoid the layout-churn double compile.
    ``base_params`` selects the LoRA-adapter step (state = adapter subtree,
    base frozen) — GSPMD modes only."""
    if mesh.shape.get("pipe", 1) > 1:
        if base_params is not None:
            raise ValueError(
                "LoRA adapter training (base_params) is not supported under "
                "pipeline parallelism; use a mesh with pipe == 1 (adapters "
                "compose with DP/TP/FSDP)"
            )
        assert model is not None, "pipeline step needs the model for staged apply"
        if pp_schedule == "1f1b":
            from dtc_tpu.parallel.pipeline import create_1f1b_train_step

            return create_1f1b_train_step(
                model, mesh, num_microbatches=num_microbatches, rules=rules,
                virtual=pp_virtual,
            )
        from dtc_tpu.parallel.pipeline import create_pp_train_step

        return create_pp_train_step(
            model, mesh, num_microbatches=num_microbatches, rules=rules
        )
    return create_gspmd_train_step(
        mesh, rules, state=state, base_params=base_params
    )
